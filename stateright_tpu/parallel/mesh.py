"""Sharded batched BFS: the multi-chip engine.

Design (SURVEY.md §7 step 4, §5 "distributed communication backend"):

  - mesh axis "shards" over N devices,
  - visited table: fingerprint-ownership sharding — shard `h1 % N` owns a
    fingerprint; four [N, cap] uint32 lanes (structure-of-arrays, see
    ops/visited_set.py), sharded on dim 0,
  - frontier: per-shard ring lanes [N, qcap], holding only owned states,
  - per era (ONE shard_map'ed jitted program, a device-resident while
    loop whose predicate is a GLOBALLY UNIFORM gate — one stacked psum
    per step yields work-left / congestion / probe-error / finish-policy
    discovery bits, identical on every shard; same design as
    engines/tpu_bfs.py's era loop):
      each shard pops a chunk, evaluates properties, expands successors,
      buckets the candidates BY OWNER into fixed per-destination quotas,
      and exchanges them with `lax.all_to_all` — each candidate crosses
      the ICI exactly once, to its owner, instead of the naive
      all_gather's N-fold broadcast. The owner runs the claim-arbitrated
      insert (cross-shard duplicates resolve exactly like in-batch ones)
      and appends fresh states to its ring.
  - bucket overflow (more candidates for one destination than the quota)
    uses the same partial-commit protocol as the single-device engine:
    delivered candidates are inserted+enqueued (idempotent), the pops are
    NOT consumed, and a per-shard take_cap halves until everything fits.

The host syncs once per era: one [N, P_LEN] stats download, then spill /
growth / finish-policy decisions (discovery-finish already exits the era
on device). Cross-shard discovery paths reconstruct
on the host by walking parent pointers across the downloaded table shards
(owner = h1 % N per hop).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..checker import Checker, CheckerBuilder
from ..core import Expectation
from ..engines.common import HostEngineBase
from ..fingerprint import combine64, hash_words_np, split64
from ..path import Path
from ..tensor import TensorModel, TensorModelAdapter

# Packed per-shard scalar params (one uint32 row per shard). Mirrors the
# single-device layout (engines/tpu_bfs.py) plus an overflow counter.
P_HEAD = 0
P_COUNT = 1
P_UNIQUE = 2
P_REC = 3
P_DEPTH_LIMIT = 4
P_GROW_LIMIT = 5
P_HIGH_WATER = 6
P_MAX_STEPS = 7
P_GEN = 8
P_MAXD = 9
P_STEPS = 10
P_ERR = 11
P_TAKE_CAP = 12  # persisted across eras (self-tuned on bucket overflow)
P_FIN_ANY = 13  # era exits when (global rec & fin_any) != 0
P_FIN_ALL = 14  # era exits when fin_all_en and (rec & fin_all) == fin_all
P_FIN_ALL_EN = 15
P_BUDGET_CAP = 16  # upper clamp for the device-adaptive step budget;
# 0 = adaptivity OFF (P_MAX_STEPS passes through unchanged)
P_LEN = 17

#: Cross-shard frontier imbalance (max/mean occupancy) above which the
#: engine logs a skew warning once per run. Hash-based ownership keeps
#: real models near 1.0; several-fold skew means one device does most of
#: the work while the rest idle in the lockstep collective.
SHARD_IMBALANCE_WARN = 4.0

_LOOP_CACHE: Dict[Tuple, Tuple[TensorModel, Any]] = {}


class BlockProgram(NamedTuple):
    """The compiled sharded era block under its two donation policies.

    ``serial``: the host consumed every readback before re-dispatching,
    so the table/queue lanes AND the freshly-uploaded params rows are
    donatable. ``chain``: a speculative chained dispatch feeds the
    previous block's params/rec_fp OUTPUTS straight back in while the
    host still needs to read them (the readback and the discovery
    fp/depth arrays), so only the table/queue lanes — which the host
    never touches mid-chain — are donated. Same traced function, so one
    lowering serves both (and on CPU, where donation is a no-op, they
    are literally the same executable)."""

    serial: Any
    chain: Any


def shard_fuse_tail_len(fuse: int, n_props: int) -> int:
    """Extra packed-params words per shard when multi-era fusion is on
    (``fuse > 1``): ``[fuse_lim, n_inner]`` + per-inner-era
    steps/generated/unique/frontier lanes (``4 * fuse``) + the per-shard
    inner-era index of each property's best discovery (``n_props`` —
    the host needs it to reproduce the serial driver's
    (depth, era, shard) discovery tie-break exactly)."""
    return (2 + 4 * fuse + n_props) if fuse > 1 else 0


def shard_params_len(A: int, P: int, cov: bool, sample_k: int,
                     fuse: int = 1) -> int:
    """Length of one shard's packed uint32 params row: scalars +
    optional coverage tail + optional sampling tail ([T1,T2,occ,0] and
    four drained lanes) + optional multi-era fusion tail. Mirrors
    `engines.tpu_bfs.params_len` minus the rec_fp tail (the sharded
    block passes rec_fp as separate args)."""
    from ..obs.coverage import DEPTH_CAP

    n = P_LEN + ((A + P + 1 + DEPTH_CAP) if cov else 0)
    if sample_k:
        from ..obs.sample import slab_entries

        n += 4 + 4 * slab_entries(sample_k)
    return n + shard_fuse_tail_len(fuse, P)


def block_abstract_args(tm: TensorModel, props, qcap: int, tcap: int,
                        n_shards: int, cov: bool, sample_k: int,
                        fuse: int = 1):
    """`jax.ShapeDtypeStruct` pytree matching `_build_block`'s jitted
    signature `(table, queue, rec_fp1, rec_fp2, params)` — global shapes
    with the leading shard axis. Used by the STR6xx program lint to
    lower the sharded era block without touching device memory."""
    import jax
    import jax.numpy as jnp

    S, A, P = tm.state_width, tm.max_actions, len(props)
    u32 = jnp.uint32
    sds = jax.ShapeDtypeStruct
    N = n_shards
    table = (
        sds((N, 2 * tcap), u32),
        sds((N, tcap), u32),
        sds((N, tcap), u32),
    )
    queue = tuple(sds((N, qcap), u32) for _ in range(S + 2))
    plen = shard_params_len(A, P, cov, sample_k, fuse)
    return (
        table,
        queue,
        sds((N, P), u32),
        sds((N, P), u32),
        sds((N, plen), u32),
    )


def _build_block(tm: TensorModel, props, chunk: int, qcap: int, n_shards: int,
                 quota: int, mesh, axis: str, cov: bool = True,
                 sample_k: int = 0, fuse: int = 1):
    key = (
        id(tm), chunk, qcap, n_shards, quota, len(props), cov, sample_k,
        fuse, tuple(id(d) for d in mesh.devices.flat),
    )
    cached = _LOOP_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_LOOP_CACHE) >= 16:  # bound executable/model pinning
        _LOOP_CACHE.pop(next(iter(_LOOP_CACHE)))

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec

    from ..compat import donate_argnums_pinned, get_shard_map
    from ..engines.tpu_bfs import _vcap
    from ..fingerprint import hash_lanes_jnp
    from ..obs.coverage import DEPTH_CAP
    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_expand_lean

    S = tm.state_width
    A = tm.max_actions
    NP_ = len(props)
    expand_lean = build_expand_lean(tm, props, chunk)
    qmask = qcap - 1
    X = S + 4  # exchanged lanes: state | p1 | p2 | ebits | depth — the
    # candidate's own fingerprint is NOT exchanged; the owner recomputes it
    # elementwise from the state lanes (elementwise work is free here,
    # ICI lanes are not: this cuts exchange traffic by 2 lanes)
    vcap = _vcap(A, chunk)
    # Pre-exchange dedup scratch, at the COMPACTED width (round 5): in the
    # sharded engine the dedup pass still earns its cost — every retained
    # duplicate would cross the ICI to its owner before losing the claim
    # there. Approximate as ever; the owner's insert arbitrates exactly.
    dedup_cap = 1 << max(1, (2 * vcap - 1).bit_length())
    # Space-sampling slab (obs/sample.py): each SHARD keeps its own
    # fixed slab of candidate fingerprints below the host's bottom-k
    # threshold, captured at the owner-side insert (is_new is exactly-once
    # globally, so slab entries are distinct fps and the host's exact h1
    # tie cut applies). Captures happen at the exchange receive width R,
    # never truncated — slab capacity s_high + R plus the psum'd
    # occupancy gate guarantee every below-threshold insert is captured,
    # so per-(shard, era) drains of the sk2 smallest merge into the exact
    # global bottom-k by trivial union (PSUM-FREE: the tails ride the
    # per-shard params rows un-reduced).
    R = n_shards * quota
    if sample_k:
        from ..obs.sample import slab_entries, slab_high_water

        sk2 = slab_entries(sample_k)
        s_high = slab_high_water(sample_k)
        scap = s_high + R  # next step's captures (<= R) always fit
    s_base = P_LEN + ((A + NP_ + 1 + DEPTH_CAP) if cov else 0)
    f_base = shard_params_len(A, NP_, cov, sample_k)  # fusion tail start

    def per_device(table, queue, rec_fp1, rec_fp2, params):
        u = jnp.uint32
        table = tuple(t[0] for t in table)
        queue = tuple(q[0] for q in queue)
        rec_fp1 = rec_fp1[0]
        rec_fp2 = rec_fp2[0]
        params = params[0]

        me = lax.axis_index(axis).astype(jnp.uint32)
        high_water = params[P_HIGH_WATER]
        grow_limit = params[P_GROW_LIMIT]
        depth_limit = params[P_DEPTH_LIMIT]
        max_steps0 = params[P_MAX_STEPS]
        rec_bits0 = params[P_REC]
        fin_any = params[P_FIN_ANY]
        fin_all = params[P_FIN_ALL]
        fin_all_en = params[P_FIN_ALL_EN]
        budget_cap = params[P_BUDGET_CAP]
        if sample_k:
            # Host bottom-k threshold (exclusive; uint32 halves). Stale
            # (looser) thresholds only over-capture — always sound.
            st1 = params[s_base]
            st2 = params[s_base + 1]

        zero_lane = jnp.zeros(chunk, dtype=u) + (params[0] & u(0))
        false_lane = zero_lane != 0
        # Scalars seeded from varying data so carry types stay consistent
        # under shard_map (constants would be unvarying on the mesh axis).
        vzero = params[0] & u(0)

        def run_era(table, queue, head0, count0, unique0, rec_bits,
                    max_steps, err0, take_cap0, covc0, sampc0):
            """ONE complete era — the lockstep step loop plus its
            once-per-era epilogue — threaded so up to ``fuse`` of them
            chain inside a single dispatch (multi-era fusion). Per-era
            accumulators (property first-hit lanes, the iteration
            counter) reset here; cross-era state (table/queue, counters,
            coverage, the sampling slab) threads through the arguments.
            Every value the outer fusion gate needs — ``budget_only``
            (the era's ONLY exit reason was budget exhaustion) and the
            global slab-occupancy bit — comes out of the one epilogue
            psum, so the gate is uniform across shards and the outer
            loop stays lockstep."""

            def global_gates(count, unique, err_cnt, hseen, rec_acc0, its,
                             socc):
                """One stacked psum produces every exit condition,
                IDENTICAL on all shards (the while predicate must be
                uniform): work left, congestion (a shard cannot refuse
                all_to_all deliveries, so no shard may pop while ANY
                shard's ring or table is within one step's receive of its
                limit), probe errors, and the finish policy's GLOBAL
                discovery bits."""
                local = [
                    (count > u(0)).astype(u),
                    ((count > high_water) | (unique > grow_limit)).astype(u),
                    (err_cnt > u(0)).astype(u),
                ] + [
                    jnp.minimum(hseen[pi].sum(dtype=u), u(1))
                    for pi in range(NP_)
                ]
                if sample_k:
                    # Sampling-slab occupancy: when ANY shard's slab passes
                    # its high-water mark the era ends so the host can
                    # drain it (appended LAST so the established g[]
                    # indices hold).
                    local.append((socc > u(s_high)).astype(u))
                g = lax.psum(jnp.stack(local), axis)
                rec_acc = rec_acc0
                for pi in range(NP_):
                    rec_acc = rec_acc | (
                        jnp.minimum(g[3 + pi], u(1)) << u(pi)
                    )
                fin_hit = ((rec_acc & fin_any) != u(0)) | (
                    (fin_all_en != u(0)) & ((rec_acc & fin_all) == fin_all)
                )
                g_cont = (
                    (g[0] > u(0))
                    & (g[1] == u(0))
                    & (g[2] == u(0))
                    & ~fin_hit
                    & (its < max_steps)
                )
                if sample_k:
                    g_cont = g_cont & (g[3 + NP_] == u(0))
                return g_cont.astype(u)

            def cond(carry):
                return carry[-1] != u(0)  # carried uniform gate

            def body(carry):
                (
                    table,
                    queue,
                    head,
                    count,
                    unique,
                    gen,
                    steps,
                    err_cnt,
                    take_cap,
                    hseen,
                    facc1,
                    facc2,
                    faccd,
                    covc,
                    sampc,
                    its,
                    _g_cont,
                ) = carry
                pred = count > 0
                take = jnp.where(
                    pred, jnp.minimum(jnp.minimum(count, u(chunk)), take_cap), u(0)
                )
                active = jnp.arange(chunk, dtype=u) < take
                popped, _ = fr.ring_gather(queue, head, chunk)
                rows = popped[:S]
                ebits = popped[S]
                depth = popped[S + 1]
                # Recomputed on pop, elementwise (the ring no longer carries
                # fingerprints — same round-5 redesign as engines/tpu_bfs.py).
                row_h1, row_h2 = hash_lanes_jnp(rows)

                ex = expand_lean(rows, ebits, depth, active, depth_limit)

                # COMPACT EARLY: validity compaction is the only padded-width
                # random-access op; hashing, dedup, bucketing, and the exchange
                # all run at the compacted [vcap] width.
                vids, vvalid, n_val = vs._compact_ids(ex.valid, vcap)
                cl = tuple(ex.flat[s][vids] for s in range(S))
                ch1, ch2 = hash_lanes_jnp(cl)
                src = vids % u(chunk)
                cp1 = jnp.where(vvalid, row_h1[src], u(0))
                cp2 = jnp.where(vvalid, row_h2[src], u(0))
                cebits = ex.ebits[src]
                cdepth = depth[src] + u(1)

                reps = fr.claim_dedup(ch1, ch2, vvalid, dedup_cap)
                owner = ch1 % u(n_shards)

                # Bucket by owner with ONE rank computation (no per-destination
                # Python loop — program size stays flat in n_shards): a
                # [vcap, N] one-hot cumsum yields each candidate's rank within
                # its owner bucket and the per-owner counts in one pass.
                onehot = (
                    owner[:, None] == jnp.arange(n_shards, dtype=u)[None, :]
                ) & reps[:, None]
                csum = jnp.cumsum(onehot.astype(u), axis=0)  # [vcap, N]
                rank = (csum * onehot.astype(u)).sum(axis=1) - u(1)
                counts_per_owner = csum[-1]  # [N]
                n_ovf_total = (
                    counts_per_owner
                    - jnp.minimum(counts_per_owner, u(quota))
                ).sum(dtype=u)
                my = jnp.arange(vcap, dtype=u)
                dest = jnp.where(
                    reps & (rank < u(quota)),
                    owner * u(quota) + rank,
                    u(n_shards * quota) + my,  # distinct drop targets
                )
                send_cand = cl + (cp1, cp2, cebits, cdepth)
                send = [
                    jnp.zeros(n_shards * quota, dtype=u)
                    .at[dest]
                    .set(c, mode="drop", unique_indices=True)
                    for c in send_cand
                ]

                # The ICI hop: one all_to_all per lane; each shard receives the
                # buckets addressed to it from every shard.
                recv = [
                    lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
                    for x in send
                ]
                rstates = tuple(recv[t] for t in range(S))
                rp1 = recv[S]
                rp2 = recv[S + 1]
                # Parent fingerprints are nonzero as a pair for every real
                # candidate; an all-zero parent pair means "empty slot".
                r_valid = (rp1 | rp2) != u(0)
                rh1, rh2 = hash_lanes_jnp(rstates)  # owner-side recompute

                table, is_new, unresolved, _ovf_ins = vs.insert(
                    table, rh1, rh2, rp1, rp2, r_valid
                )
                unres = unresolved.sum(dtype=u)
                new_count = is_new.sum(dtype=u)

                if sample_k:
                    # Capture below-threshold inserts into this shard's slab.
                    # `is_new` is exactly-once (retried partial-commit steps
                    # re-deliver already-inserted rows, which are not new), so
                    # no fingerprint is ever captured twice. Writes happen at
                    # the full receive width R — never truncated; the trash
                    # slot at index scap absorbs masked lanes.
                    below = is_new & (
                        (rh1 < st1) | ((rh1 == st1) & (rh2 < st2))
                    )

                    def _capture(sc):
                        sfp1, sfp2, sdep, socc = sc
                        cids, cvalid, n_c = vs._compact_ids(below, R)
                        pos = socc + jnp.arange(R, dtype=u)
                        ok_w = cvalid & (pos < u(scap))
                        widx = jnp.where(ok_w, pos, u(scap))
                        return (
                            sfp1.at[widx].set(rh1[cids]),
                            sfp2.at[widx].set(rh2[cids]),
                            sdep.at[widx].set(recv[S + 3][cids]),
                            socc + n_c,
                        )

                    # Tight-threshold steps capture nothing almost always;
                    # the cond skips the compaction and slab scatters then.
                    # Per-shard predicate — shards diverge, which is fine:
                    # nothing inside the branch communicates.
                    sampc = lax.cond(
                        below.any(), _capture, lambda sc: sc, sampc
                    )

                qrows = rstates + (recv[S + 2], recv[S + 3])
                tail = (head + count) & u(qmask)
                queue = fr.ring_scatter(queue, tail, qrows, is_new)

                # Partial-commit overflow protocol (see module docstring).
                # Probe-tail overflow (unresolved candidates at the OWNER) is
                # retryable the same way, but the veto must be GLOBAL: the
                # unresolved candidates' parents were popped on OTHER shards,
                # so every shard must decline to consume and shrink its take
                # (a sender cannot know which owner overflowed). Fatal only
                # when no shard can shrink further — genuinely exhausted
                # probe chains, i.e. state loss.
                g_us = lax.psum(
                    jnp.stack([unres, (take > u(1)).astype(u)]), axis
                )
                g_unres = g_us[0]
                g_can_shrink = g_us[1]
                err_cnt = err_cnt + jnp.where(
                    g_can_shrink == u(0), g_unres, u(0)
                )
                ovf = (n_ovf_total > u(0)) | (g_unres > u(0))
                consumed = jnp.where(ovf, u(0), take)
                head = (head + consumed) & u(qmask)
                count = count - consumed + new_count
                unique = unique + new_count
                gen = gen + jnp.where(ovf, u(0), ex.generated)
                steps = steps + (pred & ~ovf).astype(u)
                take_cap = jnp.where(
                    ovf,
                    jnp.maximum(take >> u(1), u(1)),
                    jnp.minimum(take_cap + u(max(1, chunk // 16)), u(chunk)),
                )

                if cov:
                    # Shard-local coverage (obs/coverage.py): action counts at
                    # the SENDER (where expansion attributes candidates to
                    # their action slot; ovf-gated like `gen`), the consumed
                    # row count, and the depth histogram at the OWNER (where
                    # inserts happen; unconditional like `unique`). Shards
                    # psum these once in the block epilogue.
                    act, covp, expanded, dhist = covc
                    pa = ex.valid.astype(u).reshape(A, chunk).sum(axis=1)
                    act = act + jnp.where(ovf, u(0), pa)
                    expanded = expanded + consumed
                    dhist = dhist.at[
                        jnp.minimum(recv[S + 3], u(DEPTH_CAP - 1))
                    ].add(is_new.astype(u))
                    covc = (act, covp, expanded, dhist)

                if NP_:
                    hseen_n, facc1_n, facc2_n, faccd_n, covp_n = [], [], [], [], []
                    for pi in range(NP_):
                        hits = ex.prop_hits[pi]
                        first = hits & ~hseen[pi]
                        facc1_n.append(jnp.where(first, row_h1, facc1[pi]))
                        facc2_n.append(jnp.where(first, row_h2, facc2[pi]))
                        faccd_n.append(jnp.where(first, depth, faccd[pi]))
                        hseen_n.append(hseen[pi] | hits)
                        if cov:
                            covp_n.append(
                                covc[1][pi]
                                + jnp.where(ovf, u(0), hits.sum(dtype=u))
                            )
                    hseen = tuple(hseen_n)
                    facc1 = tuple(facc1_n)
                    facc2 = tuple(facc2_n)
                    faccd = tuple(faccd_n)
                    if cov:
                        covc = (covc[0], tuple(covp_n), covc[2], covc[3])

                its = its + u(1)
                g_cont = global_gates(
                    count, unique, err_cnt, hseen, rec_bits, its,
                    sampc[3] if sample_k else its,
                )
                return (
                    table, queue, head, count, unique, gen, steps, err_cnt,
                    take_cap, hseen, facc1, facc2, faccd, covc, sampc, its,
                    g_cont,
                )

            # err seeds from err0 (like engines/tpu_bfs.py): a chained
            # (speculative) dispatch off a probe-error era re-derives the
            # error exit and becomes an identity no-op instead of running
            # on a table with dropped states. The slab-occupancy seed is
            # the THREADED occupancy: a later fused era resumes where the
            # previous one left its slab.
            g0 = global_gates(
                count0,
                unique0,
                err0,
                tuple(false_lane for _ in range(NP_)),
                rec_bits,
                vzero,
                sampc0[3] if sample_k else vzero,
            )
            init = (
                table,
                queue,
                head0,
                count0,
                unique0,
                vzero,
                vzero,
                err0,  # carried: closes the gate on a chained dispatch
                jnp.minimum(jnp.maximum(take_cap0, u(1)), u(chunk)),
                tuple(false_lane for _ in range(NP_)),
                tuple(zero_lane for _ in range(NP_)),
                tuple(zero_lane for _ in range(NP_)),
                tuple(zero_lane for _ in range(NP_)),
                covc0,
                sampc0,
                vzero,  # iteration counter (uniform: shards run lockstep)
                g0,
            )
            (
                table, queue, head, count, unique, gen, steps, err_cnt,
                take_cap_out, hseen, facc1, facc2, faccd, covc_out,
                sampc_out, its_out, _gc,
            ) = lax.while_loop(cond, body, init)

            # Era epilogue (once per era): BLOCK-LOCAL discovery reports.
            # The host keeps the min-depth discovery across blocks and
            # shards — shards skew, so a shallower hit can surface in a
            # LATER block than a deeper one (the reference's multithreaded
            # BFS has the same benign race, bfs.rs:243-244; tracking min
            # depth host-side makes us strictly better, not just equal).
            if NP_:
                ef1, ef2, edd = [], [], []
                for pi in range(NP_):
                    found = jnp.any(hseen[pi])
                    sel = jnp.argmin(
                        jnp.where(hseen[pi], faccd[pi], u(0xFFFFFFFF))
                    )
                    ef1.append(jnp.where(found, facc1[pi][sel], u(0)))
                    ef2.append(jnp.where(found, facc2[pi][sel], u(0)))
                    edd.append(
                        jnp.where(found, faccd[pi][sel], u(0xFFFFFFFF))
                    )
                era_fp1 = jnp.stack(ef1)
                era_fp2 = jnp.stack(ef2)
                era_dd = jnp.stack(edd)
            else:
                era_fp1 = jnp.zeros(0, dtype=u) + vzero
                era_fp2 = jnp.zeros(0, dtype=u) + vzero
                era_dd = jnp.zeros(0, dtype=u) + vzero
            maxd = jnp.where(
                steps > 0, queue[S + 1][(head - u(1)) & u(qmask)], u(0)
            )
            # Adaptive era budget (device-side emission, mirroring
            # engines/tpu_bfs.py): every input to the formula is globally
            # uniform (one epilogue psum for pressure/err/work/global rec
            # bits; `its_out` runs lockstep), so every shard emits the SAME
            # next budget and a chained dispatch stays uniform too. The
            # cost is one collective per ERA, not per step — and the same
            # psum carries the fusion gate (`budget_only`, slab occupancy),
            # so chaining eras on device adds no extra collectives.
            glocal = [
                ((count > high_water) | (unique > grow_limit)).astype(u),
                (err_cnt > u(0)).astype(u),
                (count > u(0)).astype(u),
            ] + [
                jnp.minimum(hseen[pi].sum(dtype=u), u(1))
                for pi in range(NP_)
            ]
            if sample_k and fuse > 1:
                socc_out = sampc_out[3]
                glocal.append((socc_out > u(s_high)).astype(u))
            gb = lax.psum(jnp.stack(glocal), axis)
            g_pressure = gb[0] > u(0)
            g_err = gb[1] > u(0)
            g_work = gb[2] > u(0)
            rec_all = rec_bits
            for pi in range(NP_):
                rec_all = rec_all | (jnp.minimum(gb[3 + pi], u(1)) << u(pi))
            fin_hit_final = ((rec_all & fin_any) != u(0)) | (
                (fin_all_en != u(0)) & ((rec_all & fin_all) == fin_all)
            )
            budget_only = (
                (its_out >= max_steps)
                & g_work
                & ~g_pressure
                & ~g_err
                & ~fin_hit_final
            )
            g_slab_full = (
                gb[3 + NP_] > u(0) if (sample_k and fuse > 1) else None
            )
            grown = jnp.minimum(
                jnp.maximum(max_steps, u(1)) * u(2), budget_cap
            )
            shrunk = jnp.maximum(
                jnp.minimum(max_steps, budget_cap) >> u(1),
                u(64),  # BUDGET_MIN (engines/tpu_bfs.py)
            )
            next_budget = jnp.where(
                budget_cap == u(0),
                max_steps,
                jnp.where(
                    g_pressure, shrunk,
                    jnp.where(budget_only, grown, max_steps),
                ),
            )
            return (
                table, queue, head, count, unique, rec_all, err_cnt,
                take_cap_out, covc_out, sampc_out, era_fp1, era_fp2,
                era_dd, steps, gen, maxd, next_budget, budget_only,
                g_slab_full,
            )

        sampc_init = (
            (
                jnp.zeros(scap + 1, dtype=u) + vzero,  # fp1 (+ trash slot)
                jnp.zeros(scap + 1, dtype=u) + vzero,  # fp2
                jnp.zeros(scap + 1, dtype=u) + vzero,  # depth
                vzero,  # occupied
            )
            if sample_k
            else ()
        )
        covc_init = (
            (
                jnp.zeros(A, dtype=u) + vzero,  # per-action valid counts
                tuple(vzero for _ in range(NP_)),  # per-property hits
                vzero,  # consumed rows
                jnp.zeros(DEPTH_CAP, dtype=u) + vzero,  # depth histogram
            )
            if cov
            else ()
        )

        if fuse == 1:
            (
                table, queue, head, count, unique, rec_all, err_cnt,
                take_cap_out, covc_out, sampc_out, rec_fp1, rec_fp2,
                disc_depth, steps, gen, maxd, next_budget, _budget_only,
                _g_slab,
            ) = run_era(
                table, queue, params[P_HEAD], params[P_COUNT],
                params[P_UNIQUE], rec_bits0, max_steps0, params[P_ERR],
                params[P_TAKE_CAP], covc_init, sampc_init,
            )
            ftail = []
        else:
            # Multi-era fusion: chain up to fuse_lim eras inside THIS one
            # dispatch. An era chains iff its ONLY exit reason was budget
            # exhaustion (globally uniform: psum-derived) and, with
            # sampling on, no shard's slab passed its high-water mark —
            # exactly the cases where the serial host would immediately
            # re-dispatch with nothing but a budget/threshold refresh.
            # Everything else (spill pressure, growth, probe error,
            # finish-policy hit, drained frontier) exits the outer loop so
            # the readback reports which inner era tripped.
            fuse_lim = jnp.minimum(
                jnp.maximum(params[f_base], u(1)), u(fuse)
            )
            fzero = jnp.zeros(fuse, dtype=u) + vzero
            np_zero = jnp.zeros(NP_, dtype=u) + vzero
            # Per-shard best-discovery fold across inner eras: strict
            # less-than keeps the EARLIEST era on depth ties, and the
            # per-property era index rides the params tail so the host
            # can reproduce the serial (depth, era, shard) tie-break.
            dd_init = np_zero + u(0xFFFFFFFF)

            def ocond(oc):
                return (oc[0] < fuse_lim) & (oc[1] != u(0))

            def obody(oc):
                (
                    k, _cont, steps_acc, gen_acc, maxd_acc, fsteps, fgen,
                    funiq, fcnt, table, queue, head, count, unique, rbits,
                    ms, err, tc, covc, sampc, afp1, afp2, add, aera,
                ) = oc
                uniq_in = unique
                (
                    table, queue, head, count, unique, rbits, err, tc,
                    covc, sampc, efp1, efp2, edd, steps, gen, maxd,
                    next_budget, budget_only, g_slab,
                ) = run_era(
                    table, queue, head, count, unique, rbits, ms, err, tc,
                    covc, sampc,
                )
                cont = budget_only
                if sample_k:
                    cont = cont & ~g_slab
                upd = edd < add
                return (
                    k + u(1),
                    cont.astype(u),
                    steps_acc + steps,
                    gen_acc + gen,
                    jnp.maximum(maxd_acc, maxd),
                    fsteps.at[k].set(steps),
                    fgen.at[k].set(gen),
                    funiq.at[k].set(unique - uniq_in),
                    fcnt.at[k].set(count),
                    table, queue, head, count, unique, rbits,
                    next_budget, err, tc, covc, sampc,
                    jnp.where(upd, efp1, afp1),
                    jnp.where(upd, efp2, afp2),
                    jnp.where(upd, edd, add),
                    jnp.where(upd, k, aera),
                )

            oinit = (
                vzero,  # k: inner-era counter (uniform)
                vzero + u(1),  # cont: always run at least one era
                vzero, vzero, vzero,  # steps/gen/maxd accumulators
                fzero, fzero, fzero, fzero,  # per-inner-era tail lanes
                table, queue, params[P_HEAD], params[P_COUNT],
                params[P_UNIQUE], rec_bits0, max_steps0, params[P_ERR],
                params[P_TAKE_CAP], covc_init, sampc_init,
                np_zero, np_zero, dd_init, np_zero,
            )
            (
                k_out, _cont, steps, gen, maxd, fsteps, fgen, funiq, fcnt,
                table, queue, head, count, unique, rec_all, next_budget,
                err_cnt, take_cap_out, covc_out, sampc_out, rec_fp1,
                rec_fp2, disc_depth, disc_era,
            ) = lax.while_loop(ocond, obody, oinit)
            ftail = [
                jnp.stack([fuse_lim, k_out]),
                fsteps, fgen, funiq, fcnt, disc_era,
            ]
        # P_REC emits the GLOBAL accumulated bits (rec_all), not the
        # shard-local bits: the host ORs the rows anyway, and a chained
        # (speculative) dispatch feeds the row straight back in —
        # shard-local bits would make the finish gate non-uniform across
        # shards and deadlock the lockstep collectives. Per-shard
        # discovery attribution rides disc_depth, not this word.
        parts = [
            jnp.stack(
                [
                    head, count, unique, rec_all, depth_limit,
                    grow_limit, high_water, next_budget, gen, maxd, steps,
                    (err_cnt > 0).astype(u), take_cap_out,
                    fin_any, fin_all, fin_all_en, budget_cap,
                ]
            )
        ]
        if cov:
            # Coverage tail, psum'd across the mesh so every shard's row
            # carries the GLOBAL histograms (the host reads row 0):
            # act[A] | prop_hits[NP_] | expanded[1] | depth[DEPTH_CAP].
            act, covp, expanded, dhist = covc_out
            covp_vec = (
                jnp.stack(list(covp)) if NP_ else jnp.zeros(0, dtype=u) + vzero
            )
            parts.append(
                lax.psum(
                    jnp.concatenate([act, covp_vec, expanded[None], dhist]),
                    axis,
                )
            )
        if sample_k:
            # Per-shard sample tail, deliberately UN-psum'd (fingerprints
            # don't reduce): [T1, T2, occupied, 0] + the sk2 smallest slab
            # entries by h1 (fp1 | fp2 | depth | ok). One top_k in the
            # once-per-era epilogue; the ok lane disambiguates padding
            # from a real fp1 of 0xFFFFFFFF; the host applies the exact
            # 64-bit tie cut (obs/sample.py) and unions the shards.
            sfp1, sfp2, sdep, socc = sampc_out
            used = jnp.arange(scap, dtype=u) < socc
            skey = jnp.where(used, ~sfp1[:scap], u(0))
            _topv, topi = lax.top_k(skey, sk2)
            parts += [
                jnp.stack([st1, st2, socc, vzero]),
                sfp1[:scap][topi],
                sfp2[:scap][topi],
                sdep[:scap][topi],
                used[topi].astype(u),
            ]
        # Fusion tail (fuse > 1 only): [fuse_lim (pass-through), n_inner]
        # + per-inner-era steps | generated | unique-delta | frontier
        # lanes + per-property best-discovery era index. One readback
        # then reconstructs n_inner exact flight records and the serial
        # discovery tie-break.
        parts += ftail
        params_out = jnp.concatenate(parts)

        def exp(x):
            return jnp.expand_dims(x, 0)

        return (
            tuple(exp(t) for t in table),
            tuple(exp(q) for q in queue),
            exp(rec_fp1),
            exp(rec_fp2),
            exp(params_out),
            exp(disc_depth),
        )

    spec = PartitionSpec(axis)
    mapped = get_shard_map()(
        per_device,
        mesh=mesh,
        in_specs=(spec,) * 5,
        out_specs=(spec,) * 6,
    )
    # Two donation policies over ONE traced function (see BlockProgram):
    # the serial driver's params rows are a fresh host upload each
    # dispatch, so they are donatable on top of the table/queue lanes; a
    # chained dispatch feeds the previous block's params output back in
    # while its readback (and the discovery fp/depth reads) are still
    # pending, so the chain variant pins it. The rec_fp arrays are never
    # donated — the host reads the OUTPUT handles in consume(), and under
    # chaining those same handles are the next dispatch's inputs.
    d_serial = donate_argnums_pinned((0, 1, 4))
    d_chain = donate_argnums_pinned((0, 1, 4), pinned=(4,))
    serial = jax.jit(mapped, donate_argnums=d_serial)
    chain = (
        serial
        if d_chain == d_serial
        else jax.jit(mapped, donate_argnums=d_chain)
    )
    program = BlockProgram(serial, chain)
    _LOOP_CACHE[key] = (tm, program)
    return program


# Stage-profiler kernels (obs/stageprof.py): one shard_map'd jitted
# microbench per era-loop stage, signature (table, queue, seed[N]) ->
# psummed uint32 per shard. All shards run each stage in lockstep (the
# final psum couples them), so the dispatch wall time measured by the host
# IS the global per-stage time — the mesh twin of the single-device
# engine's `_build_stage_kernels` (engines/tpu_bfs.py), plus `exchange`
# for the owner-bucketing + all_to_all hop this engine alone has.
_STAGE_KERNEL_CACHE: Dict[Tuple, Tuple[TensorModel, Dict[str, Any]]] = {}


def _build_mesh_stage_kernels(tm: TensorModel, props, chunk: int, qcap: int,
                              n_shards: int, quota: int, mesh, axis: str,
                              iters: int) -> Dict[str, Any]:
    key = (
        id(tm), chunk, qcap, n_shards, quota, len(props), iters,
        tuple(id(d) for d in mesh.devices.flat),
    )
    cached = _STAGE_KERNEL_CACHE.get(key)
    if cached is not None and cached[0] is tm:
        return cached[1]
    while len(_STAGE_KERNEL_CACHE) >= 8:
        _STAGE_KERNEL_CACHE.pop(next(iter(_STAGE_KERNEL_CACHE)))

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec

    from ..compat import get_shard_map
    from ..engines.tpu_bfs import _vcap
    from ..fingerprint import hash_lanes_jnp
    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_expand_lean

    S = tm.state_width
    A = tm.max_actions
    W = S + 2
    X = S + 4
    u = jnp.uint32
    expand_lean = build_expand_lean(tm, props, chunk)
    qmask = qcap - 1
    vcap = _vcap(A, chunk)
    dedup_cap = 1 << max(1, (2 * vcap - 1).bit_length())
    rwidth = n_shards * quota  # exchange receive / insert / append width

    def _mix(x):
        x = x ^ (x >> 16)
        x = x * u(0x7FEB352D)
        x = x ^ (x >> 15)
        x = x * u(0x846CA68B)
        return x ^ (x >> 16)

    def _lane(n, salt):
        return _mix(jnp.arange(n, dtype=u) * u(0x9E3779B1) + u(salt))

    def _wrap(stage_body):
        def per_device(table, queue, seed):
            table = tuple(t[0] for t in table)
            queue = tuple(q[0] for q in queue)
            acc = stage_body(table, queue, seed[0])
            # One final psum couples the shards, so the host-observed
            # dispatch time is gated by the slowest shard (lockstep, like
            # the real era loop's per-step global gates).
            return jnp.expand_dims(lax.psum(acc, axis), 0)

        spec = PartitionSpec(axis)
        return jax.jit(
            get_shard_map()(
                per_device, mesh=mesh, in_specs=(spec,) * 3,
                out_specs=spec,
            )
        )

    def b_expand(table, queue, s0):
        rows0 = tuple(queue[s][:chunk] for s in range(S))
        ebits0 = queue[S][:chunk]
        depth0 = queue[S + 1][:chunk]
        active = jnp.ones(chunk, dtype=bool)

        def body(_i, acc):
            rows = (rows0[0] ^ (acc & u(1)),) + rows0[1:]
            ex = expand_lean(rows, ebits0, depth0, active, u(0xFFFFFFFF))
            return acc + ex.generated

        return lax.fori_loop(0, iters, body, s0)

    def b_hash(table, queue, s0):
        rows0 = tuple(queue[s][:chunk] for s in range(S))
        cl0 = tuple(_lane(vcap, 11 + s) for s in range(S))

        def body(_i, acc):
            r = (rows0[0] ^ (acc & u(1)),) + rows0[1:]
            h1, h2 = hash_lanes_jnp(r)
            c = (cl0[0] ^ (acc & u(1)),) + cl0[1:]
            g1, g2 = hash_lanes_jnp(c)
            return acc + h1[0] + h2[0] + g1[0] + g2[0]

        return lax.fori_loop(0, iters, body, s0)

    def b_compact(table, queue, s0):
        # The single validity compaction [C*A] -> vcap plus the dependent
        # gathers to the compacted width (state lanes from the padded
        # batch, parent/ebits/depth lanes from the popped rows).
        flat0 = tuple(_lane(chunk * A, 41 + s) for s in range(S))
        r1 = _lane(chunk * A, 53)
        rowls = tuple(queue[t][:chunk] for t in range(min(4, W)))

        def body(_i, acc):
            m1 = ((r1 ^ acc) & u(3)) == u(0)
            vids, _vv, n1 = vs._compact_ids(m1, vcap)
            src = vids % u(chunk)
            acc = acc + n1
            for lane in flat0:
                acc = acc + lane[vids].sum(dtype=u)
            for lane in rowls:
                acc = acc + lane[src].sum(dtype=u)
            return acc

        return lax.fori_loop(0, iters, body, s0)

    def b_claim(table, queue, s0):
        p1 = _lane(vcap, 31)
        p2 = _lane(vcap, 37)
        valid = jnp.ones(vcap, dtype=bool)

        def body(_i, acc):
            h1 = p1 ^ (acc & u(1))
            reps = fr.claim_dedup(h1, p2, valid, dedup_cap)
            return acc + reps.sum(dtype=u)

        return lax.fori_loop(0, iters, body, s0)

    def b_exchange(table, queue, s0):
        # Owner bucketing (the [vcap, N] one-hot cumsum rank), the send
        # scatters, and the all_to_all ICI hop for all X lanes.
        ch0 = _lane(vcap, 61)
        iota_v = jnp.arange(vcap, dtype=u)
        lanes0 = tuple(_lane(vcap, 67 + x) for x in range(X))

        def body(_i, acc):
            ch1 = ch0 ^ (acc & u(1))
            reps = ((ch1 >> u(4)) & u(3)) != u(3)  # ~75% survive dedup
            owner = ch1 % u(n_shards)
            onehot = (
                owner[:, None] == jnp.arange(n_shards, dtype=u)[None, :]
            ) & reps[:, None]
            csum = jnp.cumsum(onehot.astype(u), axis=0)
            rank = (csum * onehot.astype(u)).sum(axis=1) - u(1)
            dest = jnp.where(
                reps & (rank < u(quota)),
                owner * u(quota) + rank,
                u(rwidth) + iota_v,
            )
            send = [
                jnp.zeros(rwidth, dtype=u)
                .at[dest]
                .set(c ^ acc, mode="drop", unique_indices=True)
                for c in lanes0
            ]
            recv = [
                lax.all_to_all(
                    x, axis, split_axis=0, concat_axis=0, tiled=True
                )
                for x in send
            ]
            for rl in recv:
                acc = acc + rl.sum(dtype=u)
            return acc

        return lax.fori_loop(0, iters, body, s0)

    def b_probe(table, queue, s0):
        # Owner-side insert at the receive width, against the run's real
        # table shard (copy-on-write fork in the carry; two alternating
        # key pools bound the fork's extra load at 2*rwidth keys).
        me = lax.axis_index(axis).astype(u)
        pool1 = _mix(
            jnp.arange(rwidth, dtype=u) * u(0x9E3779B1)
            + me * u(0x85EBCA77) + u(21)
        )
        pool2 = _mix(pool1 ^ u(0x6C62272E))
        ones = jnp.ones(rwidth, dtype=bool)

        def body(_i, carry):
            tbl, acc = carry
            flip = acc & u(1)
            dh1 = pool1 ^ flip
            dh2 = pool2 ^ flip
            tbl, c_new, _un, _ov = vs.insert(tbl, dh1, dh2, dh1, dh2, ones)
            return tbl, acc + c_new.sum(dtype=u)

        tbl, acc = lax.fori_loop(0, iters, body, (table, s0))
        return acc + (tbl[0][0] & u(1))

    def b_ring(table, queue, s0):
        base = jnp.arange(rwidth, dtype=u)

        def body(_i, carry):
            q, head, acc = carry
            popped, _idx = fr.ring_gather(q, head, chunk)
            cand = tuple(
                _mix(
                    base * u(2654435761)
                    + popped[w].sum(dtype=u) + u(w * 17)
                )
                for w in range(W)
            )
            valid = jnp.ones(rwidth, dtype=bool)
            q = fr.ring_scatter(
                q, (head + u(chunk)) & u(qmask), cand, valid
            )
            return q, (head + u(chunk)) & u(qmask), acc + cand[0][0]

        _q, _h, acc = lax.fori_loop(0, iters, body, (queue, s0, s0))
        return acc

    kernels = {
        name: _wrap(body_fn)
        for name, body_fn in (
            ("expand", b_expand),
            ("hash", b_hash),
            ("compact", b_compact),
            ("claim", b_claim),
            ("exchange", b_exchange),
            ("probe", b_probe),
            ("ring", b_ring),
        )
    }
    _STAGE_KERNEL_CACHE[key] = (tm, kernels)
    return kernels


_GROW_CACHE: Dict[Tuple, Any] = {}


def _build_grow(old_cap: int, new_cap: int, mesh, axis: str):
    """Compile a shard_map'd per-shard rehash old_cap -> new_cap.

    Runs entirely on device: each shard re-inserts its occupied rows into
    a fresh table created in-program. Returns (new_table, unresolved[N]).
    """
    key = (old_cap, new_cap, tuple(id(d) for d in mesh.devices.flat))
    cached = _GROW_CACHE.get(key)
    if cached is not None:
        return cached
    while len(_GROW_CACHE) >= 8:
        _GROW_CACHE.pop(next(iter(_GROW_CACHE)))

    import jax
    from jax.sharding import PartitionSpec

    from ..compat import donate_argnums_safe, get_shard_map
    from ..ops import visited_set as vs

    def per_device(table):
        import jax.numpy as jnp

        shard = tuple(t[0] for t in table)
        # Fresh tables seeded from varying input so their shard_map type is
        # varying on the mesh axis (constant zeros would be unvarying and
        # fail the rehash loop's carry typing).
        vz = shard[0][0] & jnp.uint32(0)
        empty = tuple(l + vz for l in vs.empty_table(new_cap))
        new_table, unres = vs.rehash(shard, empty)
        return (
            tuple(jnp.expand_dims(l, 0) for l in new_table),
            jnp.expand_dims(unres, 0),
        )

    spec = PartitionSpec(axis)
    grow = jax.jit(
        get_shard_map()(
            per_device,
            mesh=mesh,
            in_specs=((spec,) * 3,),
            out_specs=((spec,) * 3, spec),
        ),
        donate_argnums=donate_argnums_safe(0),
    )

    def run(table):
        new_table, unres = grow(table)
        return new_table, unres

    _GROW_CACHE[key] = run
    return run


class ShardedBfsChecker(HostEngineBase):
    """Multi-device batched BFS behind the standard Checker API.

    Spawn with `CheckerBuilder.spawn_sharded_bfs()`. Tables and frontiers
    are fingerprint-ownership-sharded across the device mesh; see module
    docstring.
    """

    _supports_threads = True  # parallelism = the mesh, not worker threads

    def __init__(
        self,
        builder: CheckerBuilder,
        *,
        devices: Optional[List] = None,
        chunk_size: int = 1024,
        queue_capacity_per_shard: int = 1 << 16,
        table_capacity_per_shard: int = 1 << 18,
        sync_steps: int = 4096,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: Optional[float] = None,
        resume_from: Optional[str] = None,
        keep_checkpoints: int = 2,
    ):
        import jax
        from jax.sharding import Mesh

        model = builder.model
        if isinstance(model, TensorModel):
            model = TensorModelAdapter(model)
        if not isinstance(model, TensorModelAdapter):
            raise TypeError(
                "spawn_sharded_bfs requires a TensorModel (or its adapter)"
            )
        super().__init__(builder, model=model)
        if self._visitor is not None:
            raise ValueError("the sharded engine does not support visitors")

        self.tm: TensorModel = model.tm
        self._tprops = self.tm.tensor_properties()
        if len(self._tprops) > 32:
            raise ValueError("at most 32 tensor properties supported")
        devices = devices if devices is not None else jax.devices()
        self.n_shards = len(devices)
        self.mesh = Mesh(np.array(devices), ("shards",))
        if queue_capacity_per_shard & (queue_capacity_per_shard - 1):
            raise ValueError("queue capacity must be a power of two")
        A = max(1, self.tm.max_actions)
        self._chunk = min(chunk_size, queue_capacity_per_shard // (2 * A))
        if self._chunk == 0:
            raise ValueError("queue capacity too small for this model's fanout")
        self._qcap = queue_capacity_per_shard
        self._tcap = table_capacity_per_shard
        self._max_sync_steps = sync_steps
        # Per-destination exchange quota: the receive width is
        # n_shards * quota, so this also caps per-step inserts per shard.
        self._quota = max(64, (self._chunk * A) // (4 * self.n_shards))
        if self._qcap < 4 * self.n_shards * self._quota:
            raise ValueError(
                "queue_capacity_per_shard must be at least 4 * n_shards * "
                f"quota (= {4 * self.n_shards * self._quota}); got "
                f"{self._qcap}. Raise the queue capacity or lower chunk_size."
            )
        self._cov = self._coverage.enabled
        self._sample_k = self._sampler.k if self._sampler is not None else 0
        self._stage_profile = bool(getattr(builder, "stage_profile_", False))
        self._stage_iters = int(getattr(builder, "stage_profile_iters_", 32))
        # Speculative era pipelining (CheckerBuilder.pipeline(), default
        # on) — see _run_loop and engines/tpu_bfs.py for the soundness
        # argument.
        self._pipeline = bool(getattr(builder, "pipeline_", True))
        # K-deep speculative chain (CheckerBuilder.pipeline(depth=K)) and
        # on-device multi-era fusion (fuse=N): both amortize host
        # bookkeeping over dispatches; defaults keep the PR-14 behaviour
        # (depth auto=2) and one era per dispatch.
        depth = getattr(builder, "pipeline_depth_", None)
        self._chain_depth = max(1, int(depth)) if depth is not None else 2
        self._chain_max = 0
        self._fuse = max(1, int(getattr(builder, "fuse_eras_", None) or 1))
        self._block = _build_block(
            self.tm, self._tprops, self._chunk, self._qcap, self.n_shards,
            self._quota, self.mesh, "shards", self._cov,
            sample_k=self._sample_k, fuse=self._fuse,
        )

        self._unique = 0
        self._discovery_fps: Dict[str, int] = {}
        # Tiered spill staging (ops/tiering.py): one budgeted host-RAM
        # LIFO per shard with an npz disk tier below; the host budget is
        # split evenly across shards. Unbudgeted (env unset) each store
        # is a plain in-RAM stack, byte-for-byte the old list behavior.
        from ..ops.tiering import TieredSpillStore, spill_host_budget_bytes

        _budget = spill_host_budget_bytes()
        if _budget is not None:
            _budget = max(1, _budget // self.n_shards)
        self._spill: List[TieredSpillStore] = [
            TieredSpillStore(
                host_budget_bytes=_budget,
                on_tier=self._on_spill_tier,
                label=f"spill-s{s}",
            )
            for s in range(self.n_shards)
        ]
        # Delta-checkpoint chain state (engines/common.py
        # save_checkpoint_tiered): None = next save is a full base.
        self._ckpt_delta = None
        # Era of the last proactive reshard (one doubling per forecast).
        self._reshard_last_era = -1
        # Sharded checkpoint/resume: per-shard tables, rings, spill lists,
        # take_caps and counters serialize to one .npz at block boundaries
        # (all arrays are host-visible there). Writes are crash-atomic with
        # rolling generations and a content digest (engines/common.py);
        # checkpoint_every is wall-clock seconds, polled at era boundaries.
        from ..engines.common import (
            register_signal_checkpoint_flush,
            validate_checkpoint_cadence,
        )

        validate_checkpoint_cadence(
            checkpoint_every, checkpoint_path, keep_checkpoints
        )
        self._ckpt_path = checkpoint_path
        self._ckpt_every = checkpoint_every
        self._ckpt_keep = keep_checkpoints
        self._resume_from = resume_from
        import time as _time

        self._last_ckpt = _time.monotonic()
        # Chaos-injection hook (tests/test_durability_chaos.py): fake a
        # probe-budget exhaustion at this era count to exercise the
        # degraded-regrow recovery.
        self._chaos_probe_error_era: Optional[int] = None
        if checkpoint_path is not None:
            register_signal_checkpoint_flush(self)
        self._init_ebits = 0
        e = 0
        for p in self._tprops:
            if p.expectation == Expectation.EVENTUALLY:
                self._init_ebits |= 1 << e
                e += 1
        self._start()

    # -- engine body --------------------------------------------------------

    def _run(self) -> None:
        import jax.numpy as jnp

        from ..ops import visited_set as vs

        tm = self.tm
        S = tm.state_width
        A = tm.max_actions
        C = self._chunk
        N = self.n_shards
        NP_ = len(self._tprops)
        W = S + 2  # ring lanes: state | ebits | depth

        if self._resume_from is not None:
            (
                table,
                queue,
                heads,
                counts,
                rec_bits,
                rec_fp1,
                rec_fp2,
                take_caps,
                disc_depth_best,
                per_shard_unique,
            ) = self._load_checkpoint(self._resume_from, W)
            depth_limit = (
                self._target_max_depth
                if self._target_max_depth is not None
                else 0xFFFFFFFF
            )
            return self._run_loop(
                table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
                take_caps, disc_depth_best, per_shard_unique, depth_limit,
                self._qcap - N * self._quota, W,
            )

        inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
        init_lanes = tuple(inits[:, i] for i in range(S))
        inb = np.asarray(tm.within_boundary_lanes(np, init_lanes), dtype=bool)
        inits = inits[inb]
        self._state_count = len(inits)
        if len(inits) == 0:
            return
        h1, h2 = hash_words_np(inits)

        # Route init states to their owners; seed tables host-side with the
        # SAME double-hash probe sequence the device insert uses.
        queue_np = np.zeros((N, self._qcap, W), dtype=np.uint32)
        counts = np.zeros(N, dtype=np.int64)
        table_np = np.zeros((N, self._tcap, 4), dtype=np.uint32)
        seen = set()
        owners = h1.astype(np.int64) % N
        per_owner = np.bincount(owners, minlength=N)
        if per_owner.max() > self._qcap:
            raise ValueError(
                f"shard {int(per_owner.argmax())} would receive "
                f"{int(per_owner.max())} initial states, exceeding "
                f"queue_capacity_per_shard={self._qcap}; raise the per-shard "
                "queue capacity (mirrors the single-device n_init > qcap check)"
            )
        for i in range(len(inits)):
            o = int(owners[i])
            fp = combine64(h1[i], h2[i])
            row = queue_np[o, counts[o]]
            row[:S] = inits[i]
            row[S] = self._init_ebits
            row[S + 1] = 1
            counts[o] += 1
            if fp not in seen:
                seen.add(fp)
                self._host_insert(table_np[o], int(h1[i]), int(h2[i]))
                self._unique += 1
        self._coverage.record_depth(1, len(seen))
        if self._sampler is not None:
            # Init states never pass the device slab (they are host-seeded,
            # not exchanged) — offer them here; the sampler dedups.
            fps = (h1.astype(np.uint64) << np.uint64(32)) | h2.astype(
                np.uint64
            )
            self._sampler.offer_array(
                fps,
                depths=np.ones(len(inits), dtype=np.int64),
                states=inits,
            )

        # Pack the host-seeded 4-lane rows into the device table layout:
        # per-shard key buffer [2*tcap] (h1 half | h2 half) + parent lanes.
        table = (
            jnp.asarray(
                np.concatenate([table_np[:, :, 0], table_np[:, :, 1]], axis=1)
            ),
            jnp.asarray(table_np[:, :, 2]),
            jnp.asarray(table_np[:, :, 3]),
        )
        queue = tuple(jnp.asarray(queue_np[:, :, t]) for t in range(W))
        rec_fp1 = jnp.zeros((N, NP_), dtype=jnp.uint32)
        rec_fp2 = jnp.zeros((N, NP_), dtype=jnp.uint32)
        heads = np.zeros(N, dtype=np.int64)

        depth_limit = (
            self._target_max_depth
            if self._target_max_depth is not None
            else 0xFFFFFFFF
        )
        # The per-step append is bounded by the receive width.
        high_water = self._qcap - N * self._quota
        rec_bits = 0
        take_caps = [self._chunk] * N
        disc_depth_best: Dict[str, int] = {}
        per_shard_unique = self._per_shard_uniques(table_np)
        return self._run_loop(
            table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
            take_caps, disc_depth_best, per_shard_unique, depth_limit,
            high_water, W,
        )

    def _mem_register(self, table, queue, rec_fps, params_dev) -> None:
        """(Re-)register the mesh's device buffers with the memory ledger
        from the shared size formulas (obs/memory.py
        mesh_component_sizes); every component carries the shard
        dimension. Called at loop entry and after every uniform all-shard
        table growth; a re-registration at a new size logs the growth
        event. The packed params row block is attached at each dispatch
        (it is rebuilt per era)."""
        rec = self._memory
        if rec is None:
            return
        from ..obs.memory import mesh_component_sizes
        from ..ops import visited_set as vs

        sizes = mesh_component_sizes(
            self.tm.state_width,
            self.tm.max_actions,
            len(self._tprops),
            chunk=self._chunk,
            queue_capacity_per_shard=self._qcap,
            table_capacity_per_shard=self._tcap,
            n_shards=self.n_shards,
            coverage=self._cov,
            sample_k=self._sample_k,
            fuse=self._fuse,
        )
        arrays = {
            "visited_table": table,
            "frontier_queue": queue,
            "record_fps": rec_fps,
            "packed_params": params_dev,
            "coverage_slab": params_dev,
            "sample_slab": params_dev,
        }
        if self._fuse > 1:
            arrays["fusion_tail"] = params_dev
        rec.register_components(sizes, arrays=arrays)
        rec.set_geometry(
            rows=self._tcap,
            max_load=vs.MAX_LOAD,
            reserve_rows=self.n_shards * self._quota,
        )

    def _spill_host_bytes(self) -> int:
        return sum(
            self._spill[s].host_bytes() for s in range(self.n_shards)
        )

    def _on_spill_tier(self, direction, rows, nbytes, disk_bytes) -> None:
        """Tier-move hook shared by every shard's TieredSpillStore: keep
        the spill_tier counters, the disk gauge, and the ledger's disk
        component exact (disk bytes re-register at the ALL-shard total,
        so plan == ledger == nbytes holds per kind)."""
        if direction == "ram_to_disk":
            self._metrics.inc("spill_tier_rows", rows)
        else:
            self._metrics.inc("spill_tier_refill_rows", rows)
        total_disk = sum(
            self._spill[s].disk_bytes() for s in range(self.n_shards)
        )
        self._metrics.set_gauge("spill_disk_bytes", total_disk)
        if self._memory is not None:
            led = self._memory.ledger
            led.register("spill_disk", nbytes=total_disk, kind="disk")
            led.event(
                "spill_tier",
                direction=direction,
                rows=int(rows),
                bytes=int(nbytes),
                disk_bytes=int(total_disk),
            )

    def _proactive_reshard_due(self) -> bool:
        """Forecast-triggered elastic reshard (ISSUE 20; mirrors
        engines/tpu_bfs.py): with a device limit set and exhaustion
        projected, front-run the next uniform table doubling once the
        forecaster puts it within the reshard horizon.  The measured
        load-fraction floor keeps it self-limiting: each doubling halves
        ``load_frac``, so a diverging fit cannot re-trigger every era."""
        rec = self._memory
        if rec is None:
            return False
        fc = rec.last_forecast()
        if fc.get("eras_to_exhaustion") is None:
            return False
        eta_grow = fc.get("eras_to_grow")
        from ..obs.memory import RESHARD_HORIZON_ERAS, RESHARD_MIN_LOAD_FRAC

        return (
            eta_grow is not None
            and eta_grow <= RESHARD_HORIZON_ERAS
            and fc.get("load_frac", 0.0) >= RESHARD_MIN_LOAD_FRAC
        )

    def _run_loop(
        self, table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
        take_caps, disc_depth_best, per_shard_unique, depth_limit,
        high_water, W,
    ) -> None:
        import time as _time

        import jax.numpy as jnp

        self._mem_register(table, queue, (rec_fp1, rec_fp2), None)

        from ..ops import visited_set as vs

        from ..obs.coverage import DEPTH_CAP

        tm = self.tm
        S = tm.state_width
        A = tm.max_actions
        C = self._chunk
        N = self.n_shards
        NP_ = len(self._tprops)
        ncov = (A + NP_ + 1 + DEPTH_CAP) if self._cov else 0
        sk2 = nsamp = 0
        if self._sample_k:
            from ..obs.sample import slab_entries

            sk2 = slab_entries(self._sample_k)
            nsamp = 4 + 4 * sk2  # [T1,T2,occupied,0] + fp1|fp2|dep|ok
        s_base = P_LEN + ncov
        nfuse = shard_fuse_tail_len(self._fuse, NP_)
        f_base = s_base + nsamp
        last_thresh = None
        max_sync = (
            self._max_sync_steps
            if self._timeout is None and self._ckpt_every is None
            else min(64, self._max_sync_steps)
        )
        # Adaptive era budgets (see engines/tpu_bfs.py): the device epilogue
        # emits the next era's budget through the P_MAX_STEPS output slot
        # (globally uniform — computed from psum'd pressure bits), doubling
        # after clean budget-only exits and halving under pressure. The host
        # only steers the CAP by wall-clock feedback so checkpoint cadence
        # and timeout polling hold.
        adaptive = self._timeout is not None or self._ckpt_every is not None
        budget = max_sync
        budget_cap = min(64, max_sync) if adaptive else 0
        cap_limit = min(self._max_sync_steps, 1 << 30)
        poll_target = None
        if self._ckpt_every is not None:
            poll_target = self._ckpt_every / 4.0
        if self._timeout is not None:
            t = self._timeout / 4.0
            poll_target = t if poll_target is None else min(poll_target, t)
        fin_any, fin_all, fin_all_en = self._finish_when.device_masks(
            self._tprops
        )
        # Spill hysteresis (see engines/tpu_bfs.py): drain to / refill up
        # to a margin below the watermark so spilling runs still get long
        # eras between host round-trips.
        spill_target = max(high_water // 2, high_water - 64 * N * self._quota)
        # Graceful-degradation budget: each recovery doubles every shard
        # table, so a handful of rounds covers any realistic exhaustion.
        regrow_budget = 8

        # Per-shard exchange accounting: the per-era delta of each shard's
        # P_UNIQUE row is the rows that shard accepted from the all_to_all
        # exchange (plus its locally-kept share — ownership routing makes
        # every insert an exchanged row). prev starts at ZERO, not the
        # seeded values, so on a clean run the shard_exchange_rows series
        # sums exactly to the final unique_state_count (seeding is era-0
        # exchange volume by definition). A degraded_regrow reload resets
        # prev to the checkpoint: replayed rows are physically re-exchanged
        # and count again, so the identity is exact only for clean runs.
        flight_prev_unique = np.zeros(N, dtype=np.int64)
        imbalance_warned = False
        stop = False
        # Speculative era pipelining (tentpole; see engines/tpu_bfs.py for
        # the full soundness argument): the block re-derives EVERY
        # host-intervention exit from the chained params rows — count /
        # high_water / grow_limit / GLOBAL rec bits / err (seeded from
        # P_ERR) all close the uniform gate — so a block chained off a
        # host-action boundary is an exact identity no-op. The chain is
        # not entered while any host-ONLY concern (spill-backlog refill,
        # checkpoint cadence, timeout, graceful stop, state-count target)
        # could fire.
        pipeline = self._pipeline and self._target_state_count is None

        def _fuse_lim_now() -> int:
            """Inner-era cap for the NEXT dispatch (P_FUSE_LIM lane):
            degrade fusion to one era whenever a host-only concern needs
            per-era boundaries — spill backlog, a state-count target, or
            checkpoint / timeout cadence at half-elapsed (mirrors
            engines/tpu_bfs.py)."""
            if self._fuse <= 1:
                return 1
            if any(self._spill[s] for s in range(N)):
                return 1
            if self._target_state_count is not None:
                return 1
            now = _time.monotonic()
            if (
                self._ckpt_every is not None
                and now - self._last_ckpt >= self._ckpt_every / 2
            ):
                return 1
            if (
                self._deadline is not None
                and now >= self._deadline - self._timeout / 2
            ):
                return 1
            # Auto-N (engines/common.py): back off when the flight history
            # shows the dispatch gap already amortized.
            return self._fuse_auto_n(self._fuse)

        def consume(vals, fp1_dev, fp2_dev, dd_dev, era_wall, era_budget,
                    spec_in_flight=False):
            """Consume one block result: error recovery, counters,
            discoveries, spill drain, telemetry, checkpoint cadence, and
            stop conditions. Returns False when the era was discarded
            (probe error -> degraded-regrow reload), True otherwise.
            With ``spec_in_flight`` a chained block is still executing on
            device: the checkpoint save is deferred to the next serial
            boundary (the table/queue bindings here are the NEXT block's
            output buffers — pairing this era's heads/counts with them is
            only safe when that block is a no-op, which the caller cannot
            know yet)."""
            nonlocal table, queue, heads, counts, take_caps
            nonlocal per_shard_unique, rec_bits, rec_fp1, rec_fp2
            nonlocal budget, budget_cap, regrow_budget, disc_depth_best
            nonlocal flight_prev_unique, imbalance_warned, stop
            # Inner eras executed by this (possibly fused) dispatch: the
            # fusion tail's k_out lane, uniform across shards.
            n_inner = 1
            if nfuse:
                n_inner = max(1, min(int(vals[0, f_base + 1]), self._fuse))
            err = bool(vals[:, P_ERR].any())
            if not err and self._chaos_probe_error_era is not None and (
                self._metrics.get("eras") >= self._chaos_probe_error_era
            ):
                self._chaos_probe_error_era = None
                err = True
            if err:
                # Graceful degradation (degraded_regrow): the failed era's
                # work is unsound (unresolved inserts dropped states), so
                # discard it — reload the last crash-safe checkpoint,
                # double every shard table, and continue. Without a
                # checkpoint the consumed frontier rows are gone: abort.
                from ..engines.common import checkpoint_generations

                if (
                    self._ckpt_path is None
                    or regrow_budget == 0
                    or not checkpoint_generations(self._ckpt_path)
                ):
                    raise RuntimeError(
                        "visited-table probe budget exhausted despite "
                        "headroom"
                    )
                regrow_budget -= 1
                (
                    table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
                    take_caps, disc_depth_best, per_shard_unique,
                ) = self._load_checkpoint(self._ckpt_path, W)
                flight_prev_unique = np.asarray(
                    per_shard_unique, dtype=np.int64
                )
                with self._metrics.phase("table_grow"):
                    table = self._grow_tables(table)
                self._metrics.inc("degraded_regrow")
                self._metrics.inc("table_growths")
                self._obs_event(
                    "degraded_regrow",
                    frontier=int(counts.sum()),
                    new_tcap=self._tcap,
                )
                if self._memory is not None:
                    self._memory.event(
                        "checkpoint_load", frontier=int(counts.sum())
                    )
                    self._mem_register(table, queue, (rec_fp1, rec_fp2), None)
                return False
            heads = vals[:, P_HEAD].astype(np.int64)
            counts = vals[:, P_COUNT].astype(np.int64)
            take_caps = list(vals[:, P_TAKE_CAP].astype(np.int64))
            # Device-emitted next-era budget (uniform across shards — it is
            # computed from psum'd inputs); the host steers only the cap.
            budget = int(vals[0, P_MAX_STEPS])
            self._metrics.set_gauge("era_step_budget", int(era_budget))
            # Wall feedback steers the PER-ERA budget cap; under fusion the
            # dispatch wall covers n_inner eras, so feed back the mean.
            per_era_wall = era_wall / n_inner
            if poll_target is not None and era_wall > 0.0:
                if per_era_wall < poll_target / 2 and budget_cap < cap_limit:
                    budget_cap = min(budget_cap * 2, cap_limit)
                elif per_era_wall > poll_target and budget_cap > 64:
                    budget_cap = max(budget_cap // 2, 64)
            per_shard_unique = list(vals[:, P_UNIQUE].astype(np.int64))
            self._unique = int(sum(per_shard_unique))
            self._state_count += int(vals[:, P_GEN].sum())
            self._max_depth = max(self._max_depth, int(vals[:, P_MAXD].max()))
            self._metrics.inc("eras", n_inner)
            self._metrics.inc("steps", int(vals[:, P_STEPS].sum()))
            self._metrics.inc("states_generated", int(vals[:, P_GEN].sum()))
            self._metrics.set_gauge("take_cap", int(min(take_caps)))

            if self._cov:
                # The coverage tail is psum'd on device — every shard row
                # carries the global era deltas; read row 0.
                base = P_LEN
                cov_row = vals[0]
                cov_acc = self._coverage
                cov_acc.record_action_counts(cov_row[base : base + A])
                expanded = int(cov_row[base + A + NP_])
                for pi, p in enumerate(self._tprops):
                    cov_acc.record_property_eval(p.name, expanded)
                    cov_acc.record_property_hit(
                        p.name, int(cov_row[base + A + pi])
                    )
                cov_acc.record_depth_counts(
                    cov_row[base + A + NP_ + 1 : base + ncov]
                )

            if self._sampler is not None:
                # Drain every shard's sample tail (un-psum'd, per-shard
                # rows): the global bottom-k is the trivial union of the
                # per-shard drains — the sampler's offer dedups and keeps
                # the k smallest.
                for s in range(N):
                    row = vals[s]
                    occupied = int(row[s_base + 2])
                    if occupied:
                        off = s_base + 4
                        self._sampler.drain_slab(
                            row[off : off + sk2],
                            row[off + sk2 : off + 2 * sk2],
                            row[off + 2 * sk2 : off + 3 * sk2],
                            row[off + 3 * sk2 : off + 4 * sk2],
                            occupied,
                        )

            block_bits = int(np.bitwise_or.reduce(vals[:, P_REC]))
            if block_bits:
                fp1 = np.asarray(fp1_dev)
                fp2 = np.asarray(fp2_dev)
                depths = np.asarray(dd_dev)  # [N, NP_]
                if nfuse:
                    # Per-shard inner-era index of each best discovery
                    # (fusion tail): the serial driver's tie-break is
                    # lexicographic (depth, era, shard) — the device fold
                    # kept the per-shard (depth, era) lexmin, lexsort
                    # recovers the global serial winner across shards.
                    e_off = f_base + 2 + 4 * self._fuse
                    disc_era = vals[:, e_off : e_off + NP_].astype(np.int64)
                for pi, p in enumerate(self._tprops):
                    if not (block_bits >> pi) & 1:
                        continue
                    if nfuse:
                        s = int(
                            np.lexsort(
                                (
                                    np.arange(N),
                                    disc_era[:, pi],
                                    depths[:, pi].astype(np.int64),
                                )
                            )[0]
                        )
                    else:
                        s = int(np.argmin(depths[:, pi]))
                    d = int(depths[s, pi])
                    if (
                        p.name not in self._discovery_fps
                        or d < disc_depth_best.get(p.name, 1 << 62)
                    ):
                        disc_depth_best[p.name] = d
                        self._discovery_fps[p.name] = combine64(
                            fp1[s, pi], fp2[s, pi]
                        )
                rec_bits |= block_bits

            # Per-shard spill: drain to the hysteresis margin, ONE stacked
            # download per shard.
            spilled = 0
            for s in range(N):
                if counts[s] > high_water:
                    k = int(counts[s] - spill_target)
                    idx = jnp.asarray(
                        (heads[s] + counts[s] - k + np.arange(k))
                        & (self._qcap - 1)
                    )
                    with self._metrics.phase("spill"):
                        big = np.asarray(
                            jnp.stack(
                                [queue[t][s, idx] for t in range(W)], axis=1
                            )
                        )
                    for off in range(0, k, N * self._quota):
                        self._spill[s].append(big[off : off + N * self._quota])
                    counts[s] -= k
                    spilled += k
                    self._metrics.inc("spill_rows", k)
                    self._max_depth = max(
                        self._max_depth, int(big[:, S + 1].max())
                    )
            if spilled and self._memory is not None:
                self._memory.staging(
                    self._spill_host_bytes(), event="spill", rows=int(spilled)
                )

            # Per-shard telemetry off the same per-shard params rows (zero
            # extra device reads): labeled counter series (Prometheus
            # `{shard="k"}` via SHARD_SERIES_LABELS), per-shard gauges, and
            # the cross-shard frontier imbalance gauge. The labeled sums
            # equal the engine totals exactly — same vals columns.
            shard_unique = np.asarray(per_shard_unique, dtype=np.int64)
            exchange = np.maximum(0, shard_unique - flight_prev_unique)
            flight_prev_unique = shard_unique
            shards_rec = {}
            for s in range(N):
                key = str(s)
                self._metrics.inc_labeled(
                    "shard_steps", key, int(vals[s, P_STEPS])
                )
                self._metrics.inc_labeled(
                    "shard_states_generated", key, int(vals[s, P_GEN])
                )
                self._metrics.inc_labeled(
                    "shard_exchange_rows", key, int(exchange[s])
                )
                shards_rec[key] = {
                    "frontier": int(counts[s]),
                    "load_factor": round(
                        int(shard_unique[s]) / max(1, self._tcap), 4
                    ),
                    "exchange_rows": int(exchange[s]),
                }
            self._metrics.set_gauge(
                "shard_frontier_rows",
                {k: v["frontier"] for k, v in shards_rec.items()},
            )
            self._metrics.set_gauge(
                "shard_load_factor",
                {k: v["load_factor"] for k, v in shards_rec.items()},
            )
            occ_mean = float(counts.mean())
            imbalance = (
                float(counts.max()) / occ_mean if occ_mean > 0 else 1.0
            )
            self._metrics.set_gauge("shard_imbalance", round(imbalance, 4))
            # Skew on a near-empty frontier (the drain phase) is noise —
            # only warn when the mean shard holds at least a full take.
            if (
                imbalance > SHARD_IMBALANCE_WARN
                and occ_mean >= self._chunk
                and not imbalance_warned
            ):
                imbalance_warned = True
                from ..obs.log import get_logger

                get_logger("parallel.mesh").warning(
                    "cross-shard frontier imbalance: the busiest shard "
                    "holds several times the mean occupancy (ownership "
                    "hashing is skewed for this model)",
                    imbalance=round(imbalance, 2),
                    max_rows=int(counts.max()),
                    mean_rows=round(occ_mean, 1),
                )

            self._obs_event(
                "era",
                frontier=int(counts.sum()),
                load_factor=round(
                    max(per_shard_unique) / max(1, self._tcap), 4
                ),
                take_cap=int(min(take_caps)),
                steps=int(vals[:, P_STEPS].sum()),
                generated=int(vals[:, P_GEN].sum()),
                spill_rows=spilled,
            )

            if not spec_in_flight and self._ckpt_path is not None and (
                self._ckpt_every is not None
                and _time.monotonic() - self._last_ckpt >= self._ckpt_every
            ):
                self._save_checkpoint(
                    table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
                    take_caps, disc_depth_best, per_shard_unique,
                )

            # Flight record after spill/checkpoint so this era's host work
            # lands in its own host_gap. Under pipelining era_wall is the
            # MARGINAL readback-to-readback span, so the summary still
            # reconciles with the external wall clock (obs/flight.py
            # overlap-aware accounting). A fused dispatch hands the
            # per-inner-era attribution lanes through so the recorder can
            # split it into n_inner exact records.
            inner = None
            if nfuse:
                F = self._fuse
                off = f_base + 2
                fsteps = vals[:, off : off + F].astype(np.int64)
                fgen = vals[:, off + F : off + 2 * F].astype(np.int64)
                funiq = vals[:, off + 2 * F : off + 3 * F].astype(np.int64)
                fcnt = vals[:, off + 3 * F : off + 4 * F].astype(np.int64)
                inner = []
                for j in range(n_inner):
                    # Reconstruct each era's post-era per-shard unique by
                    # peeling back the later eras' per-shard deltas.
                    u_after = shard_unique - funiq[
                        :, j + 1 : n_inner
                    ].sum(axis=1)
                    inner.append(
                        {
                            "steps": int(fsteps[:, j].sum()),
                            "generated": int(fgen[:, j].sum()),
                            "unique": int(u_after.sum()),
                            "frontier": int(fcnt[:, j].sum()),
                            "load_factor": round(
                                int(u_after.max()) / max(1, self._tcap), 4
                            ),
                        }
                    )
            self._flight_record(
                device_era_secs=era_wall,
                steps=int(vals[:, P_STEPS].sum()),
                generated=int(vals[:, P_GEN].sum()),
                unique=self._unique,
                frontier=int(counts.sum()),
                load_factor=round(
                    max(per_shard_unique) / max(1, self._tcap), 4
                ),
                take_cap=int(min(take_caps)),
                spill_rows=spilled,
                shards=shards_rec,
                grow_rows=int(max(per_shard_unique)),
                inner=inner,
            )

            if self._finish_matched(self._discovery_fps):
                stop = True
            elif (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                stop = True
            elif self._timed_out():
                stop = True
            elif self._ckpt_stop.is_set():
                # Graceful-stop request (SIGTERM/SIGINT flush): the final
                # checkpoint below captures this era boundary — the same
                # path timeout/target stops take.
                self._metrics.set_gauge("interrupted", 1)
                stop = True
            return True

        while not stop and (
            counts.sum() > 0 or any(self._spill[s] for s in range(N))
        ):
            # Refill spills per shard (one batched upload per shard).
            for s in range(N):
                refill = []
                refill_rows = 0
                # Spill blocks are <= N*quota rows and spill_target >=
                # 1.5*N*quota (qcap >= 4*N*quota in __init__), so an empty
                # shard always refills at least one block.
                while self._spill[s] and (
                    counts[s] + refill_rows + self._spill[s].peek_rows()
                    <= spill_target
                ):
                    refill.append(self._spill[s].pop())
                    refill_rows += len(refill[-1])
                if refill:
                    rows = np.concatenate(refill, axis=0)
                    k = len(rows)
                    idx = jnp.asarray(
                        (heads[s] + counts[s] + np.arange(k)) & (self._qcap - 1)
                    )
                    with self._metrics.phase("refill"):
                        rows_dev = jnp.asarray(rows)
                        queue = tuple(
                            queue[t].at[s, idx].set(rows_dev[:, t])
                            for t in range(W)
                        )
                    counts[s] += k
                    self._metrics.inc("refill_rows", k)
                    if self._memory is not None:
                        self._memory.staging(
                            self._spill_host_bytes(),
                            event="refill",
                            rows=int(k),
                        )
            if counts.sum() == 0:
                if any(self._spill[s] for s in range(N)):
                    # Unreachable by the block-size invariant above; loud
                    # beats silently dropping spilled states.
                    raise RuntimeError("empty frontier with stranded spill")
                break

            # Grow ALL shard tables together when any shard nears the load
            # limit (uniform shapes keep one compiled program).
            grew = False
            while (
                max(per_shard_unique) + N * self._quota
                > vs.MAX_LOAD * self._tcap
            ):
                with self._metrics.phase("table_grow"):
                    table = self._grow_tables(table)
                self._metrics.inc("table_growths")
                grew = True
            # Elastic re-shard (ISSUE 20; see engines/tpu_bfs.py): when
            # the forecaster projects growth within the horizon, take the
            # uniform doubling NOW at this host-owned boundary. At most
            # one proactive doubling per era — the forecast refreshes at
            # every _flight_record.
            if (
                self._proactive_reshard_due()
                and self._metrics.get("eras") != self._reshard_last_era
            ):
                self._reshard_last_era = self._metrics.get("eras")
                with self._metrics.phase("table_grow"):
                    table = self._grow_tables(table)
                self._metrics.inc("table_growths")
                self._metrics.inc("reshard_proactive")
                self._obs_event(
                    "reshard_proactive", table_capacity_per_shard=self._tcap
                )
                grew = True
            if grew:
                self._mem_register(table, queue, (rec_fp1, rec_fp2), None)
            grow_limit = max(
                0, int(vs.MAX_LOAD * self._tcap) - N * self._quota
            )

            max_steps = min(budget, budget_cap) if adaptive else budget
            if self._target_state_count is not None:
                remaining = max(
                    0, self._target_state_count - self._state_count
                )
                max_steps = max(
                    1, min(max_steps, 1 + remaining // max(1, N * C * A))
                )

            params_np = np.zeros(
                (N, P_LEN + ncov + nsamp + nfuse), dtype=np.uint32
            )
            for s in range(N):
                params_np[s, :P_LEN] = [
                    heads[s], counts[s], per_shard_unique[s], rec_bits,
                    depth_limit, grow_limit, high_water, max_steps,
                    0, 0, 0, 0, take_caps[s],
                    fin_any, fin_all, fin_all_en, budget_cap,
                ]
            if self._sample_k:
                t1, t2 = self._sampler.threshold_parts()
                params_np[:, s_base] = t1
                params_np[:, s_base + 1] = t2
                last_thresh = (t1, t2)
            if nfuse:
                params_np[:, f_base] = _fuse_lim_now()
            _era_w0 = _time.monotonic()
            table, queue, rec_fp1, rec_fp2, params, disc_depth = (
                self._block.serial(
                    table, queue, rec_fp1, rec_fp2, jnp.asarray(params_np)
                )
            )
            self._metrics.inc("dispatches")
            if self._memory is not None:
                self._memory.attach("packed_params", params)
                self._memory.attach("coverage_slab", params)
                self._memory.attach("sample_slab", params)
                if self._fuse > 1:
                    self._memory.attach("fusion_tail", params)
            cur_budget = max_steps
            # K-deep speculative chain (oldest first): chain[i] holds the
            # i-th chained block's OUTPUT handles (params, rec_fp1,
            # rec_fp2, disc_depth) plus its dispatch timestamp. Unlike the
            # single-device engine, each entry pairs the era with its OWN
            # fp/depth arrays — the mesh discovery path reads them.
            chain: List[Tuple[Any, Any, Any, Any, float]] = []
            while True:
                # Top up the chain while every host-only concern is quiet:
                # each chained block launches off the newest on-device
                # state with its predecessor's readback queued
                # (non-blocking) behind the ones already in flight.
                while (
                    pipeline
                    and len(chain) < self._chain_depth
                    and not any(self._spill[s] for s in range(N))
                    and not self._ckpt_stop.is_set()
                    and not self._timed_out()
                    and not self._proactive_reshard_due()
                    and (
                        self._ckpt_every is None
                        or _time.monotonic() - self._last_ckpt
                        < self._ckpt_every
                    )
                ):
                    if chain:
                        src_p, src_f1, src_f2 = chain[-1][:3]
                    else:
                        src_p, src_f1, src_f2 = params, rec_fp1, rec_fp2
                    # Kick the pending readback without blocking, then
                    # chain off the on-device state (the chain program
                    # variant pins the params operand, so every readback
                    # source stays live).
                    try:
                        src_p.copy_to_host_async()
                    except AttributeError:
                        pass  # CPU backend: the copy below is free anyway
                    t0 = _time.monotonic()
                    table, queue, c_f1, c_f2, c_p, c_dd = self._block.chain(
                        table, queue, src_f1, src_f2, src_p
                    )
                    self._metrics.inc("dispatches")
                    self._metrics.inc("spec_dispatch")
                    chain.append((c_p, c_f1, c_f2, c_dd, t0))
                    if len(chain) > self._chain_max:
                        self._chain_max = len(chain)
                        self._metrics.set_gauge(
                            "spec_chain_depth", self._chain_max
                        )
                if not chain:
                    # Serial boundary: block on the readback, consume with
                    # full host services (spill drain, checkpoint, stop).
                    with self._metrics.phase("readback"):
                        vals = np.asarray(params)  # one download per block
                    era_wall = _time.monotonic() - _era_w0
                    self._metrics.add_phase("device_era", era_wall)
                    self._metrics.observe("era_secs", era_wall)
                    consume(vals, rec_fp1, rec_fp2, disc_depth, era_wall,
                            cur_budget)
                    break
                with self._metrics.phase("readback"):
                    vals = np.asarray(params)
                era_wall = _time.monotonic() - _era_w0
                self._metrics.add_phase("device_era", era_wall)
                self._metrics.observe("era_secs", era_wall)
                ok = consume(vals, rec_fp1, rec_fp2, disc_depth, era_wall,
                             cur_budget, spec_in_flight=True)
                if not ok:
                    # Probe error -> checkpoint reload. The real-err case
                    # makes every chained block a guaranteed no-op (the
                    # carried P_ERR closes the gate); a chaos-faked err may
                    # have let them run real work — either way the reload
                    # discards the whole chain. Quiesce each dispatch
                    # before dropping its handles so the reload's uploads
                    # don't race the blocks.
                    for c_p, _f1, _f2, _dd, _t0 in chain:
                        np.asarray(c_p)
                        self._metrics.inc("spec_wasted")
                    chain.clear()
                    break
                cur_budget = budget
                if (
                    not stop
                    and counts.sum() > 0
                    and not any(self._spill[s] for s in range(N))
                    and max(per_shard_unique) + N * self._quota
                    <= vs.MAX_LOAD * self._tcap
                    and not self._proactive_reshard_due()
                    and (
                        self._sampler is None
                        or self._sampler.threshold_parts() == last_thresh
                    )
                ):
                    # Clean boundary: the oldest chained block IS the next
                    # era and has been executing since this readback
                    # completed (marginal readback-to-readback timing).
                    # (A tightened sampling threshold also breaks the chain
                    # — stale thresholds are sound but over-capture; the
                    # serial rebuild below uploads the fresh one.)
                    # grow_limit check mirrors the proactive-grow trigger
                    # above, so a growth boundary always falls through to
                    # the drain below.
                    params, rec_fp1, rec_fp2, disc_depth, _t0 = chain.pop(0)
                    _era_w0 = _time.monotonic()
                    continue
                # Host action at this boundary (stop request, drained
                # frontier, spill backlog, or table growth due): drain the
                # chain in order. Every DEVICE-visible case makes each
                # remaining block an identity no-op (see the soundness note
                # above); peek its steps to tell. steps > 0 means a
                # host-ONLY stop (timeout/SIGTERM) landed mid-chain while
                # the device legitimately ran — consume that real, sound
                # work before stopping.
                while chain:
                    c_p, c_f1, c_f2, c_dd, c_t0 = chain.pop(0)
                    svals = np.asarray(c_p)  # blocking: quiesce
                    # Keep the rebound handles either way — a no-op's
                    # outputs are value-equal to its inputs, and later
                    # chained blocks feed off these buffers.
                    params, rec_fp1, rec_fp2, disc_depth = (
                        c_p, c_f1, c_f2, c_dd,
                    )
                    if int(svals[:, P_STEPS].sum()) == 0:
                        self._metrics.inc("spec_wasted")
                        continue
                    era_wall = _time.monotonic() - c_t0
                    self._metrics.add_phase("device_era", era_wall)
                    self._metrics.observe("era_secs", era_wall)
                    ok = consume(svals, c_f1, c_f2, c_dd, era_wall,
                                 cur_budget, spec_in_flight=bool(chain))
                    cur_budget = budget
                    if not ok:
                        for d_p, _f1, _f2, _dd, _t0 in chain:
                            np.asarray(d_p)
                            self._metrics.inc("spec_wasted")
                        chain.clear()
                        break
                break

        if self._ckpt_path is not None:
            self._save_checkpoint(
                table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
                take_caps, disc_depth_best, per_shard_unique,
            )
        # Any disk-tier spools are dead weight past this point (a resume
        # rebuilds the stacks from the checkpoint's spill arrays).
        for s in range(N):
            self._spill[s].close()
        # Mega-dispatch gauges: deepest speculative chain reached and the
        # realized fusion ratio (device eras per host dispatch — 1.0 when
        # neither chaining nor fusion engaged).
        self._metrics.set_gauge("spec_chain_depth", self._chain_max)
        n_disp = max(1, self._metrics.get("dispatches"))
        self._metrics.set_gauge(
            "fused_eras_per_dispatch",
            round(self._metrics.get("eras") / n_disp, 3),
        )
        self._profile_stages(table, queue)
        self._table_dev = table
        if self._memory is not None:
            # Final era's live buffers, for the post-run nbytes parity.
            led = self._memory.ledger
            led.attach("visited_table", table)
            led.attach("frontier_queue", queue)
            led.attach("record_fps", (rec_fp1, rec_fp2))
        return

    def _profile_stages(self, table, queue) -> None:
        """Post-run per-stage attribution of device_era wall time across
        the mesh (CheckerBuilder.stage_profile(); obs/stageprof.py). The
        kernels run every shard in lockstep, so the attributed `stage_*`
        phases are GLOBAL times; `steps` is normalized to lockstep era
        iterations (total steps / n_shards). Never fatal."""
        if not self._stage_profile:
            return
        try:
            import jax.numpy as jnp

            from ..obs import stageprof

            steps = int(self._metrics.get("steps")) // max(1, self.n_shards)
            era_secs = self._metrics.phase_ms().get("device_era", 0.0) / 1e3
            if steps <= 0 or era_secs <= 0.0:
                return
            kernels = _build_mesh_stage_kernels(
                self.tm, self._tprops, self._chunk, self._qcap,
                self.n_shards, self._quota, self.mesh, "shards",
                self._stage_iters,
            )
            seeds = jnp.arange(1, self.n_shards + 1, dtype=jnp.uint32)
            with self._metrics.phase("profiler_overhead"):
                timed = stageprof.measure_stage_kernels(
                    {
                        name: (fn, (table, queue, seeds))
                        for name, fn in kernels.items()
                    },
                    self._stage_iters,
                )
            stageprof.attribute_stages(
                self._metrics, timed, era_secs, steps, self._stage_iters
            )
        except Exception as exc:
            from ..obs.log import get_logger

            self._metrics.set_gauge("stage_profile_error", repr(exc)[:200])
            get_logger("parallel.mesh").warning(
                "stage profiling failed (run results unaffected)",
                error=repr(exc),
            )

    # -- checkpoint/resume --------------------------------------------------

    def _save_checkpoint(
        self, table, queue, heads, counts, rec_bits, rec_fp1, rec_fp2,
        take_caps, disc_depth_best, per_shard_unique,
    ) -> None:
        """Serialize the full sharded engine state (per-shard tables, rings,
        spill lists, take_caps, counters) to one .npz via the crash-safe
        protocol in engines/common.py (tmp + fsync + generation rotation +
        rename, content digest in the meta). Mirrors the single-device
        engine's checkpoint (engines/tpu_bfs.py); the reference has no
        equivalent."""
        import time as _time

        from ..engines.common import checkpoint_meta, save_checkpoint_tiered
        from ..ops import visited_set as vs

        meta = checkpoint_meta(
            self.tm,
            self._tprops,
            n_shards=self.n_shards,
            ring_lanes=len(queue),
            qcap=self._qcap,
            tcap=self._tcap,
            chunk=self._chunk,
            quota=self._quota,
            max_probes=vs.MAX_PROBES,
            rec_bits=rec_bits,
            state_count=self._state_count,
            unique=self._unique,
            max_depth=self._max_depth,
            discovery_fps={k: str(v) for k, v in self._discovery_fps.items()},
            disc_depth_best={k: int(v) for k, v in disc_depth_best.items()},
            per_shard_unique=[int(u) for u in per_shard_unique],
            take_caps=[int(t) for t in take_caps],
            sampler=(
                self._sampler.export_state()
                if self._sampler is not None
                else None
            ),
        )
        arrays = {
            "heads": np.asarray(heads, dtype=np.int64),
            "counts": np.asarray(counts, dtype=np.int64),
            "rec_fp1": np.asarray(rec_fp1),
            "rec_fp2": np.asarray(rec_fp2),
        }
        # On-disk format keeps the four flat lanes (table0..3) per shard;
        # the packed key buffer is split host-side (views, one download).
        keys = np.asarray(table[0])
        cap = keys.shape[1] // 2
        arrays["table0"] = keys[:, :cap]
        arrays["table1"] = keys[:, cap:]
        arrays["table2"] = np.asarray(table[1])
        arrays["table3"] = np.asarray(table[2])
        for w, lane in enumerate(queue):
            arrays[f"queue{w}"] = np.asarray(lane)
        for s in range(self.n_shards):
            for i, blk in enumerate(self._spill[s].iter_blocks()):
                arrays[f"spill_{s}_{i}"] = blk
        # Tiered save (ISSUE 20): a full base when the chain state says so
        # (first save, tcap changed, chain at max), else a delta holding
        # only the table rows inserted since the base — the per-shard
        # lanes flatten into one occupancy vector, so the shared delta
        # protocol applies unchanged.
        self._ckpt_delta = save_checkpoint_tiered(
            self._ckpt_path, meta, arrays,
            state=self._ckpt_delta, tcap=self._tcap,
            keep=self._ckpt_keep, metrics=self._metrics,
        )
        self._last_ckpt = _time.monotonic()

    def _load_checkpoint(self, path: str, W: int):
        import jax.numpy as jnp

        from ..engines.common import (
            load_checkpoint_folded,
            validate_checkpoint_meta,
        )
        from ..ops import visited_set as vs

        # Digest-verified load with automatic fallback to the previous
        # generation when the newest file is truncated/corrupt, folding any
        # surviving delta chain onto the base (engines/common.py).
        data, meta = load_checkpoint_folded(path, metrics=self._metrics)
        validate_checkpoint_meta(
            meta,
            self.tm,
            self._tprops,
            exact={
                "n_shards": self.n_shards,
                "qcap": self._qcap,
                "state_width": self.tm.state_width,
                # The exchange program and spill headroom are compiled
                # around these; a silent mismatch would change behavior
                # mid-run.
                "chunk": self._chunk,
                "quota": self._quota,
                # Ring layout changed in round 5 (hashes no longer carried).
                "ring_lanes": W,
                # The probe cascade is part of the table's on-disk meaning.
                "max_probes": vs.MAX_PROBES,
            },
        )
        self._tcap = meta["tcap"]
        self._state_count = meta["state_count"]
        self._unique = meta["unique"]
        self._max_depth = meta["max_depth"]
        self._discovery_fps = {
            k: int(v) for k, v in meta["discovery_fps"].items()
        }
        if self._sampler is not None and meta.get("sampler"):
            self._sampler.restore_state(meta["sampler"])
        for s in range(self.n_shards):
            blocks = sorted(
                (k for k in data if k.startswith(f"spill_{s}_")),
                key=lambda n: int(n.rsplit("_", 1)[1]),
            )
            self._spill[s].reset(data[k] for k in blocks)
        # A reload invalidates the delta-chain baseline (the resumed run's
        # next save must be a fresh full base).
        self._ckpt_delta = None
        table = (
            jnp.asarray(
                np.concatenate([data["table0"], data["table1"]], axis=1)
            ),
            jnp.asarray(data["table2"]),
            jnp.asarray(data["table3"]),
        )
        queue = tuple(jnp.asarray(data[f"queue{w}"]) for w in range(W))
        return (
            table,
            queue,
            data["heads"].astype(np.int64),
            data["counts"].astype(np.int64),
            meta["rec_bits"],
            jnp.asarray(data["rec_fp1"]),
            jnp.asarray(data["rec_fp2"]),
            list(meta["take_caps"]),
            {k: int(v) for k, v in meta["disc_depth_best"].items()},
            list(meta["per_shard_unique"]),
        )

    @staticmethod
    def _host_insert(table_shard: np.ndarray, h1: int, h2: int) -> None:
        cap = table_shard.shape[0]
        stride = (h2 | 1) & 0xFFFFFFFF
        idx = h1 & (cap - 1)
        while table_shard[idx, 0] != 0 or table_shard[idx, 1] != 0:
            if table_shard[idx, 0] == h1 and table_shard[idx, 1] == h2:
                return
            idx = (idx + stride) & (cap - 1)
        table_shard[idx] = (h1, h2, 0, 0)

    def _per_shard_uniques(self, table_np) -> List[int]:
        return [
            int(((table_np[s, :, 0] != 0) | (table_np[s, :, 1] != 0)).sum())
            for s in range(self.n_shards)
        ]

    def _grow_tables(self, table):
        """Double every shard's capacity with an ON-DEVICE shard_map'd
        rehash — the table never round-trips through the host (round 5;
        the old implementation downloaded, rehashed, and re-uploaded every
        shard, a multi-GB host bounce at real table sizes)."""
        new_cap = self._tcap * 2
        grow = _build_grow(self._tcap, new_cap, self.mesh, "shards")
        table, unres = grow(table)
        if int(np.asarray(unres).sum()) != 0:
            raise RuntimeError("rehash failed; table pathologically full")
        self._tcap = new_cap
        return table

    # -- accessors ----------------------------------------------------------

    def telemetry(self) -> Dict[str, Any]:
        m = self._metrics
        m.set_gauge("n_shards", self.n_shards)
        m.set_gauge("quota", self._quota)
        m.set_gauge("chunk", self._chunk)
        m.set_gauge("table_capacity", self._tcap)
        m.set_gauge(
            "load_factor",
            round(self._unique / max(1, self.n_shards * self._tcap), 4),
        )
        return super().telemetry()

    def unique_state_count(self) -> int:
        return self._unique

    def discoveries(self) -> Dict[str, Path]:
        self.join()
        return {
            name: self._reconstruct(fp)
            for name, fp in list(self._discovery_fps.items())
        }

    def _sample_resolver(self):
        # Device slabs carry only (fp, depth): resolve sampled states
        # lazily via cross-shard parent-pointer reconstruction.
        return self._path_sample_resolver(self._reconstruct)

    def _reconstruct(self, fp64: int) -> Path:
        """Walk parent pointers ACROSS shard tables (owner = h1 % N per
        hop), then re-execute the model along the fingerprint chain."""
        from ..ops import visited_set as vs

        if not hasattr(self, "_table_np"):
            # Split the packed per-shard key buffer into the four flat
            # lanes lookup_parent_np walks (views over one download each).
            keys = np.asarray(self._table_dev[0])
            cap = keys.shape[1] // 2
            self._table_np = [
                keys[:, :cap],
                keys[:, cap:],
                np.asarray(self._table_dev[1]),
                np.asarray(self._table_dev[2]),
            ]
        chain = [fp64]
        cur = fp64
        for _ in range(10_000_000):
            h1, h2 = split64(cur)
            s = h1 % self.n_shards
            shard = tuple(self._table_np[t][s] for t in range(4))
            found, p1, p2 = vs.lookup_parent_np(shard, h1, h2)
            if not found:
                raise RuntimeError(
                    f"fingerprint {cur} missing from shard {s} during "
                    "path reconstruction"
                )
            if p1 == 0 and p2 == 0:
                break
            cur = combine64(p1, p2)
            chain.append(cur)
        chain.reverse()
        return Path.from_fingerprints(self._model, chain)


# Back-compat style helper mirroring the original prototype's interface.
class ShardedBfs:
    """Thin wrapper: build a ShardedBfsChecker from a bare TensorModel."""

    def __init__(self, tm: TensorModel, devices=None, **kw):
        self._tm = tm
        self._devices = devices
        self._kw = kw
        self.checker: Optional[ShardedBfsChecker] = None

    def run(self) -> "ShardedBfs":
        builder = TensorModelAdapter(self._tm).checker()
        self.checker = ShardedBfsChecker(
            builder, devices=self._devices, **self._kw
        )
        self.checker.join()
        return self

    @property
    def state_count(self):
        return self.checker.state_count()

    @property
    def unique_state_count(self):
        return self.checker.unique_state_count()

    @property
    def max_depth(self):
        return self.checker.max_depth()

    @property
    def discovery_fps(self):
        return self.checker._discovery_fps
