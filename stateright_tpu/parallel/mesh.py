"""Sharded batched BFS: the multi-chip engine core.

Design (SURVEY.md §7 step 4, §5 "distributed communication backend"):

  - mesh axis "shards" over N devices,
  - visited table: [N, cap_local, 4] sharded on dim 0 — each device owns
    the fingerprints with h1 % N == its index,
  - frontier queue: [N, qcap_local, S] ring buffers, one per device, holding
    only states that device owns,
  - per step (one `shard_map`-ped XLA program):
      1. each device pops a chunk from its local ring and evaluates
         properties on it (results returned per-device; host merges),
      2. expands successors locally with the model's batched step,
      3. `all_gather`s candidate (state, fingerprint, parent, ebits, depth)
         tuples over the mesh axis — this is the ICI hop, the analogue of
         the reference's cross-thread job market (src/job_market.rs),
      4. keeps only candidates it owns, dedups in-batch, scatter-claims
         into its local table shard, compacts, and appends to its ring.

The all_gather exchange is simple and correct; a sorted all_to_all that
routes each candidate only to its owner is the planned optimization (it
cuts ICI traffic by ~N_devices x).

Initial states are pre-routed to their owners on the host. Queue overflow
raises (size the ring for the model; per-shard spill is future work).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

from ..core import Expectation
from ..fingerprint import combine64, hash_words_jnp, hash_words_np
from ..tensor import TensorModel


def _build_sharded_step(tm: TensorModel, props, chunk: int, n_shards: int, axis: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import frontier as fr
    from ..ops import visited_set as vs
    from ..ops.expand import build_eval_and_expand

    S = tm.state_width
    eval_and_expand = build_eval_and_expand(tm, props, chunk)

    def per_device(table, queue, head, count, depth_limit):
        # Local blocks arrive with a leading length-1 shard dim; drop it.
        # `table` is the 4-lane visited tuple, `queue` the W-lane ring tuple
        # (structure-of-arrays; see ops/visited_set.py for why).
        table = tuple(t[0] for t in table)
        queue = tuple(q[0] for q in queue)
        head = head[0]
        count = count[0]
        depth_limit = depth_limit[0]

        u = jnp.uint32
        me = lax.axis_index(axis).astype(jnp.uint32)
        qcap = queue[0].shape[0]
        qmask = u(qcap - 1)
        take = jnp.minimum(count, u(chunk))
        active = jnp.arange(chunk, dtype=jnp.uint32) < take
        popped, _slots = fr.ring_gather(queue, head, chunk)
        rows = popped[:S]
        row_h1 = popped[S]
        row_h2 = popped[S + 1]
        ebits = popped[S + 2]
        depth = popped[S + 3]

        ex = eval_and_expand(
            rows, row_h1, row_h2, ebits, depth, active, depth_limit
        )
        generated = ex.generated
        max_depth_seen = jnp.max(jnp.where(active, depth, u(0)))
        # Discovery extraction per step is fine here: this program runs once
        # per host call (no device loop), so argmax/max stay off hot paths.
        n_props = len(props)
        if n_props:
            pf = jnp.stack([jnp.any(h) for h in ex.prop_hits])
            sels = [jnp.argmax(h) for h in ex.prop_hits]
            pfp1 = jnp.stack([row_h1[s] for s in sels])
            pfp2 = jnp.stack([row_h2[s] for s in sels])
        else:
            pf = jnp.zeros(0, dtype=bool)
            pfp1 = jnp.zeros(0, dtype=jnp.uint32)
            pfp2 = jnp.zeros(0, dtype=jnp.uint32)

        # --- ICI exchange: gather all candidates, keep what I own -------
        def gather(x):
            return lax.all_gather(x, axis, tiled=True)

        g_flat = tuple(gather(l) for l in ex.flat)
        g_h1 = gather(ex.h1)
        g_h2 = gather(ex.h2)
        g_p1 = gather(ex.parent1)
        g_p2 = gather(ex.parent2)
        g_ebits = gather(ex.child_ebits)
        g_depth = gather(ex.child_depth)
        g_valid = gather(ex.valid)

        # The claim protocol inside vs.insert resolves in-batch duplicates,
        # so ownership filtering is the only pre-insert mask needed.
        mine = g_valid & ((g_h1 % u(n_shards)) == me)
        table, is_new, unresolved, _ovf = vs.insert(
            table, g_h1, g_h2, g_p1, g_p2, mine
        )

        new_count = is_new.sum(dtype=jnp.uint32)
        cand = g_flat + (g_h1, g_h2, g_ebits, g_depth)
        tail = (head + count) & qmask
        queue = fr.ring_scatter(queue, tail, cand, is_new)

        head = (head + take) & qmask
        count = count - take + new_count
        overflow = count > u(qcap)

        def exp(x):
            return jnp.expand_dims(x, 0)

        return (
            tuple(exp(t) for t in table),
            tuple(exp(q) for q in queue),
            exp(head),
            exp(count),
            exp(generated),
            exp(new_count),
            exp(unresolved.sum(dtype=jnp.uint32)),
            exp(max_depth_seen),
            exp(overflow),
            exp(pf),
            exp(pfp1),
            exp(pfp2),
        )

    return per_device


class ShardedBfs:
    """Host driver for the sharded batched BFS across a device mesh."""

    def __init__(
        self,
        tm: TensorModel,
        devices: Optional[List] = None,
        *,
        chunk_size: int = 1024,
        queue_capacity_per_shard: int = 1 << 14,
        table_capacity_per_shard: int = 1 << 16,
        target_max_depth: Optional[int] = None,
    ):
        import jax
        from jax import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        self.tm = tm
        self._props = tm.tensor_properties()
        devices = devices if devices is not None else jax.devices()
        self.n_shards = len(devices)
        self.mesh = Mesh(np.array(devices), ("shards",))
        self._chunk = chunk_size
        self._qcap = queue_capacity_per_shard
        self._tcap = table_capacity_per_shard
        self._target_max_depth = target_max_depth
        if self._qcap & (self._qcap - 1) or self._tcap & (self._tcap - 1):
            raise ValueError("capacities must be powers of two")

        per_device = _build_sharded_step(
            tm, self._props, chunk_size, self.n_shards, "shards"
        )
        spec = P("shards")
        # Prefix specs: the table/queue lane tuples share one spec each.
        self._step = jax.jit(
            shard_map(
                per_device,
                mesh=self.mesh,
                in_specs=(spec,) * 5,
                out_specs=(spec,) * 12,
            ),
            donate_argnums=(0, 1),
        )

        self.state_count = 0
        self.unique_state_count = 0
        self.max_depth = 0
        self.discovery_fps: Dict[str, int] = {}

    def run(self, max_steps: int = 1_000_000) -> "ShardedBfs":
        import jax.numpy as jnp

        tm = self.tm
        N = self.n_shards
        S = tm.state_width

        inits = np.asarray(tm.init_states_array(), dtype=np.uint32)
        init_lanes = tuple(inits[:, i] for i in range(S))
        inb = np.asarray(tm.within_boundary_lanes(np, init_lanes), dtype=bool)
        inits = inits[inb]
        self.state_count = len(inits)
        h1, h2 = hash_words_np(inits)

        init_ebits = 0
        e = 0
        for p in self._props:
            if p.expectation == Expectation.EVENTUALLY:
                init_ebits |= 1 << e
                e += 1

        # Route init states to their owner shards; dedup via host set.
        # Queue lanes: [state lanes | h1 | h2 | ebits | depth].
        W = S + 4
        queue = np.zeros((N, self._qcap, W), dtype=np.uint32)
        queue[:, :, S + 2] = init_ebits
        queue[:, :, S + 3] = 1
        counts = np.zeros(N, dtype=np.uint32)
        table = np.zeros((N, self._tcap, 4), dtype=np.uint32)
        seen = set()
        for i in range(len(inits)):
            owner = int(h1[i]) % N
            queue[owner, counts[owner], :S] = inits[i]
            queue[owner, counts[owner], S] = h1[i]
            queue[owner, counts[owner], S + 1] = h2[i]
            counts[owner] += 1
            fp = combine64(h1[i], h2[i])
            if fp not in seen:
                seen.add(fp)
                # Seed the owner's table directly (host-side, pre-run).
                self._host_insert(table[owner], int(h1[i]), int(h2[i]))
                self.unique_state_count += 1

        table = tuple(jnp.asarray(table[:, :, i]) for i in range(4))
        queue = tuple(jnp.asarray(queue[:, :, i]) for i in range(W))
        head = jnp.zeros(N, dtype=jnp.uint32)
        count = jnp.asarray(counts)
        depth_limit = jnp.full(
            N,
            self._target_max_depth
            if self._target_max_depth is not None
            else 0xFFFFFFFF,
            dtype=jnp.uint32,
        )

        for _ in range(max_steps):
            if int(np.asarray(count).sum()) == 0:
                break
            (
                table,
                queue,
                head,
                count,
                generated,
                new_count,
                unresolved,
                max_depth_seen,
                overflow,
                pf,
                p1,
                p2,
            ) = self._step(table, queue, head, count, depth_limit)
            if bool(np.asarray(overflow).any()):
                raise RuntimeError(
                    "per-shard frontier ring overflow; increase "
                    "queue_capacity_per_shard"
                )
            if int(np.asarray(unresolved).sum()) != 0:
                raise RuntimeError(
                    "visited-table probe budget exhausted; increase "
                    "table_capacity_per_shard"
                )
            self.state_count += int(np.asarray(generated).sum())
            self.unique_state_count += int(np.asarray(new_count).sum())
            self.max_depth = max(self.max_depth, int(np.asarray(max_depth_seen).max()))
            if self._props:
                pf_np = np.asarray(pf)
                p1_np = np.asarray(p1)
                p2_np = np.asarray(p2)
                for i, p in enumerate(self._props):
                    if p.name in self.discovery_fps:
                        continue
                    hits = np.nonzero(pf_np[:, i])[0]
                    if len(hits):
                        d = hits[0]
                        self.discovery_fps[p.name] = combine64(
                            p1_np[d, i], p2_np[d, i]
                        )
        self._table = tuple(np.asarray(t) for t in table)
        return self

    @staticmethod
    def _host_insert(table_shard: np.ndarray, h1: int, h2: int) -> None:
        # Must trace the SAME probe sequence as the device insert (double
        # hashing, stride = h2|1) or device probes will never find
        # host-seeded entries.
        cap = table_shard.shape[0]
        stride = (h2 | 1) & 0xFFFFFFFF
        idx = h1 & (cap - 1)
        while table_shard[idx, 0] != 0 or table_shard[idx, 1] != 0:
            if table_shard[idx, 0] == h1 and table_shard[idx, 1] == h2:
                return
            idx = (idx + stride) & (cap - 1)
        table_shard[idx] = (h1, h2, 0, 0)
