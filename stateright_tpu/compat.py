"""JAX version/platform compatibility shims.

Two hazards live here so every call site shares one vetted answer:

1. `shard_map` moved. jax >= 0.5 exposes it as `jax.shard_map`; on the
   0.4.x line (0.4.37 in this container) it lives at
   `jax.experimental.shard_map.shard_map`. `get_shard_map()` resolves
   whichever exists, once, so the mesh engine imports cannot break on
   either side of the move.

2. Buffer donation is UNSOUND on XLA:CPU when the persistent compilation
   cache is enabled (measured on jax 0.4.37, this container): an
   executable DESERIALIZED from the cache mis-executes donated-buffer
   while-loop programs — in the visited-set claim protocol, 8 of 2,556
   inserted keys landed off their double-hash probe sequence, silently
   breaking dedup (a resumed 2pc-5 run counted 28,003 "uniques" in an
   8,832-state space). Freshly compiled executables are always correct;
   only the cache-hit path corrupts, and only with donation. Donation
   only matters on device backends (it keeps the 2x table/ring footprint
   out of HBM); on CPU the arrays are host RAM and the copy is cheap.
   `donate_argnums_safe(...)` therefore returns the requested argnums on
   TPU/GPU backends and `()` on CPU, keeping the persistent cache (which
   CI relies on for compile wall-clock) sound.
"""

from __future__ import annotations

from typing import Tuple


def get_shard_map():
    """The `shard_map` transform for the installed jax version.

    jax >= 0.5: `jax.shard_map`; jax 0.4.x: `jax.experimental.shard_map`.
    The 0.4.x implementation has no replication rule for `lax.while_loop`
    (the shape of every per-shard era loop here) and must be told to skip
    that static check, so when the resolved transform accepts `check_rep`
    it is pinned False; newer jax dropped the parameter along with the
    limitation.
    """
    import inspect

    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):  # builtins without introspectable sigs
        return sm
    if "check_rep" in params:
        import functools

        return functools.partial(sm, check_rep=False)
    return sm


def donate_argnums_safe(*argnums: int) -> Tuple[int, ...]:
    """`argnums` on device backends, `()` on CPU.

    See the module docstring: deserialized persistent-cache executables
    corrupt donated buffers on XLA:CPU, so donation is only requested
    where it pays (device HBM) and is known sound.
    """
    import jax

    if jax.default_backend() == "cpu":
        return ()
    return tuple(argnums)


def donate_argnums_pinned(
    argnums: Tuple[int, ...], pinned: Tuple[int, ...] = ()
) -> Tuple[int, ...]:
    """`donate_argnums_safe` minus the argnums whose INPUT buffers the
    host may still read after dispatch — the pinned-source analysis for
    speculative era chaining.

    Donating an input aliases its buffer to an output, so the handle the
    caller still holds is dead the moment the dispatch is enqueued. That
    is fine for operands the driver has already consumed (the serial
    dispatch->readback->dispatch path reads every readback before the
    next launch), but a CHAINED dispatch launches while the previous
    era's packed-params readback is still in flight: its params operand
    is exactly that not-yet-consumed output, and donating it would race
    the async device->host copy against the aliased in-place write (JAX
    surfaces the race as a deleted-buffer error on the readback). The
    engines therefore build two jit variants of one era program — a
    serial variant donating the full operand set and a chain variant
    with the readback-pinned argnums excluded — and pick per dispatch.

    ``argnums`` is the full donation set; ``pinned`` the subset whose
    sources an in-flight readback may pin. Returns `()` on CPU exactly
    like `donate_argnums_safe` (same miscompile hazard).
    """
    pin = set(pinned)
    return tuple(a for a in donate_argnums_safe(*argnums) if a not in pin)
