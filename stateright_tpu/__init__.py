"""stateright_tpu: a TPU-native explicit-state model checker for distributed systems.

A brand-new framework with the capabilities of the Rust `stateright` library
(reference: /root/reference): an explicit-state model checker (BFS / DFS /
on-demand / simulation engines) for user-defined transition systems with
always / sometimes / eventually properties, an actor framework whose systems
can be both exhaustively checked and executed on a real network,
linearizability and sequential-consistency testers, symmetry reduction, and an
interactive state-space explorer.

The core exploration loop is re-designed TPU-first: successor generation and
property evaluation are batched `vmap`-style kernels over fixed-width uint32
state encodings, the visited set is an open-addressing hash table living in
device memory, and multi-chip scale comes from sharding the frontier over a
`jax.sharding.Mesh` with XLA collectives (see `stateright_tpu.parallel`).

Public API parity map (reference file:line cited in each module's docstring):
  - Model / Property / Expectation   <-> src/lib.rs:158-338
  - CheckerBuilder / Checker         <-> src/checker.rs:65-578
  - BFS / DFS / simulation / on-demand engines <-> src/checker/{bfs,dfs,simulation,on_demand}.rs
  - Path                             <-> src/checker/path.rs
  - actor framework                  <-> src/actor.rs, src/actor/*
  - semantics (linearizability etc.) <-> src/semantics*
"""

from .core import Expectation, Model, Property, fingerprint
from .checker import Checker, CheckerBuilder, DiscoveryClassification
from .analysis import AnalysisReport, SpecLintError, analyze
from .has_discoveries import HasDiscoveries
from .path import Path
from .report import ReportData, ReportDiscovery, Reporter, WriteReporter
from .visitor import CheckerVisitor, PathRecorder, StateRecorder
from .symmetry import Representative, RewritePlan
from .tensor import TensorModel, TensorModelAdapter, TensorProperty
from .utils import DenseNatMap, VectorClock
from .engines.simulation import Chooser, UniformChooser

__all__ = [
    "AnalysisReport",
    "Checker",
    "CheckerBuilder",
    "CheckerVisitor",
    "Chooser",
    "SpecLintError",
    "analyze",
    "DenseNatMap",
    "DiscoveryClassification",
    "Expectation",
    "HasDiscoveries",
    "Model",
    "Path",
    "PathRecorder",
    "Property",
    "Representative",
    "RewritePlan",
    "VectorClock",
    "ReportData",
    "ReportDiscovery",
    "Reporter",
    "StateRecorder",
    "TensorModel",
    "TensorModelAdapter",
    "TensorProperty",
    "UniformChooser",
    "WriteReporter",
    "fingerprint",
]

__version__ = "0.1.0"
