"""Stable 64-bit state fingerprinting.

Role parity with the reference's seed-stable hashing (src/lib.rs:341-349 and
the fixed-seed `stable::hasher` at src/lib.rs:369-387): fingerprints must be
reproducible across runs, builds, and machines, because discovery traces are
externalized as fingerprint paths and golden tests pin exact values.

Two hash domains, both with fixed seeds:

1. `fingerprint(value)` — arbitrary (host-side) Python model states. The value
   is canonically serialized (order-insensitive for sets/dicts, mirroring the
   reference's order-insensitive `HashableHashSet`/`HashableHashMap` hashing at
   src/util.rs:137-159) and hashed with BLAKE2b-64.

2. `hash_words_np` / `hash_words_jnp` — fixed-width uint32 state rows used by
   the tensor (TPU) engines. The same word-stream mix is implemented for
   numpy (host) and jax.numpy (device) so host and device engines agree on
   every fingerprint bit-for-bit. The mix is an xxhash32-style per-word
   round + avalanche, evaluated twice with independent seeds to form a
   64-bit fingerprint from two 32-bit halves; everything stays in uint32 so
   it runs natively on the TPU VPU (no 64-bit emulation in the hot loop).

Fingerprints are nonzero (reference: Fingerprint = NonZeroU64, src/lib.rs:341);
zero is reserved as the empty-slot sentinel in the device visited table.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import struct
from typing import Any

import numpy as np

# Fixed seeds (stable across runs; arbitrary odd constants of our own).
SEED1 = np.uint32(0x9E3779B1)
SEED2 = np.uint32(0x85EBCA77)

_PRIME2 = 2246822519
_PRIME3 = 3266489917
_PRIME4 = 668265263
_PRIME5 = 374761393

_PERSON = b"srtpu-v1"


# ---------------------------------------------------------------------------
# Canonical serialization for arbitrary host states.
# ---------------------------------------------------------------------------

def _encode(value: Any, out: bytearray) -> None:
    """Append a canonical, type-tagged encoding of `value` to `out`.

    Canonical means: equal values (by our equality semantics) always produce
    identical bytes. Sets and dicts are encoded order-insensitively by sorting
    the element encodings, which mirrors the reference's sorted-pre-hash
    strategy for HashableHashSet/Map (src/util.rs:137-159, 351-374).
    """
    if value is None:
        out += b"N"
    elif value is True:
        out += b"T"
    elif value is False:
        out += b"F"
    elif isinstance(value, enum.Enum):
        out += b"E"
        _encode(type(value).__name__, out)
        _encode(value.name, out)
    elif isinstance(value, (int, np.integer)):
        v = int(value)
        if -(2**63) <= v < 2**63:
            out += b"i"
            out += struct.pack("<q", v)
        else:  # arbitrary precision
            out += b"I"
            b = v.to_bytes((v.bit_length() + 15) // 8, "little", signed=True)
            out += struct.pack("<I", len(b))
            out += b
    elif isinstance(value, (float, np.floating)):
        out += b"f"
        out += struct.pack("<d", float(value))
    elif isinstance(value, str):
        b = value.encode("utf-8")
        out += b"s"
        out += struct.pack("<I", len(b))
        out += b
    elif isinstance(value, (bytes, bytearray)):
        out += b"b"
        out += struct.pack("<I", len(value))
        out += bytes(value)
    elif isinstance(value, (tuple, list)):
        out += b"l"
        out += struct.pack("<I", len(value))
        for item in value:
            _encode(item, out)
    elif isinstance(value, (set, frozenset)):
        out += b"S"
        out += struct.pack("<I", len(value))
        encs = []
        for item in value:
            buf = bytearray()
            _encode(item, buf)
            encs.append(bytes(buf))
        for e in sorted(encs):
            out += e
    elif isinstance(value, dict):
        out += b"D"
        out += struct.pack("<I", len(value))
        encs = []
        for k, v in value.items():
            buf = bytearray()
            _encode(k, buf)
            _encode(v, buf)
            encs.append(bytes(buf))
        for e in sorted(encs):
            out += e
    elif isinstance(value, np.ndarray):
        out += b"A"
        _encode(value.shape, out)
        _encode(value.dtype.str, out)
        out += np.ascontiguousarray(value).tobytes()
    elif dataclasses.is_dataclass(value):
        out += b"O"
        _encode(type(value).__name__, out)
        for field in dataclasses.fields(value):
            if field.metadata.get("skip_fingerprint"):
                continue
            _encode(getattr(value, field.name), out)
    elif hasattr(value, "fingerprint_key"):
        out += b"K"
        _encode(type(value).__name__, out)
        _encode(value.fingerprint_key(), out)
    else:
        raise TypeError(
            f"Cannot canonically fingerprint value of type {type(value).__name__}. "
            "Use dataclasses, builtin containers, or define fingerprint_key()."
        )


def canonical_bytes(value: Any) -> bytes:
    out = bytearray()
    _encode(value, out)
    return bytes(out)


def fingerprint(value: Any) -> int:
    """Stable nonzero 64-bit fingerprint of an arbitrary host-side state.

    Reference role: `fingerprint()` at src/lib.rs:344-349.
    """
    digest = hashlib.blake2b(
        canonical_bytes(value), digest_size=8, person=_PERSON
    ).digest()
    fp = int.from_bytes(digest, "little")
    return fp if fp != 0 else 1


# ---------------------------------------------------------------------------
# Vectorized word-stream hashing for tensor states (numpy + jax twins).
# ---------------------------------------------------------------------------

# The two fingerprint halves MUST be structurally independent mixes, not
# the same mix with different seeds: a seed-only difference leaves the
# halves correlated on structured model states, and the pair degrades far
# below 64 effective bits. Measured on the real 2pc-7 space (round 4): the
# seed-only variant produced 8 h1-collisions among 296,448 states — the
# expected birthday rate for 32 bits — but ONE of those eight ALSO collided
# in h2, i.e. the "64-bit" fingerprint behaved like ~35 bits and silently
# merged two distinct states (the long-standing 296,447 "golden" was this
# bug). h2 therefore absorbs the words in REVERSE order with different
# multipliers and a different rotation; after the fix the full space has
# zero pair collisions and the h1-only collisions remain at the normal
# 32-bit rate.
_H1 = (17, _PRIME3, _PRIME4, _PRIME2, _PRIME3)  # rot, mul, post, fin1, fin2
_H2 = (13, _PRIME2, _PRIME5, _PRIME4, _PRIME5)


def _absorb(xp, word_iter, base_shape, S, seed, params):
    rot, mul, post, fin1, fin2 = params
    acc = xp.zeros(base_shape, dtype=xp.uint32)
    acc = acc + xp.uint32(seed) + xp.uint32(_PRIME5) + xp.uint32(S * 4)
    for w in word_iter:
        acc = acc + w * xp.uint32(mul)
        acc = (acc << xp.uint32(rot)) | (acc >> xp.uint32(32 - rot))
        acc = acc * xp.uint32(post)
    acc = acc ^ (acc >> xp.uint32(15))
    acc = acc * xp.uint32(fin1)
    acc = acc ^ (acc >> xp.uint32(13))
    acc = acc * xp.uint32(fin2)
    acc = acc ^ (acc >> xp.uint32(16))
    return acc


def _hash_words_generic(xp, words, seed, params=_H1, reverse=False):
    """xxhash32-style mix over the trailing axis of a uint32 array.

    words: [..., S] uint32 -> [...] uint32. Identical results for xp=numpy
    and xp=jax.numpy; all arithmetic wraps mod 2**32.
    """
    S = words.shape[-1]
    order = range(S - 1, -1, -1) if reverse else range(S)
    return _absorb(
        xp, (words[..., i] for i in order), words.shape[:-1], S, seed, params
    )


def hash_words_np(words: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Hash uint32 rows -> (h1, h2) uint32 pair; (h1<<32)|h2 is the fingerprint.

    Guaranteed nonzero as a pair: if both halves are zero, h2 is forced to 1,
    matching the NonZeroU64 fingerprint invariant (src/lib.rs:341-349).
    """
    words = np.asarray(words, dtype=np.uint32)
    with np.errstate(over="ignore"):
        h1 = _hash_words_generic(np, words, SEED1)
        h2 = _hash_words_generic(np, words, SEED2, _H2, reverse=True)
    both_zero = (h1 == 0) & (h2 == 0)
    h2 = np.where(both_zero, np.uint32(1), h2)
    return h1, h2


def hash_words_jnp(words):
    """JAX twin of `hash_words_np` (jit-friendly; uint32 all the way)."""
    import jax.numpy as jnp

    words = words.astype(jnp.uint32)
    h1 = _hash_words_generic(jnp, words, int(SEED1))
    h2 = _hash_words_generic(jnp, words, int(SEED2), _H2, reverse=True)
    both_zero = (h1 == 0) & (h2 == 0)
    h2 = jnp.where(both_zero, jnp.uint32(1), h2)
    return h1, h2


def _hash_lanes_generic(xp, lanes, seed, params=_H1, reverse=False):
    """Same mix as `_hash_words_generic`, but over a sequence of 1-D lane
    arrays (structure-of-arrays layout) instead of the trailing axis of one
    2-D array. lanes[i][n] == words[n, i] implies identical hashes — the two
    layouts are interchangeable bit-for-bit.

    The SoA form is the TPU-native one: each lane is a dense [N] vector, so
    the mix is pure elementwise VPU work with no strided minor-axis reads
    (a [N, S] row layout with small S wastes the 8x128 vector tiles).
    """
    S = len(lanes)
    seq = reversed(lanes) if reverse else lanes
    return _absorb(xp, seq, lanes[0].shape, S, seed, params)


def hash_lanes_np(lanes) -> tuple[np.ndarray, np.ndarray]:
    """SoA twin of `hash_words_np`: hash a sequence of uint32 lane arrays."""
    lanes = [np.asarray(l, dtype=np.uint32) for l in lanes]
    with np.errstate(over="ignore"):
        h1 = _hash_lanes_generic(np, lanes, SEED1)
        h2 = _hash_lanes_generic(np, lanes, SEED2, _H2, reverse=True)
    both_zero = (h1 == 0) & (h2 == 0)
    h2 = np.where(both_zero, np.uint32(1), h2)
    return h1, h2


def hash_lanes_jnp(lanes):
    """JAX twin of `hash_lanes_np`."""
    import jax.numpy as jnp

    lanes = [l.astype(jnp.uint32) for l in lanes]
    h1 = _hash_lanes_generic(jnp, lanes, int(SEED1))
    h2 = _hash_lanes_generic(jnp, lanes, int(SEED2), _H2, reverse=True)
    both_zero = (h1 == 0) & (h2 == 0)
    h2 = jnp.where(both_zero, jnp.uint32(1), h2)
    return h1, h2


def combine64(h1, h2) -> int:
    """Combine a (h1, h2) uint32 pair into the canonical 64-bit fingerprint int."""
    return (int(h1) << 32) | int(h2)


def split64(fp: int) -> tuple[int, int]:
    return (fp >> 32) & 0xFFFFFFFF, fp & 0xFFFFFFFF
