"""Explorer web service: a JSON API + SPA over an on-demand checker.

Reference parity: src/checker/explorer.rs. Routes:

  - ``GET /``, ``/app.css``, ``/app.js`` — the bundled single-page UI;
  - ``GET /.status`` — checker progress + per-property discovery paths
    (StatusView, explorer.rs:15-24);
  - ``GET /metrics`` (alias ``/.metrics``) — live JSON telemetry snapshot
    (counters + the engine's metrics registry, obs/metrics.py) feeding the
    dashboard panel's states/sec sparkline and gauges — beyond the
    reference, which has no runtime observability surface;
  - ``GET /metrics?format=prometheus`` (alias ``/metrics.prom``) — the
    same snapshot in the Prometheus text exposition format
    (``stateright_``-prefixed, text/plain; version=0.0.4), so a scraper
    can point straight at a running Explorer;
  - ``GET /coverage`` (alias ``/.coverage``) — the run's coverage
    snapshot (obs/coverage.py): per-action fire counts, dead actions,
    depth histogram, per-property eval/hit counts — feeding the
    dashboard's action bar chart + depth histogram panel;
  - ``GET /flight`` (alias ``/.flight``) — the run's flight recording
    (obs/flight.py): the retained per-era records (device_era vs
    host_gap wall split, frontier occupancy, load factor, spill/refill
    volumes) plus the run-level summary — feeding the dashboard's
    flight timeline panel;
  - ``GET /space`` (alias ``/.space``) — the run's space profile
    (obs/sample.py): the deterministic bottom-k state sample rendered
    into per-field value sketches, depth/action exemplars, packing
    saturation warnings, and the KMV state-count estimate — feeding
    the dashboard's space panel;
  - ``GET /memory`` (alias ``/.memory``) — the run's memory-ledger
    snapshot (obs/memory.py): per-component device residency with
    shapes/dtypes, growth events, live headroom, the forecaster's
    eras-to-exhaustion projection, and the early warning once one has
    fired — feeding the dashboard's memory panel;
  - ``GET /events`` — Server-Sent Events stream (text/event-stream):
    ``span`` events as the checker's spans complete (obs/spans.py) and
    periodic ``metrics`` events carrying the numeric telemetry deltas
    since the previous tick. ``?limit=N`` closes after N span events,
    ``?duration=SECS`` after a wall-clock budget, ``?replay=N`` seeds
    the stream with the last N already-recorded spans — together they
    make the stream bounded for tests/CI;
  - ``GET /.explain/{fp}/{fp}/...`` — counterexample forensics for the
    state path named by the fingerprints: per-step action, field-level
    state diff, and property-predicate flips (`Path.explain_steps`);
  - ``GET /.states/{fp}/{fp}/...`` — walk the state space by fingerprint
    path: returns the successor `StateView`s of the path's final state,
    asking the on-demand checker to expand that frontier node in the
    background (explorer.rs:224-320);
  - ``POST /.runtocompletion`` — switch the checker to exhaustive search.

A snapshot visitor records a recently visited path every ~4 seconds so the
UI can show live activity (explorer.rs:60-94).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path as FsPath
from typing import Any, Dict, List, Optional

from ..checker import Checker, CheckerBuilder
from ..core import Model
from ..obs.log import get_logger
from ..obs.spans import SpanRecorder
from ..path import Path

_log = get_logger("explorer.server")

_UI_DIR = FsPath(__file__).parent / "ui"
_SNAPSHOT_REFRESH_SECS = 4.0  # explorer.rs:90-93
_SSE_METRICS_INTERVAL_SECS = 1.0


def numeric_leaves(snapshot: Dict, prefix: str = "") -> Dict[str, float]:
    """Flatten a telemetry snapshot to its numeric leaves
    (``phase_ms.device_era`` style dotted keys) — the unit the /events
    ``metrics`` delta events diff against the previous tick."""
    out: Dict[str, float] = {}
    for key, value in snapshot.items():
        dotted = f"{prefix}{key}"
        if isinstance(value, bool):
            continue
        if isinstance(value, (int, float)):
            out[dotted] = value
        elif isinstance(value, dict):
            out.update(numeric_leaves(value, dotted + "."))
    return out


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Shared JSON-over-HTTP plumbing for this package's services (the
    Explorer here; the run service in serve/http.py). Subclasses implement
    `do_GET`/`do_POST` on top of `_send_json` / `_read_json`."""

    def log_message(self, fmt, *args):
        pass  # quiet

    def _send(self, code: int, body: bytes, content_type: str):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, payload, code=200):
        self._send(code, json.dumps(payload).encode(), "application/json")

    def _read_json(self):
        """The request body parsed as JSON; {} when empty, None (after a
        400 reply) when unparsable."""
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except ValueError:
            self._send_json({"error": "request body is not valid JSON"}, 400)
            return None

    # ---- Server-Sent Events (GET /events on the Explorer and serve) ----

    def _sse_emit(self, event: str, payload) -> None:
        data = json.dumps(payload)
        self.wfile.write(f"event: {event}\ndata: {data}\n\n".encode())
        self.wfile.flush()

    def _serve_sse(self, recorder, query: str = "", telemetry=None) -> None:
        """Stream ``span`` events (completions fanned out by a
        SpanRecorder subscription) and periodic ``metrics`` events (the
        numeric telemetry leaves that changed since the last tick).

        Bounding knobs so tests/CI can consume a finite stream:
        ``?limit=N`` (close after N span events), ``?duration=SECS``
        (wall-clock budget), ``?replay=N`` (seed with the last N spans
        already recorded — they count toward the limit). A disconnected
        client just ends the stream; it never wedges the recorder
        because the subscription queue drops when full."""
        limit: Optional[int] = None
        duration: Optional[float] = None
        replay = 0
        for part in query.split("&"):
            name, _, value = part.partition("=")
            try:
                if name == "limit":
                    limit = max(0, int(value))
                elif name == "duration":
                    duration = max(0.0, float(value))
                elif name == "replay":
                    replay = max(0, int(value))
            except ValueError:
                pass

        sub = recorder.subscribe() if recorder is not None else None
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()

            deadline = None if duration is None else time.time() + duration
            sent = 0
            last_leaves: Dict[str, float] = {}
            last_tick = 0.0

            if replay and recorder is not None:
                for span in list(recorder.spans())[-replay:]:
                    if limit is not None and sent >= limit:
                        break
                    self._sse_emit("span", span)
                    sent += 1

            while True:
                now = time.time()
                if deadline is not None and now >= deadline:
                    break
                if limit is not None and sent >= limit:
                    break
                if telemetry is not None and now - last_tick >= _SSE_METRICS_INTERVAL_SECS:
                    last_tick = now
                    leaves = numeric_leaves(telemetry())
                    changed = {
                        k: v
                        for k, v in leaves.items()
                        if last_leaves.get(k) != v
                    }
                    last_leaves = leaves
                    if changed or not sent:
                        self._sse_emit(
                            "metrics", {"ts": now, "changed": changed}
                        )
                wait = 0.25
                if deadline is not None:
                    wait = min(wait, max(0.0, deadline - now))
                span = None
                if sub is not None:
                    try:
                        span = sub.get(timeout=wait)
                    except queue.Empty:
                        span = None
                else:
                    time.sleep(wait)
                if span is not None:
                    self._sse_emit("span", span)
                    sent += 1
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away; nothing to clean up but the sub
        finally:
            if sub is not None and recorder is not None:
                recorder.unsubscribe(sub)


class _Snapshot:
    """Records one visited path, rearmed periodically (explorer.rs:60-76)."""

    def __init__(self):
        self._armed = True
        self._actions: Optional[List[Any]] = None
        self._lock = threading.Lock()

    def visit(self, path) -> None:
        with self._lock:
            if self._armed:
                self._armed = False
                self._actions = path.into_actions()

    def rearm(self) -> None:
        with self._lock:
            self._armed = True

    def recent(self) -> Optional[str]:
        with self._lock:
            return None if self._actions is None else repr(self._actions)


def _properties_view(checker: Checker, model: Model) -> List[List[Any]]:
    """(expectation, name, encoded discovery path) triples (explorer.rs:203-221)."""
    out = []
    for prop in model.properties():
        discovery = checker.discovery(prop.name)
        out.append(
            [
                prop.expectation.value,
                prop.name,
                discovery.encode(model) if discovery is not None else None,
            ]
        )
    return out


def _status_view(checker: Checker, model: Model, snapshot: _Snapshot) -> Dict:
    return {
        "done": checker.is_done(),
        "model": type(model).__name__,
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "properties": _properties_view(checker, model),
        "recent_path": snapshot.recent(),
    }


def _metrics_view(checker: Checker) -> Dict:
    """GET /metrics: one timestamped snapshot of the run's counters plus the
    engine's metrics registry (obs/metrics.py). The dashboard polls this to
    derive the states/sec sparkline client-side from successive samples."""
    return {
        "ts": time.time(),
        "done": checker.is_done(),
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "telemetry": checker.telemetry(),
    }


def _metrics_prometheus(checker: Checker) -> str:
    """GET /metrics?format=prometheus: the same snapshot in Prometheus
    text exposition format (obs/metrics.py:render_prometheus)."""
    from ..obs.metrics import (
        MEMORY_SERIES_LABELS,
        SHARD_SERIES_LABELS,
        render_prometheus,
    )

    snap = dict(checker.telemetry())
    snap.setdefault("state_count", checker.state_count())
    snap.setdefault("unique_state_count", checker.unique_state_count())
    snap.setdefault("max_depth", checker.max_depth())
    snap.setdefault("done", checker.is_done())
    return render_prometheus(
        snap, labels={**SHARD_SERIES_LABELS, **MEMORY_SERIES_LABELS}
    )


def _coverage_view(checker: Checker) -> Dict:
    """GET /coverage: the run's coverage snapshot (obs/coverage.py),
    timestamped like /metrics so the dashboard can poll both."""
    return {
        "ts": time.time(),
        "done": checker.is_done(),
        "coverage": checker.coverage(),
    }


def _flight_view(checker: Checker) -> Dict:
    """GET /flight: the run's flight recording (obs/flight.py) —
    retained per-era records plus the run-level summary, timestamped
    like /metrics so the dashboard can poll all three."""
    summary = (checker.telemetry() or {}).get("flight") or {}
    return {
        "ts": time.time(),
        "done": checker.is_done(),
        "records": checker.flight(),
        "summary": summary,
    }


def _space_view(checker: Checker) -> Dict:
    """GET /space: the run's space profile (obs/sample.py) — the
    deterministic bottom-k sample's per-field sketches, depth exemplars,
    action exemplars, saturation warnings, and the KMV cardinality
    estimate — timestamped like /metrics so the dashboard can poll it.
    Runs with sampling disabled serve an empty ``space`` object."""
    return {
        "ts": time.time(),
        "done": checker.is_done(),
        "space": checker.space_profile() or {},
    }


def _memory_view(checker: Checker) -> Dict:
    """GET /memory: the run's memory-ledger snapshot (obs/memory.py) —
    per-component residency, growth events, the forecaster's projection,
    and the early warning when one fired — timestamped like /metrics so
    the dashboard can poll it. Engines without a ledger (host engines,
    `.memory(False)` runs) serve an empty ``memory`` object."""
    memory = (checker.telemetry() or {}).get("memory") or {}
    return {
        "ts": time.time(),
        "done": checker.is_done(),
        "memory": memory,
    }


def _trace_view(trace_path: Optional[str], query: str = "") -> Dict:
    """GET /trace (alias /.trace): a recorded conformance trace
    (conformance/record.py JSONL), served for the dashboard when the
    Explorer was started with one (`serve(..., trace=path)` / the CLI's
    ``explore --trace``). ``?limit=N`` caps the event list (default 2000)."""
    if trace_path is None:
        raise KeyError("no recorded trace attached (start with --trace PATH)")
    from ..conformance import TraceError, load_trace

    try:
        meta, events = load_trace(trace_path)
    except TraceError as e:
        raise KeyError(str(e))
    limit = 2000
    for part in query.split("&"):
        if part.startswith("limit="):
            try:
                limit = max(0, int(part[len("limit"):].lstrip("=")))
            except ValueError:
                pass
    return {
        "path": trace_path,
        "meta": meta,
        "count": len(events),
        "events": events[:limit],
    }


def _deployment_view(trace_path, handle, query: str = "") -> Dict:
    """GET /deployment (alias /.deployment): the live deployment panel —
    actor topology, per-edge delivery/fault counts, live telemetry from a
    running deployment's `NetObs` (when the Explorer holds a spawn
    handle), and a formatted tail of the trace's most recent events.
    ``?tail=N`` sizes the event tail (default 40)."""
    from ..obs.netobs import deployment_view

    tail = 40
    for part in query.split("&"):
        if part.startswith("tail="):
            try:
                tail = max(0, int(part[len("tail"):].lstrip("=")))
            except ValueError:
                pass
    return deployment_view(trace_path=trace_path, handle=handle, tail=tail)


def explain_view(checker: Checker, fingerprints_path: str) -> Dict:
    """Handler for GET /.explain/... (testable without a socket):
    counterexample forensics for the fingerprint path — the per-step
    records of `Path.explain_steps` plus the rendered narrative."""
    model = checker.model()
    cleaned = fingerprints_path.strip("/")
    if not cleaned:
        raise KeyError("explain needs a /fp/fp/... fingerprint path")
    try:
        fingerprints = [int(part) for part in cleaned.split("/")]
    except ValueError:
        raise KeyError(f"Unable to parse fingerprints {cleaned}")
    try:
        path = Path.from_fingerprints(model, fingerprints)
    except Exception as e:
        raise KeyError(f"Unable to reconstruct path: {e}")
    return {
        "steps": path.explain_steps(model),
        "narrative": path.explain(model),
    }


def _state_view(
    checker: Checker,
    model: Model,
    fingerprints: List[int],
    state: Any,
    action: Optional[Any],
    outcome: Optional[str],
) -> Dict:
    fp = model.fingerprint_state(state)
    checker.check_fingerprint(fp)  # expand in the background
    svg = None
    try:
        svg = model.as_svg(Path.from_fingerprints(model, fingerprints + [fp]))
    except Exception:
        pass  # diagram is best-effort
    view: Dict[str, Any] = {
        "state": _pretty(state),
        "fingerprint": str(fp),
        "properties": _properties_view(checker, model),
    }
    if action is not None:
        view["action"] = model.format_action(action)
    if outcome is not None:
        view["outcome"] = outcome
    if svg is not None:
        view["svg"] = svg
    return view


def _pretty(state: Any) -> str:
    text = repr(state)
    if len(text) <= 80:
        return text
    # Cheap pretty-printer: break on commas at bracket depth transitions.
    out, depth, indent = [], 0, "  "
    for ch in text:
        if ch in "([{":
            depth += 1
            out.append(ch + "\n" + indent * depth)
        elif ch in ")]}":
            depth -= 1
            out.append("\n" + indent * depth + ch)
        elif ch == "," :
            out.append(",\n" + indent * depth)
        else:
            out.append(ch)
    return "".join(out).replace(" \n", "\n")


def states_views(checker: Checker, fingerprints_path: str) -> List[Dict]:
    """Handler for GET /.states/... (testable without a socket).

    Reference: states() at explorer.rs:224-320.
    """
    model = checker.model()
    cleaned = fingerprints_path.strip("/")
    fingerprints: List[int] = []
    if cleaned:
        for part in cleaned.split("/"):
            try:
                fingerprints.append(int(part))
            except ValueError:
                raise KeyError(f"Unable to parse fingerprints {cleaned}")

    results: List[Dict] = []
    if not fingerprints:
        for state in model.init_states():
            results.append(_state_view(checker, model, [], state, None, None))
        return results

    last_state = Path.final_state(model, fingerprints)
    if last_state is None:
        raise KeyError(f"Unable to find state following fingerprints {cleaned}")
    actions: List[Any] = []
    model.actions(last_state, actions)
    for action in actions:
        outcome = model.format_step(last_state, action)
        next_state = model.next_state(last_state, action)
        if next_state is not None:
            results.append(
                _state_view(checker, model, fingerprints, next_state, action, outcome)
            )
        else:
            # "Action ignored" is still returned for debugging
            # (explorer.rs:299-307).
            results.append(
                {
                    "action": model.format_action(action),
                    "properties": _properties_view(checker, model),
                }
            )
    return results


class ExplorerServer:
    """A running Explorer; `serve()` constructs it."""

    def __init__(self, builder: CheckerBuilder, address: str, trace: Optional[str] = None,
                 deployment=None):
        self.snapshot = _Snapshot()
        self.trace_path = trace  # recorded conformance trace to serve, if any
        self.deployment = deployment  # live SpawnHandle for GET /deployment
        builder.visitor(self.snapshot.visit)
        # Attach a span recorder (unless the caller brought their own) so
        # the on-demand engine's run/progress spans feed GET /events.
        if getattr(builder, "span_recorder_", None) is None:
            builder.spans(SpanRecorder())
        self.spans = builder.span_recorder_
        self.checker = builder.spawn_on_demand()
        self.model = self.checker.model()

        host, _, port = address.replace("localhost", "127.0.0.1").partition(":")
        self.address = (host or "127.0.0.1", int(port or 3000))

        self._rearm_thread = threading.Thread(target=self._rearm_loop, daemon=True)
        self._stop = threading.Event()

        explorer = self

        class Handler(JsonRequestHandler):
            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/.status":
                    self._send_json(
                        _status_view(explorer.checker, explorer.model, explorer.snapshot)
                    )
                elif path in ("/metrics", "/.metrics", "/metrics.prom"):
                    if path == "/metrics.prom" or "format=prometheus" in query:
                        self._send(
                            200,
                            _metrics_prometheus(explorer.checker).encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    else:
                        self._send_json(_metrics_view(explorer.checker))
                elif path in ("/coverage", "/.coverage"):
                    self._send_json(_coverage_view(explorer.checker))
                elif path in ("/flight", "/.flight"):
                    self._send_json(_flight_view(explorer.checker))
                elif path in ("/memory", "/.memory"):
                    self._send_json(_memory_view(explorer.checker))
                elif path in ("/space", "/.space"):
                    self._send_json(_space_view(explorer.checker))
                elif path in ("/events", "/.events"):
                    self._serve_sse(
                        explorer.spans,
                        query,
                        telemetry=lambda: _metrics_view(explorer.checker),
                    )
                elif path in ("/trace", "/.trace"):
                    try:
                        self._send_json(_trace_view(explorer.trace_path, query))
                    except KeyError as e:
                        self._send(404, str(e).encode(), "text/plain")
                elif path in ("/deployment", "/.deployment"):
                    try:
                        self._send_json(
                            _deployment_view(
                                explorer.trace_path, explorer.deployment, query
                            )
                        )
                    except KeyError as e:
                        self._send(404, str(e).encode(), "text/plain")
                elif path.startswith("/.explain"):
                    try:
                        self._send_json(
                            explain_view(
                                explorer.checker, path[len("/.explain"):]
                            )
                        )
                    except KeyError as e:
                        self._send(404, str(e).encode(), "text/plain")
                elif path.startswith("/.states"):
                    try:
                        self._send_json(
                            states_views(explorer.checker, path[len("/.states"):])
                        )
                    except KeyError as e:
                        self._send(404, str(e).encode(), "text/plain")
                elif path in ("/", "/index.htm", "/index.html"):
                    self._ui_file("index.html", "text/html")
                elif path == "/app.js":
                    self._ui_file("app.js", "application/javascript")
                elif path == "/app.css":
                    self._ui_file("app.css", "text/css")
                else:
                    self._send(404, b"", "text/plain")

            def _ui_file(self, name: str, content_type: str):
                try:
                    self._send(200, (_UI_DIR / name).read_bytes(), content_type)
                except OSError:
                    self._send(404, b"missing UI file", "text/plain")

            def do_POST(self):
                if self.path.split("?", 1)[0] == "/.runtocompletion":
                    explorer.checker.run_to_completion()
                    self._send(200, b"", "text/plain")
                else:
                    self._send(404, b"", "text/plain")

        self.httpd = ThreadingHTTPServer(self.address, Handler)

    def _rearm_loop(self):
        while not self._stop.wait(_SNAPSHOT_REFRESH_SECS):
            self.snapshot.rearm()

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}/"

    def serve_forever(self):
        _log.info("explorer ready", url=self.url)
        self._rearm_thread.start()
        try:
            self.httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def serve_in_background(self) -> "ExplorerServer":
        self._rearm_thread.start()
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        return self

    def shutdown(self):
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()


def serve(builder: CheckerBuilder, address: str, block: bool = True,
          trace: Optional[str] = None, deployment=None):
    """Start the Explorer. Reference: serve() (explorer.rs:79-99).

    With `block=False` the server runs on daemon threads and the handle is
    returned (a testability capability the reference lacks). `trace`
    attaches a recorded conformance trace, served at ``GET /trace``;
    `deployment` attaches a live spawn handle whose netobs telemetry
    feeds ``GET /deployment``.
    """
    server = ExplorerServer(builder, address, trace=trace, deployment=deployment)
    if block:
        server.serve_forever()
        return server.checker
    return server.serve_in_background()
