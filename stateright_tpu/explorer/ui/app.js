// Explorer SPA logic. Navigation state lives in location.hash as a
// fingerprint path ("#/fp1/fp2/..."), exactly like the reference UI, so
// views are linkable and the back button works.
"use strict";

const $ = (id) => document.getElementById(id);

let selected = 0; // index into the current successor list
let lastViews = [];

function fpPath() {
  return location.hash.replace(/^#/, "").replace(/\/+$/, "");
}

function verdict(expectation, discovered, done) {
  if (discovered) {
    return expectation === "sometimes"
      ? "✅ example found"
      : "⚠️ counterexample found";
  }
  if (!done) return "🔎 searching…";
  return expectation === "sometimes"
    ? "⚠️ example not found"
    : "✅ holds";
}

async function pollStatus() {
  try {
    const res = await fetch("/.status");
    const st = await res.json();
    $("model-name").textContent = st.model;
    $("progress").textContent = st.done
      ? "Done."
      : "Checking… " + (st.recent_path || "");
    $("counters").textContent =
      ` states=${st.state_count.toLocaleString()}` +
      ` unique=${st.unique_state_count.toLocaleString()}` +
      ` depth=${st.max_depth}`;
    const ul = $("properties");
    ul.innerHTML = "";
    for (const [expectation, name, discovery] of st.properties) {
      const li = document.createElement("li");
      const label = document.createElement("span");
      label.textContent = `${expectation} "${name}": ${verdict(
        expectation, discovery, st.done)}`;
      li.appendChild(label);
      if (discovery) {
        const a = document.createElement("a");
        a.textContent = " → view discovery";
        a.href = "#/" + discovery;
        li.appendChild(a);
      }
      ul.appendChild(li);
    }
  } catch (e) {
    $("progress").textContent = "status unavailable: " + e;
  }
  setTimeout(pollStatus, 1000);
}

function renderBreadcrumbs() {
  const nav = $("breadcrumbs");
  nav.innerHTML = "";
  const root = document.createElement("a");
  root.textContent = "init";
  root.href = "#/";
  nav.appendChild(root);
  const parts = fpPath().split("/").filter(Boolean);
  let acc = "";
  for (const fp of parts) {
    acc += "/" + fp;
    nav.appendChild(document.createTextNode(" / "));
    const a = document.createElement("a");
    a.textContent = "…" + fp.slice(-6);
    a.href = "#" + acc;
    nav.appendChild(a);
  }
}

function showDetail(view) {
  $("detail-state").textContent = view && view.state ? view.state : "";
  $("detail-svg").innerHTML = view && view.svg ? view.svg : "";
}

function select(i) {
  const rows = document.querySelectorAll("#states .state-row");
  if (!rows.length) return;
  selected = Math.max(0, Math.min(i, rows.length - 1));
  rows.forEach((r, k) => r.classList.toggle("selected", k === selected));
  rows[selected].scrollIntoView({ block: "nearest" });
  showDetail(lastViews[selected]);
}

async function loadStates() {
  renderBreadcrumbs();
  const section = $("states");
  section.textContent = "loading…";
  const res = await fetch("/.states/" + fpPath().split("/").filter(Boolean).join("/"));
  if (!res.ok) {
    section.textContent = "error: " + (await res.text());
    return;
  }
  lastViews = await res.json();
  section.innerHTML = "";
  lastViews.forEach((v, i) => {
    const row = document.createElement("div");
    row.className = "state-row";
    const action = document.createElement("span");
    action.className = "action";
    action.textContent = v.action || "(init)";
    row.appendChild(action);
    if (v.outcome) {
      const out = document.createElement("span");
      out.className = "outcome";
      out.textContent = " " + v.outcome;
      row.appendChild(out);
    }
    if (v.fingerprint) {
      row.addEventListener("click", () => {
        select(i);
      });
      row.addEventListener("dblclick", () => {
        location.hash = "#/" + fpPath().split("/").filter(Boolean)
          .concat([v.fingerprint]).join("/");
      });
    } else {
      row.classList.add("ignored");
      const note = document.createElement("span");
      note.textContent = " (action ignored)";
      row.appendChild(note);
    }
    section.appendChild(row);
  });
  select(0);
}

document.addEventListener("keydown", (ev) => {
  if (ev.key === "j") select(selected + 1);
  else if (ev.key === "k") select(selected - 1);
  else if (ev.key === "Enter" || ev.key === "l") {
    const v = lastViews[selected];
    if (v && v.fingerprint) {
      location.hash = "#/" + fpPath().split("/").filter(Boolean)
        .concat([v.fingerprint]).join("/");
    }
  } else if (ev.key === "Backspace" || ev.key === "h") {
    const parts = fpPath().split("/").filter(Boolean);
    parts.pop();
    location.hash = "#/" + parts.join("/");
    ev.preventDefault();
  }
});

$("run-to-completion").addEventListener("click", async () => {
  await fetch("/.runtocompletion", { method: "POST" });
});

window.addEventListener("hashchange", loadStates);
pollStatus();
loadStates();
