// Explorer SPA logic. Navigation state lives in location.hash as a
// fingerprint path ("#/fp1/fp2/..."), exactly like the reference UI, so
// views are linkable and the back button works.
"use strict";

const $ = (id) => document.getElementById(id);

let selected = 0; // index into the current successor list
let lastViews = [];

function fpPath() {
  return location.hash.replace(/^#/, "").replace(/\/+$/, "");
}

function verdict(expectation, discovered, done) {
  if (discovered) {
    return expectation === "sometimes"
      ? "✅ example found"
      : "⚠️ counterexample found";
  }
  if (!done) return "🔎 searching…";
  return expectation === "sometimes"
    ? "⚠️ example not found"
    : "✅ holds";
}

async function pollStatus() {
  try {
    const res = await fetch("/.status");
    const st = await res.json();
    $("model-name").textContent = st.model;
    $("progress").textContent = st.done
      ? "Done."
      : "Checking… " + (st.recent_path || "");
    $("counters").textContent =
      ` states=${st.state_count.toLocaleString()}` +
      ` unique=${st.unique_state_count.toLocaleString()}` +
      ` depth=${st.max_depth}`;
    const ul = $("properties");
    ul.innerHTML = "";
    for (const [expectation, name, discovery] of st.properties) {
      const li = document.createElement("li");
      const label = document.createElement("span");
      label.textContent = `${expectation} "${name}": ${verdict(
        expectation, discovery, st.done)}`;
      li.appendChild(label);
      if (discovery) {
        const a = document.createElement("a");
        a.textContent = " → view discovery";
        a.href = "#/" + discovery;
        li.appendChild(a);
      }
      ul.appendChild(li);
    }
  } catch (e) {
    $("progress").textContent = "status unavailable: " + e;
  }
  setTimeout(pollStatus, 1000);
}

// ---- live metrics dashboard ------------------------------------------------
// Polls /metrics once a second; the states/sec series derives client-side
// from successive state_count samples. Single series, one hue (--series-1),
// hover shows the nearest sample's value.

const sparkHistory = []; // [{ts, rate}], bounded window
const SPARK_WINDOW = 60;
let lastMetricsSample = null;

function fmtRate(r) {
  if (r >= 1e6) return (r / 1e6).toFixed(2) + "M";
  if (r >= 1e3) return (r / 1e3).toFixed(1) + "k";
  return r.toFixed(0);
}

function renderSparkline(hoverX) {
  const svg = $("sparkline");
  const w = svg.clientWidth || 240;
  const h = svg.clientHeight || 36;
  const pad = 2;
  svg.innerHTML = "";
  if (sparkHistory.length < 2) return;
  const max = Math.max(...sparkHistory.map((s) => s.rate), 1);
  const dx = (w - 2 * pad) / (SPARK_WINDOW - 1);
  const x0 = w - pad - (sparkHistory.length - 1) * dx;
  const pts = sparkHistory.map((s, i) => [
    x0 + i * dx,
    h - pad - (s.rate / max) * (h - 2 * pad),
  ]);
  const line = document.createElementNS("http://www.w3.org/2000/svg", "polyline");
  line.setAttribute("points", pts.map((p) => p.map((v) => v.toFixed(1)).join(",")).join(" "));
  line.setAttribute("class", "spark-line");
  svg.appendChild(line);
  // Hover readout: nearest sample to the cursor gets a marker + value.
  let idx = sparkHistory.length - 1;
  if (hoverX != null) {
    idx = Math.max(0, Math.min(sparkHistory.length - 1, Math.round((hoverX - x0) / dx)));
    const dot = document.createElementNS("http://www.w3.org/2000/svg", "circle");
    dot.setAttribute("cx", pts[idx][0].toFixed(1));
    dot.setAttribute("cy", pts[idx][1].toFixed(1));
    dot.setAttribute("r", "3");
    dot.setAttribute("class", "spark-dot");
    svg.appendChild(dot);
  }
  $("spark-readout").textContent =
    hoverX != null ? fmtRate(sparkHistory[idx].rate) + "/s" : "";
}

function renderGauges(m) {
  const box = $("gauges");
  box.innerHTML = "";
  const add = (k, v) => {
    const row = document.createElement("div");
    row.className = "gauge";
    const key = document.createElement("span");
    key.className = "gauge-k";
    key.textContent = k;
    const val = document.createElement("span");
    val.className = "gauge-v";
    val.textContent = v;
    row.appendChild(key);
    row.appendChild(val);
    box.appendChild(row);
  };
  add("states", m.state_count.toLocaleString());
  add("unique", m.unique_state_count.toLocaleString());
  add("depth", m.max_depth);
  const tel = m.telemetry || {};
  for (const k of Object.keys(tel).sort()) {
    if (k === "phase_ms" || k === "engine") continue;
    add(k, typeof tel[k] === "number" ? tel[k].toLocaleString() : tel[k]);
  }
  const phases = tel.phase_ms || {};
  for (const k of Object.keys(phases).sort()) {
    add(k + " ms", phases[k].toLocaleString());
  }
}

async function pollMetrics() {
  try {
    const res = await fetch("/metrics");
    const m = await res.json();
    $("metrics-panel").hidden = false;
    if (lastMetricsSample && m.ts > lastMetricsSample.ts) {
      const rate =
        (m.state_count - lastMetricsSample.state_count) /
        (m.ts - lastMetricsSample.ts);
      sparkHistory.push({ ts: m.ts, rate: Math.max(0, rate) });
      if (sparkHistory.length > SPARK_WINDOW) sparkHistory.shift();
      $("rate-now").textContent = fmtRate(Math.max(0, rate));
    }
    lastMetricsSample = m;
    renderSparkline(null);
    renderGauges(m);
  } catch (e) {
    /* metrics endpoint unavailable: leave the panel hidden */
  }
  setTimeout(pollMetrics, 1000);
}

$("sparkline").addEventListener("mousemove", (ev) => {
  const box = $("sparkline").getBoundingClientRect();
  renderSparkline(ev.clientX - box.left);
});
$("sparkline").addEventListener("mouseleave", () => renderSparkline(null));

// ---- coverage panel --------------------------------------------------------
// Polls /coverage every 2s: per-action fire counts as a horizontal bar
// chart, the per-depth unique-state histogram as vertical bars, and a
// dead-action warning list (actions whose guard never fired).

function renderActionBars(actions) {
  const box = $("action-bars");
  box.innerHTML = "";
  const entries = Object.entries(actions);
  if (!entries.length) return;
  const max = Math.max(...entries.map(([, v]) => v), 1);
  for (const [label, count] of entries) {
    const row = document.createElement("div");
    row.className = "cov-row" + (count === 0 ? " cov-dead" : "");
    const name = document.createElement("span");
    name.className = "cov-label";
    name.textContent = label;
    const track = document.createElement("span");
    track.className = "cov-track";
    const bar = document.createElement("span");
    bar.className = "cov-bar";
    bar.style.width = ((count / max) * 100).toFixed(1) + "%";
    track.appendChild(bar);
    const val = document.createElement("span");
    val.className = "cov-count";
    val.textContent = count.toLocaleString();
    row.appendChild(name);
    row.appendChild(track);
    row.appendChild(val);
    box.appendChild(row);
  }
}

function renderDepthHist(depths) {
  const svg = $("depth-hist");
  const w = svg.clientWidth || 240;
  const h = svg.clientHeight || 48;
  svg.innerHTML = "";
  const entries = Object.entries(depths).map(([d, n]) => [Number(d), n]);
  if (!entries.length) return;
  entries.sort((a, b) => a[0] - b[0]);
  const maxDepth = entries[entries.length - 1][0];
  const maxN = Math.max(...entries.map(([, n]) => n), 1);
  const bw = Math.max(1, (w - 2) / (maxDepth + 1) - 1);
  for (const [d, n] of entries) {
    const bar = document.createElementNS("http://www.w3.org/2000/svg", "rect");
    const bh = Math.max(1, (n / maxN) * (h - 2));
    bar.setAttribute("x", (1 + d * (bw + 1)).toFixed(1));
    bar.setAttribute("y", (h - 1 - bh).toFixed(1));
    bar.setAttribute("width", bw.toFixed(1));
    bar.setAttribute("height", bh.toFixed(1));
    bar.setAttribute("class", "hist-bar");
    const tip = document.createElementNS("http://www.w3.org/2000/svg", "title");
    tip.textContent = `depth ${d}: ${n.toLocaleString()} states`;
    bar.appendChild(tip);
    svg.appendChild(bar);
  }
  $("depth-readout").textContent = `max depth ${maxDepth}`;
}

function renderDeadActions(dead) {
  const box = $("dead-actions");
  box.innerHTML = "";
  if (!dead || !dead.length) return;
  const head = document.createElement("div");
  head.className = "dead-head";
  head.textContent =
    `⚠ ${dead.length} action(s) never fired (dead transition or ` +
    "mis-modeled guard; speclint STR306):";
  box.appendChild(head);
  for (const label of dead) {
    const row = document.createElement("div");
    row.className = "dead-row";
    row.textContent = label;
    box.appendChild(row);
  }
}

async function pollCoverage() {
  try {
    const res = await fetch("/coverage");
    const body = await res.json();
    const cov = body.coverage || {};
    if (cov.enabled && Object.keys(cov.actions || {}).length) {
      $("coverage-panel").hidden = false;
      renderActionBars(cov.actions);
      renderDepthHist(cov.depths || {});
      renderDeadActions(cov.dead_actions);
    }
  } catch (e) {
    /* coverage endpoint unavailable: leave the panel hidden */
  }
  setTimeout(pollCoverage, 2000);
}

// ---- flight timeline -------------------------------------------------------
// Polls /flight every 2s: the per-era wall split (device_era stacked under
// host_gap) as paired bars, and frontier occupancy (bars) with the table
// load factor (line) on a second axis — the dispatch-gap story over eras.

function renderFlightEras(records) {
  const svg = $("flight-eras");
  const w = svg.clientWidth || 480;
  const h = svg.clientHeight || 48;
  svg.innerHTML = "";
  const maxWall = Math.max(...records.map((r) => r.wall_secs), 1e-9);
  const bw = Math.max(1, (w - 2) / records.length - 1);
  records.forEach((r, i) => {
    const x = 1 + i * (bw + 1);
    const devH = Math.max(1, (r.device_era_secs / maxWall) * (h - 2));
    const gapH = (r.host_gap_secs / maxWall) * (h - 2);
    const dev = document.createElementNS("http://www.w3.org/2000/svg", "rect");
    dev.setAttribute("x", x.toFixed(1));
    dev.setAttribute("y", (h - 1 - devH).toFixed(1));
    dev.setAttribute("width", bw.toFixed(1));
    dev.setAttribute("height", devH.toFixed(1));
    dev.setAttribute("class", "flight-dev");
    const tip = document.createElementNS("http://www.w3.org/2000/svg", "title");
    tip.textContent =
      `era ${r.era}: device ${(r.device_era_secs * 1000).toFixed(1)} ms, ` +
      `gap ${(r.host_gap_secs * 1000).toFixed(1)} ms`;
    dev.appendChild(tip);
    svg.appendChild(dev);
    if (gapH > 0.5) {
      const gap = document.createElementNS("http://www.w3.org/2000/svg", "rect");
      gap.setAttribute("x", x.toFixed(1));
      gap.setAttribute("y", (h - 1 - devH - gapH).toFixed(1));
      gap.setAttribute("width", bw.toFixed(1));
      gap.setAttribute("height", gapH.toFixed(1));
      gap.setAttribute("class", "flight-gap");
      svg.appendChild(gap);
    }
  });
}

function renderFlightOccupancy(records) {
  const svg = $("flight-occupancy");
  const w = svg.clientWidth || 480;
  const h = svg.clientHeight || 48;
  svg.innerHTML = "";
  const maxF = Math.max(...records.map((r) => r.frontier), 1);
  const maxLf = Math.max(...records.map((r) => r.load_factor), 1e-9);
  const bw = Math.max(1, (w - 2) / records.length - 1);
  records.forEach((r, i) => {
    const x = 1 + i * (bw + 1);
    const bh = Math.max(1, (r.frontier / maxF) * (h - 2));
    const bar = document.createElementNS("http://www.w3.org/2000/svg", "rect");
    bar.setAttribute("x", x.toFixed(1));
    bar.setAttribute("y", (h - 1 - bh).toFixed(1));
    bar.setAttribute("width", bw.toFixed(1));
    bar.setAttribute("height", bh.toFixed(1));
    bar.setAttribute("class", "flight-frontier");
    const tip = document.createElementNS("http://www.w3.org/2000/svg", "title");
    tip.textContent =
      `era ${r.era}: frontier ${r.frontier.toLocaleString()} rows, ` +
      `load factor ${r.load_factor}`;
    bar.appendChild(tip);
    svg.appendChild(bar);
  });
  const pts = records.map((r, i) => [
    1 + i * (bw + 1) + bw / 2,
    h - 1 - (r.load_factor / maxLf) * (h - 2),
  ]);
  if (pts.length > 1) {
    const line = document.createElementNS("http://www.w3.org/2000/svg", "polyline");
    line.setAttribute(
      "points",
      pts.map((p) => p.map((v) => v.toFixed(1)).join(",")).join(" ")
    );
    line.setAttribute("class", "flight-lf-line");
    svg.appendChild(line);
  }
}

async function pollFlight() {
  try {
    const res = await fetch("/flight");
    const body = await res.json();
    const records = body.records || [];
    if (records.length) {
      $("flight-panel").hidden = false;
      renderFlightEras(records);
      renderFlightOccupancy(records);
      const s = body.summary || {};
      $("flight-era-readout").textContent =
        `${s.eras || records.length} eras · device ` +
        `${((s.device_secs || 0) * 1000).toFixed(0)} ms · host gap ` +
        `${((s.host_gap_secs || 0) * 1000).toFixed(0)} ms ` +
        `(${s.host_gap_pct != null ? s.host_gap_pct : 0}%)`;
      const last = records[records.length - 1];
      $("flight-occ-readout").textContent =
        `latest: frontier ${last.frontier.toLocaleString()} rows · ` +
        `load factor ${last.load_factor}`;
    }
  } catch (e) {
    /* flight endpoint unavailable: leave the panel hidden */
  }
  setTimeout(pollFlight, 2000);
}

// ---- memory panel ----------------------------------------------------------
// Polls /memory every 2s: the ledger's per-component device residency as
// horizontal bars (obs/memory.py), a headroom/forecast readout, and the
// forecaster's one-shot early warning as a banner once it has fired.

function fmtBytes(n) {
  if (n == null) return "–";
  const units = ["B", "KiB", "MiB", "GiB", "TiB"];
  let v = n;
  let u = 0;
  while (v >= 1024 && u < units.length - 1) {
    v /= 1024;
    u += 1;
  }
  return (u === 0 || v >= 10 ? Math.round(v) : v.toFixed(1)) + " " + units[u];
}

function renderMemoryBars(components) {
  const holder = $("memory-bars");
  holder.innerHTML = "";
  const entries = Object.entries(components).sort(
    (a, b) => b[1].bytes - a[1].bytes
  );
  const max = Math.max(...entries.map(([, c]) => c.bytes), 1);
  for (const [label, c] of entries) {
    const row = document.createElement("div");
    row.className = "cov-row";
    const name = document.createElement("span");
    name.className = "cov-label";
    name.textContent = label;
    name.title = `shape ${JSON.stringify(c.shape)} · ${c.dtype}`;
    const track = document.createElement("span");
    track.className = "cov-track";
    const bar = document.createElement("span");
    bar.className = "cov-bar mem-bar";
    bar.style.width = Math.max(1, (c.bytes / max) * 100).toFixed(1) + "%";
    track.appendChild(bar);
    const val = document.createElement("span");
    val.className = "cov-count";
    val.textContent = fmtBytes(c.bytes);
    row.appendChild(name);
    row.appendChild(track);
    row.appendChild(val);
    holder.appendChild(row);
  }
}

async function pollMemory() {
  try {
    const res = await fetch("/memory");
    const body = await res.json();
    const mem = body.memory || {};
    const components = mem.components || {};
    if (Object.keys(components).length) {
      $("memory-panel").hidden = false;
      renderMemoryBars(components);
      const bits = [
        `total ${fmtBytes(mem.total_bytes)}`,
        `peak ${fmtBytes(mem.peak_bytes)}`,
      ];
      if (mem.host_bytes) bits.push(`host staging ${fmtBytes(mem.host_bytes)}`);
      if (mem.headroom_bytes != null)
        bits.push(`headroom ${fmtBytes(mem.headroom_bytes)}`);
      const fc = mem.forecast || {};
      if (fc.eras_to_exhaustion != null)
        bits.push(`~${fc.eras_to_exhaustion} eras to exhaustion`);
      else if (fc.eras_to_grow != null)
        bits.push(`~${fc.eras_to_grow} eras to next growth`);
      $("memory-readout").textContent = bits.join(" · ");
      const warnEl = $("memory-warning");
      if (mem.warning) {
        warnEl.hidden = false;
        warnEl.textContent = "⚠ " + mem.warning;
      } else {
        warnEl.hidden = true;
      }
    }
  } catch (e) {
    /* memory endpoint unavailable: leave the panel hidden */
  }
  setTimeout(pollMemory, 2000);
}

// ---- space panel -----------------------------------------------------------
// Polls /space every 2s: the deterministic bottom-k sample's per-field
// value sketches as rows (obs/sample.py), a sample-size / KMV-estimate
// readout, and packing-saturation warnings as a banner.

function sketchSummary(sk) {
  if (sk.kind === "bool") return `true ${sk.true} · false ${sk.false}`;
  if (sk.kind === "int")
    return sk.min === sk.max
      ? `= ${sk.min}`
      : `${sk.min} … ${sk.max} · ${sk.distinct} distinct`;
  return `${sk.distinct} distinct`;
}

function renderSpaceFields(fields) {
  const holder = $("space-fields");
  holder.innerHTML = "";
  const entries = Object.entries(fields);
  const max = Math.max(...entries.map(([, sk]) => sk.distinct), 1);
  for (const [label, sk] of entries) {
    const row = document.createElement("div");
    row.className = "cov-row";
    const name = document.createElement("span");
    name.className = "cov-label";
    name.textContent = label;
    name.title = `${sk.kind} · ${sk.count} sampled`;
    const track = document.createElement("span");
    track.className = "cov-track";
    const bar = document.createElement("span");
    bar.className = "cov-bar";
    bar.style.width = Math.max(1, (sk.distinct / max) * 100).toFixed(1) + "%";
    track.appendChild(bar);
    const val = document.createElement("span");
    val.className = "cov-count";
    val.textContent = sketchSummary(sk);
    row.appendChild(name);
    row.appendChild(track);
    row.appendChild(val);
    holder.appendChild(row);
  }
}

async function pollSpace() {
  try {
    const res = await fetch("/space");
    const body = await res.json();
    const space = body.space || {};
    if (space.samples) {
      $("space-panel").hidden = false;
      renderSpaceFields(space.fields || {});
      const bits = [
        `sample ${space.samples}/${space.k}`,
        `~${Number(space.est_states).toLocaleString()} states (KMV)`,
      ];
      if (space.unresolved) bits.push(`${space.unresolved} unresolved`);
      if (space.degraded) bits.push("degraded");
      const depths = Object.keys(space.depths || {});
      if (depths.length) bits.push(`depths ${depths.length}`);
      $("space-readout").textContent = bits.join(" · ");
      const warnEl = $("space-warning");
      const sat = space.saturated || [];
      if (sat.length) {
        warnEl.hidden = false;
        warnEl.textContent =
          "⚠ packing saturation: " +
          sat
            .map((s) => `${s.field || "lane " + s.lane} at ${s.bits}-bit`)
            .join(", ");
      } else {
        warnEl.hidden = true;
      }
    }
  } catch (e) {
    /* space endpoint unavailable: leave the panel hidden */
  }
  setTimeout(pollSpace, 2000);
}

// ---- deployment panel ------------------------------------------------------
// Polls /deployment every 2s: per-link delivery/fault counts from the
// attached conformance trace (and live netobs telemetry when the
// Explorer holds a spawn handle) as rows, plus the causal event tail.

function renderDeployEdges(edges) {
  const holder = $("deploy-edges");
  holder.innerHTML = "";
  const max = Math.max(...edges.map((e) => e.delivered || 0), 1);
  for (const e of edges) {
    const row = document.createElement("div");
    row.className = "cov-row";
    const name = document.createElement("span");
    name.className = "cov-label";
    name.textContent = `${e.src} \u2192 ${e.dst}`;
    const track = document.createElement("span");
    track.className = "cov-track";
    const bar = document.createElement("span");
    bar.className = "cov-bar";
    bar.style.width =
      Math.max(1, ((e.delivered || 0) / max) * 100).toFixed(1) + "%";
    track.appendChild(bar);
    const val = document.createElement("span");
    val.className = "cov-count";
    const faults = Object.entries(e.faults || {})
      .map(([k, n]) => `${k} ${n}`)
      .join(" ");
    val.textContent =
      `${e.sent || 0} sent · ${e.delivered || 0} delivered` +
      (faults ? ` · ${faults}` : "");
    row.appendChild(name);
    row.appendChild(track);
    row.appendChild(val);
    holder.appendChild(row);
  }
}

async function pollDeployment() {
  try {
    const res = await fetch("/deployment");
    if (!res.ok) throw new Error("no deployment");
    const body = await res.json();
    const actors = body.actors || [];
    if (actors.length) {
      $("deployment-panel").hidden = false;
      renderDeployEdges(body.edges || {});
      const bits = [
        `${actors.length} actors`,
        `${body.events || 0} events`,
      ];
      if (body.engine) bits.push(`engine ${body.engine}`);
      if (body.faults_plan) bits.push(`fault seed ${body.faults_plan.seed}`);
      $("deploy-readout").textContent = bits.join(" · ");
      $("deploy-tail").textContent = (body.tail || []).join("\n");
    }
  } catch (e) {
    /* deployment endpoint unavailable: leave the panel hidden */
  }
  setTimeout(pollDeployment, 2000);
}

// ---- span waterfall (run ledger) -------------------------------------------
// Span completions arrive live over GET /events (SSE, obs/spans.py). The
// waterfall draws the most recent trace's spans as horizontal bars on a
// shared time axis — a job timeline when pointed at the run service, the
// checking run's phases here on the Explorer.

const spanLedger = []; // bounded recent span completions
const SPAN_WINDOW = 200;
const WF_ROWS = 40;

function renderWaterfall() {
  if (!spanLedger.length) return;
  $("spans-panel").hidden = false;
  const latest = spanLedger[spanLedger.length - 1].trace_id;
  const spans = spanLedger
    .filter((s) => s.trace_id === latest)
    .slice()
    .sort((a, b) => a.start - b.start);
  const t0 = Math.min(...spans.map((s) => s.start));
  const t1 = Math.max(...spans.map((s) => s.end), t0 + 1e-6);
  const depth = {}; // span_id -> indent by parent chain
  for (const s of spans) {
    depth[s.span_id] =
      s.parent_id != null && depth[s.parent_id] != null
        ? depth[s.parent_id] + 1
        : 0;
  }
  const box = $("waterfall");
  box.innerHTML = "";
  for (const s of spans.slice(-WF_ROWS)) {
    const ms = (s.end - s.start) * 1000;
    const row = document.createElement("div");
    row.className = "wf-row" + (s.status && s.status !== "ok" ? " wf-err" : "");
    const label = document.createElement("span");
    label.className = "wf-label";
    label.style.paddingLeft = (depth[s.span_id] || 0) * 10 + "px";
    label.textContent = s.name;
    const track = document.createElement("span");
    track.className = "wf-track";
    const bar = document.createElement("span");
    bar.className = "wf-bar";
    bar.style.left = (((s.start - t0) / (t1 - t0)) * 100).toFixed(2) + "%";
    bar.style.width =
      Math.max(0.5, ((s.end - s.start) / (t1 - t0)) * 100).toFixed(2) + "%";
    bar.title = `${s.name}: ${ms.toFixed(1)} ms (${s.status || "ok"})`;
    track.appendChild(bar);
    const dur = document.createElement("span");
    dur.className = "wf-dur";
    dur.textContent = ms.toFixed(1) + " ms";
    row.appendChild(label);
    row.appendChild(track);
    row.appendChild(dur);
    box.appendChild(row);
  }
  $("wf-readout").textContent =
    `trace ${latest.slice(0, 8)}… · ${spans.length} spans · ` +
    ((t1 - t0) * 1000).toFixed(1) + " ms total";
}

function startSpanStream() {
  let stream;
  try {
    stream = new EventSource("/events?replay=" + SPAN_WINDOW);
  } catch (e) {
    return; // SSE unavailable: leave the panel hidden
  }
  stream.addEventListener("span", (ev) => {
    try {
      spanLedger.push(JSON.parse(ev.data));
    } catch (e) {
      return;
    }
    if (spanLedger.length > SPAN_WINDOW) spanLedger.shift();
    renderWaterfall();
  });
  stream.onerror = () => {
    /* server restarting: EventSource retries on its own */
  };
}

// ---- path explain (counterexample forensics) -------------------------------

$("explain-path").addEventListener("click", async () => {
  const parts = fpPath().split("/").filter(Boolean);
  const out = $("detail-explain");
  if (!parts.length) {
    out.textContent = "navigate into a path first (or open a discovery)";
    return;
  }
  out.textContent = "explaining…";
  try {
    const res = await fetch("/.explain/" + parts.join("/"));
    if (!res.ok) {
      out.textContent = "error: " + (await res.text());
      return;
    }
    const body = await res.json();
    out.textContent = body.narrative;
  } catch (e) {
    out.textContent = "explain failed: " + e;
  }
});

function renderBreadcrumbs() {
  const nav = $("breadcrumbs");
  nav.innerHTML = "";
  const root = document.createElement("a");
  root.textContent = "init";
  root.href = "#/";
  nav.appendChild(root);
  const parts = fpPath().split("/").filter(Boolean);
  let acc = "";
  for (const fp of parts) {
    acc += "/" + fp;
    nav.appendChild(document.createTextNode(" / "));
    const a = document.createElement("a");
    a.textContent = "…" + fp.slice(-6);
    a.href = "#" + acc;
    nav.appendChild(a);
  }
}

function showDetail(view) {
  $("detail-state").textContent = view && view.state ? view.state : "";
  $("detail-svg").innerHTML = view && view.svg ? view.svg : "";
}

function select(i) {
  const rows = document.querySelectorAll("#states .state-row");
  if (!rows.length) return;
  selected = Math.max(0, Math.min(i, rows.length - 1));
  rows.forEach((r, k) => r.classList.toggle("selected", k === selected));
  rows[selected].scrollIntoView({ block: "nearest" });
  showDetail(lastViews[selected]);
}

async function loadStates() {
  renderBreadcrumbs();
  const section = $("states");
  section.textContent = "loading…";
  const res = await fetch("/.states/" + fpPath().split("/").filter(Boolean).join("/"));
  if (!res.ok) {
    section.textContent = "error: " + (await res.text());
    return;
  }
  lastViews = await res.json();
  section.innerHTML = "";
  lastViews.forEach((v, i) => {
    const row = document.createElement("div");
    row.className = "state-row";
    const action = document.createElement("span");
    action.className = "action";
    action.textContent = v.action || "(init)";
    row.appendChild(action);
    if (v.outcome) {
      const out = document.createElement("span");
      out.className = "outcome";
      out.textContent = " " + v.outcome;
      row.appendChild(out);
    }
    if (v.fingerprint) {
      row.addEventListener("click", () => {
        select(i);
      });
      row.addEventListener("dblclick", () => {
        location.hash = "#/" + fpPath().split("/").filter(Boolean)
          .concat([v.fingerprint]).join("/");
      });
    } else {
      row.classList.add("ignored");
      const note = document.createElement("span");
      note.textContent = " (action ignored)";
      row.appendChild(note);
    }
    section.appendChild(row);
  });
  select(0);
}

document.addEventListener("keydown", (ev) => {
  if (ev.key === "j") select(selected + 1);
  else if (ev.key === "k") select(selected - 1);
  else if (ev.key === "Enter" || ev.key === "l") {
    const v = lastViews[selected];
    if (v && v.fingerprint) {
      location.hash = "#/" + fpPath().split("/").filter(Boolean)
        .concat([v.fingerprint]).join("/");
    }
  } else if (ev.key === "Backspace" || ev.key === "h") {
    const parts = fpPath().split("/").filter(Boolean);
    parts.pop();
    location.hash = "#/" + parts.join("/");
    ev.preventDefault();
  }
});

$("run-to-completion").addEventListener("click", async () => {
  await fetch("/.runtocompletion", { method: "POST" });
});

window.addEventListener("hashchange", () => {
  $("detail-explain").textContent = "";
  loadStates();
});
pollStatus();
pollMetrics();
pollCoverage();
pollFlight();
pollMemory();
pollSpace();
pollDeployment();
startSpanStream();
loadStates();
