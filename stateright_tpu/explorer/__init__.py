"""The Explorer: an interactive web UI over an on-demand checking run.

Reference parity: src/checker/explorer.rs (JSON API) + ui/ (SPA). See
`server.serve` for the HTTP surface.
"""

from .server import ExplorerServer, serve

__all__ = ["ExplorerServer", "serve"]
