"""Finish policies: when a checking run may stop early.

Reference: `HasDiscoveries` at src/has_discoveries.rs:6-42.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, List, Set


class HasDiscoveries:
    """When to finish the checker run, given the set of discovered property names."""

    _kind: str
    _names: FrozenSet[str]

    def __init__(self, kind: str, names: Iterable[str] = ()):  # internal
        self._kind = kind
        self._names = frozenset(names)

    # Constructors mirroring the reference enum variants.
    ALL: "HasDiscoveries"
    ANY: "HasDiscoveries"
    ANY_FAILURES: "HasDiscoveries"
    ALL_FAILURES: "HasDiscoveries"

    @staticmethod
    def all_of(names: Iterable[str]) -> "HasDiscoveries":
        return HasDiscoveries("all_of", names)

    @staticmethod
    def any_of(names: Iterable[str]) -> "HasDiscoveries":
        return HasDiscoveries("any_of", names)

    def matches(self, discoveries: Set[str], properties: List) -> bool:
        """Reference: src/has_discoveries.rs:21-42."""
        kind = self._kind
        if kind == "all":
            return len(discoveries) == len(properties)
        if kind == "any":
            return bool(discoveries)
        if kind == "any_failures":
            return any(
                p.name in discoveries
                for p in properties
                if p.expectation.discovery_is_failure
            )
        if kind == "all_failures":
            return all(
                p.name in discoveries
                for p in properties
                if p.expectation.discovery_is_failure
            )
        if kind == "all_of":
            return all(name in discoveries for name in self._names)
        if kind == "any_of":
            return any(name in discoveries for name in self._names)
        raise ValueError(f"unknown finish policy {kind!r}")

    def device_masks(self, properties: List):
        """Lower this policy to property-index bitmasks for device gates.

        Returns (any_mask, all_mask, all_enabled): the policy matches a
        discovery bitmask `rec` iff `(rec & any_mask) != 0 or
        (all_enabled and (rec & all_mask) == all_mask)` — exactly
        `matches()` over index bitmaps. When the policy references a
        property name that does not exist (an `all_of` that can never
        complete), the all-gate is disabled so the device never exits
        early on it; the host-side `matches()` stays authoritative.
        """
        idx = {p.name: i for i, p in enumerate(properties)}
        all_bits = (1 << len(properties)) - 1
        failure_bits = 0
        for i, p in enumerate(properties):
            if p.expectation.discovery_is_failure:
                failure_bits |= 1 << i
        kind = self._kind
        if kind == "all":
            return 0, all_bits, 1
        if kind == "any":
            return all_bits, 0, 0
        if kind == "any_failures":
            return failure_bits, 0, 0
        if kind == "all_failures":
            return 0, failure_bits, 1
        if kind == "all_of":
            if not all(n in idx for n in self._names):
                return 0, 0, 0  # can never match; disable the device gate
            return 0, sum(1 << idx[n] for n in self._names), 1
        if kind == "any_of":
            return sum(1 << idx[n] for n in self._names if n in idx), 0, 0
        raise ValueError(f"unknown finish policy {kind!r}")

    def __repr__(self) -> str:
        if self._names:
            return f"HasDiscoveries.{self._kind}({sorted(self._names)})"
        return f"HasDiscoveries.{self._kind.upper()}"


HasDiscoveries.ALL = HasDiscoveries("all")
HasDiscoveries.ANY = HasDiscoveries("any")
HasDiscoveries.ANY_FAILURES = HasDiscoveries("any_failures")
HasDiscoveries.ALL_FAILURES = HasDiscoveries("all_failures")
