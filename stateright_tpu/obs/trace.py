"""Structured run traces: one JSONL event per era/wave/round.

`CheckerBuilder.trace(path)` hands every engine a `TraceWriter`; the engine
emits one event per unit of forward progress (an *era* for the device
engines, a *wave*/block for the host engines, a *round* for the pbfs
coordinator, a *walk* for simulation traces) plus `run_start` / `run_end`
brackets. Lines are standalone JSON objects, flushed as written, so a
killed run still leaves a parseable prefix.

Event schema — every record carries:

  ``ts``      wall-clock seconds (time.time())
  ``seq``     per-writer monotonically increasing sequence number
  ``engine``  emitting engine class name
  ``event``   "run_start" | "era" | "wave" | "round" | "walk" | "run_end"

Progress events additionally carry ``states`` (generated total),
``unique`` (unique states so far), ``frontier`` (pending rows/jobs),
``max_depth``, and ``phase_ms`` — the per-event *delta* of each phase
timer, i.e. the milliseconds each instrumented phase consumed since the
previous event (see obs/metrics.py for the phase catalog). Device-engine
era events also carry ``load_factor``, ``take_cap``, ``steps``,
``generated``, and ``spill_rows`` for that era.

Profiling: `start_profile(dir)` / `stop_profile()` wrap `jax.profiler`
start/stop_trace and degrade to no-ops when the profiler (or jax itself)
is unavailable, so `CheckerBuilder.profile(dir)` is safe on any backend.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any


def _coerce(obj: Any):
    """JSON fallback for numpy scalars and other non-JSON types."""
    try:
        return int(obj)
    except (TypeError, ValueError):
        try:
            return float(obj)
        except (TypeError, ValueError):
            return repr(obj)


class TraceWriter:
    """Append-only JSONL event stream for one checking run. Thread-safe;
    every emit is one flushed line, so traces survive hard kills."""

    def __init__(self, path: str, engine: str = ""):
        self._path = path
        self._engine = engine
        self._lock = threading.Lock()
        self._seq = 0
        self._f = open(path, "w", encoding="utf-8")

    def emit(self, event: str, **fields: Any) -> None:
        record = {
            "ts": time.time(),
            "seq": 0,
            "engine": self._engine,
            "event": event,
        }
        record.update(fields)
        with self._lock:
            if self._f.closed:
                return
            record["seq"] = self._seq
            self._seq += 1
            self._f.write(json.dumps(record, default=_coerce) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


# -- jax.profiler bracket (best-effort; no-op off-device) ---------------------

_profile_active = False
_profile_lock = threading.Lock()


def start_profile(log_dir: str) -> bool:
    """Start a jax.profiler trace into `log_dir`. Returns False (and does
    nothing) when the profiler is unavailable or already running."""
    global _profile_active
    with _profile_lock:
        if _profile_active:
            return False
        try:
            import jax.profiler

            jax.profiler.start_trace(log_dir)
        except Exception:
            return False
        _profile_active = True
        return True


def stop_profile() -> None:
    global _profile_active
    with _profile_lock:
        if not _profile_active:
            return
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception:
            pass
        _profile_active = False
