"""Structured run traces: one JSONL event per era/wave/round.

`CheckerBuilder.trace(path)` hands every engine a `TraceWriter`; the engine
emits one event per unit of forward progress (an *era* for the device
engines, a *wave*/block for the host engines, a *round* for the pbfs
coordinator, a *walk* for simulation traces) plus `run_start` / `run_end`
brackets. Lines are standalone JSON objects, flushed as written, so a
killed run still leaves a parseable prefix.

`CheckerBuilder.trace(path, format="chrome")` swaps in the
`ChromeTraceWriter`: the SAME engine-side emit() calls render as Chrome
trace-event JSON loadable in Perfetto (https://ui.perfetto.dev) or
`chrome://tracing` — each progress event becomes an instant event on the
engine's timeline, and its per-event phase-timer deltas become duration
("X") events stacked on one track per phase, so a run's wall time reads
as a flame-style lane chart. Records are flushed as written and Perfetto
tolerates a missing closing bracket, so killed runs stay loadable.

Event schema — every record carries:

  ``ts``      wall-clock seconds (time.time())
  ``seq``     per-writer monotonically increasing sequence number
  ``engine``  emitting engine class name
  ``event``   "run_start" | "era" | "wave" | "round" | "walk" | "run_end"

Progress events additionally carry ``states`` (generated total),
``unique`` (unique states so far), ``frontier`` (pending rows/jobs),
``max_depth``, and ``phase_ms`` — the per-event *delta* of each phase
timer, i.e. the milliseconds each instrumented phase consumed since the
previous event (see obs/metrics.py for the phase catalog). Device-engine
era events also carry ``load_factor``, ``take_cap``, ``steps``,
``generated``, and ``spill_rows`` for that era.

Profiling: `start_profile(dir)` / `stop_profile()` wrap `jax.profiler`
start/stop_trace and degrade to no-ops when the profiler (or jax itself)
is unavailable, so `CheckerBuilder.profile(dir)` is safe on any backend.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any


def _coerce(obj: Any):
    """JSON fallback for numpy scalars and other non-JSON types."""
    try:
        return int(obj)
    except (TypeError, ValueError):
        try:
            return float(obj)
        except (TypeError, ValueError):
            return repr(obj)


class TraceWriter:
    """Append-only JSONL event stream for one checking run. Thread-safe;
    every emit is one flushed line, so traces survive hard kills."""

    def __init__(self, path: str, engine: str = ""):
        self._path = path
        self._engine = engine
        self._lock = threading.Lock()
        self._seq = 0
        self._f = open(path, "w", encoding="utf-8")

    def emit(self, event: str, **fields: Any) -> None:
        record = {
            "ts": time.time(),
            "seq": 0,
            "engine": self._engine,
            "event": event,
        }
        record.update(fields)
        with self._lock:
            if self._f.closed:
                return
            record["seq"] = self._seq
            self._seq += 1
            self._f.write(json.dumps(record, default=_coerce) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class ChromeTraceWriter:
    """Chrome trace-event JSON writer behind the TraceWriter interface.

    Output is the Trace Event Format's "JSON Array Format": a `[` followed
    by one event object per line (comma-terminated). Perfetto and
    chrome://tracing both accept a truncated array, so every record is
    flushed as written and `close()` merely seals the bracket. Mapping:

      - every emit() becomes an instant event ("ph": "i", global scope)
        named after the engine event (era/wave/round/walk/run_start/...),
        carrying the numeric fields as args;
      - the event's ``phase_ms`` dict (per-event phase-timer DELTAS, see
        TraceWriter) additionally becomes one duration event ("ph": "X")
        per phase, ending at the emit timestamp, on a per-phase track
        (tid = the phase's name) — so phases render as parallel lanes.
    """

    def __init__(self, path: str, engine: str = ""):
        self._path = path
        self._engine = engine
        self._lock = threading.Lock()
        self._seq = 0
        self._pid = 1
        self._f = open(path, "w", encoding="utf-8")
        self._f.write("[\n")
        self._f.flush()

    def _write(self, record: dict) -> None:
        self._f.write(json.dumps(record, default=_coerce) + ",\n")

    def emit(self, event: str, **fields: Any) -> None:
        now_us = time.time() * 1e6
        phase_ms = fields.pop("phase_ms", None) or {}
        args = {"engine": self._engine, "seq": 0}
        for k, v in fields.items():
            args[k] = v
        with self._lock:
            if self._f.closed:
                return
            args["seq"] = self._seq
            self._seq += 1
            self._write(
                {
                    "name": event,
                    "ph": "i",
                    "s": "g",
                    "ts": round(now_us, 1),
                    "pid": self._pid,
                    "tid": self._engine or "engine",
                    "args": args,
                }
            )
            for phase, ms in sorted(phase_ms.items()):
                dur_us = float(ms) * 1000.0
                if dur_us <= 0:
                    continue
                self._write(
                    {
                        "name": phase,
                        "ph": "X",
                        "ts": round(now_us - dur_us, 1),
                        "dur": round(dur_us, 1),
                        "pid": self._pid,
                        "tid": phase,
                        "args": {"engine": self._engine},
                    }
                )
            self._f.flush()

    def write_counter_events(self, events) -> int:
        """Merge pre-built counter records ("ph": "C" dicts on the same
        epoch-microsecond clock as emit(); see FlightRecorder.
        chrome_counter_events) into the open trace, so the flight
        recorder's counter tracks render under the engine's phase lanes.
        Returns the number of records written."""
        with self._lock:
            if self._f.closed:
                return 0
            for record in events:
                self._write(record)
            self._f.flush()
        return len(events)

    def embed_spans(self, spans) -> int:
        """Merge completed span dicts (obs/spans.py SpanRecorder shape)
        into the open trace as B/E duration pairs. Spans use the same
        time.time()-derived microsecond clock as emit(), so one Perfetto
        file shows engine phases and request spans aligned. Returns the
        number of trace-event records written."""
        from .spans import spans_to_chrome

        events = spans_to_chrome(spans)
        with self._lock:
            if self._f.closed:
                return 0
            for record in events:
                self._write(record)
            self._f.flush()
        return len(events)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.write("{}]\n")  # sentinel closes the trailing comma
                self._f.close()


TRACE_FORMATS = ("jsonl", "chrome")


def make_trace_writer(path: str, engine: str = "", format: str = "jsonl"):
    """The writer for `CheckerBuilder.trace(path, format=...)`."""
    if format == "chrome":
        return ChromeTraceWriter(path, engine=engine)
    if format == "jsonl":
        return TraceWriter(path, engine=engine)
    raise ValueError(
        f"unknown trace format {format!r}; available: {TRACE_FORMATS}"
    )


# -- jax.profiler bracket (best-effort; no-op off-device) ---------------------

_profile_active = False
_profile_lock = threading.Lock()


def start_profile(log_dir: str) -> bool:
    """Start a jax.profiler trace into `log_dir`. Returns False (and does
    nothing) when the profiler is unavailable or already running."""
    global _profile_active
    with _profile_lock:
        if _profile_active:
            return False
        try:
            import jax.profiler

            jax.profiler.start_trace(log_dir)
        except Exception:
            return False
        _profile_active = True
        return True


def stop_profile() -> None:
    global _profile_active
    with _profile_lock:
        if not _profile_active:
            return
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception:
            pass
        _profile_active = False
