"""Unified engine observability: metrics registry + structured run traces.

Every engine owns a `MetricsRegistry` (created by `HostEngineBase`) and
populates it through one common API — counters, gauges, and monotonic phase
timers — which backs `Checker.telemetry()` uniformly across all nine
engines. `CheckerBuilder.trace(path)` additionally streams one JSONL event
per era/wave/round to disk via `TraceWriter`, and
`CheckerBuilder.profile(dir)` brackets the run with `jax.profiler` traces
when the profiler is available.

See `obs/metrics.py` for the metric-name catalog and `obs/trace.py` for the
trace event schema.
"""

from .metrics import MetricsRegistry
from .trace import TraceWriter, start_profile, stop_profile

__all__ = ["MetricsRegistry", "TraceWriter", "start_profile", "stop_profile"]
