"""Unified engine observability: metrics, coverage, and run traces.

Every engine owns a `MetricsRegistry` and a `Coverage` accumulator
(created by `HostEngineBase`) and populates them through one common API —
counters, gauges, monotonic phase timers, and per-action/per-depth/
per-property coverage tallies — backing `Checker.telemetry()` and
`Checker.coverage()` uniformly across all nine engines.
`CheckerBuilder.trace(path)` additionally streams one JSONL event per
era/wave/round to disk via `TraceWriter` (`format="chrome"` swaps in the
Perfetto-loadable `ChromeTraceWriter`), and `CheckerBuilder.profile(dir)`
brackets the run with `jax.profiler` traces when the profiler is
available. `render_prometheus` serializes any telemetry snapshot in the
Prometheus text exposition format (the Explorer serves it at
``GET /metrics?format=prometheus``).

`obs/flight.py` adds the era-granularity flight recorder: per-era
``device_era`` vs ``host_gap`` wall-time split plus frontier/table/spill
counters, populated from the packed-params readback the device engines
already do (`Checker.flight()`; `CheckerBuilder.flight()` configures it).

`obs/sample.py` adds the space profiler: deterministic bottom-k
fingerprint sampling of the explored state space (identical sample set
across engines/shards/pipelining), rendered into per-field distribution
sketches, depth/action exemplars, and a packing-saturation detector
(`Checker.space_profile()`; `CheckerBuilder.sample()` configures it).

See `stateright_tpu/obs/README.md` for the consolidated metric-name
catalog, `obs/coverage.py` for coverage-count semantics, and
`obs/trace.py` for the trace event schema.
"""

from .coverage import DEPTH_CAP, Coverage
from .flight import DEFAULT_FLIGHT_CAPACITY, FlightRecorder
from .log import get_logger
from .memory import (
    Forecaster,
    MemoryLedger,
    MemoryRecorder,
    device_memory_bytes,
    format_plan,
    plan,
    recommend_engine,
)
from .sample import (
    DEFAULT_SAMPLE_K,
    DEVICE_STEP_CAP,
    NO_ACTION,
    SLAB_PAD,
    SpaceSampler,
    build_space_profile,
    detect_saturation,
    slab_capacity,
    slab_entries,
    slab_high_water,
)
from .metrics import (
    MEMORY_SERIES_LABELS,
    NETOBS_SERIES_LABELS,
    SHARD_SERIES_LABELS,
    Histogram,
    MetricsRegistry,
    render_prometheus,
)
from .netobs import (
    DEFAULT_CAUSAL_PAST_K,
    NetObs,
    as_netobs,
    assign_lamport,
    causal_order,
    causal_past,
    deployment_view,
    export_chrome_trace,
    flow_pairs,
    format_event,
)
from .spans import SpanRecorder, attach_phase_spans, new_span_id, new_trace_id
from .stageprof import STAGE_ORDER, stage_rows
from .trace import (
    ChromeTraceWriter,
    TraceWriter,
    make_trace_writer,
    start_profile,
    stop_profile,
)

__all__ = [
    "DEFAULT_CAUSAL_PAST_K",
    "DEFAULT_FLIGHT_CAPACITY",
    "DEFAULT_SAMPLE_K",
    "DEPTH_CAP",
    "DEVICE_STEP_CAP",
    "NO_ACTION",
    "SLAB_PAD",
    "ChromeTraceWriter",
    "Coverage",
    "SpaceSampler",
    "build_space_profile",
    "detect_saturation",
    "slab_capacity",
    "slab_entries",
    "slab_high_water",
    "FlightRecorder",
    "Forecaster",
    "Histogram",
    "MEMORY_SERIES_LABELS",
    "MemoryLedger",
    "MemoryRecorder",
    "MetricsRegistry",
    "NETOBS_SERIES_LABELS",
    "NetObs",
    "SHARD_SERIES_LABELS",
    "STAGE_ORDER",
    "SpanRecorder",
    "TraceWriter",
    "as_netobs",
    "assign_lamport",
    "attach_phase_spans",
    "causal_order",
    "causal_past",
    "deployment_view",
    "device_memory_bytes",
    "export_chrome_trace",
    "flow_pairs",
    "format_event",
    "format_plan",
    "get_logger",
    "make_trace_writer",
    "new_span_id",
    "new_trace_id",
    "plan",
    "recommend_engine",
    "render_prometheus",
    "stage_rows",
    "start_profile",
    "stop_profile",
]
