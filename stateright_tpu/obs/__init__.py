"""Unified engine observability: metrics, coverage, and run traces.

Every engine owns a `MetricsRegistry` and a `Coverage` accumulator
(created by `HostEngineBase`) and populates them through one common API —
counters, gauges, monotonic phase timers, and per-action/per-depth/
per-property coverage tallies — backing `Checker.telemetry()` and
`Checker.coverage()` uniformly across all nine engines.
`CheckerBuilder.trace(path)` additionally streams one JSONL event per
era/wave/round to disk via `TraceWriter` (`format="chrome"` swaps in the
Perfetto-loadable `ChromeTraceWriter`), and `CheckerBuilder.profile(dir)`
brackets the run with `jax.profiler` traces when the profiler is
available. `render_prometheus` serializes any telemetry snapshot in the
Prometheus text exposition format (the Explorer serves it at
``GET /metrics?format=prometheus``).

See `obs/metrics.py` for the metric-name catalog, `obs/coverage.py` for
coverage-count semantics, and `obs/trace.py` for the trace event schema.
"""

from .coverage import DEPTH_CAP, Coverage
from .log import get_logger
from .metrics import Histogram, MetricsRegistry, render_prometheus
from .spans import SpanRecorder, attach_phase_spans, new_span_id, new_trace_id
from .stageprof import STAGE_ORDER, stage_rows
from .trace import (
    ChromeTraceWriter,
    TraceWriter,
    make_trace_writer,
    start_profile,
    stop_profile,
)

__all__ = [
    "DEPTH_CAP",
    "ChromeTraceWriter",
    "Coverage",
    "Histogram",
    "MetricsRegistry",
    "STAGE_ORDER",
    "SpanRecorder",
    "TraceWriter",
    "attach_phase_spans",
    "get_logger",
    "make_trace_writer",
    "new_span_id",
    "new_trace_id",
    "render_prometheus",
    "stage_rows",
    "start_profile",
    "stop_profile",
]
