"""Space profiler: deterministic bottom-k state sampling + field sketches.

The observability stack answers *how fast* and *how big* a check is
(metrics, flight recorder, memory ledger); this module answers *what the
checker is actually exploring*. It keeps a small uniform sample of the
explored state space and renders it into a `SpaceProfile`: per-field
value-distribution sketches, per-depth exemplar states, per-action
exemplar transitions, and a packing-saturation detector.

Determinism is the load-bearing property. The sampler is **bottom-k over
the existing 64-bit state fingerprints**: a state is sampled iff its
fingerprint is among the k smallest seen — equivalently, iff it falls
below an adaptive threshold (the current kth-smallest fingerprint). The
fingerprints are bit-identical on host and device (fingerprint.py), so
the sample set is a pure function of the EXPLORED SET:

  - independent of visitation order (BFS vs DFS vs vectorized waves),
  - independent of engine (host `bfs` and `tpu_bfs` produce the
    *identical* sample set on the same model — locked by tests),
  - independent of shard layout (mesh shards each keep a local bottom-k
    and the host merges by trivial bottom-k union, no psum needed),
  - independent of pipelining (a speculative chained era filters against
    a STALE threshold, which only admits a superset of candidates; the
    host-side bottom-k discards the excess — same final set).

Because fingerprints are uniform in [0, 2^64), a bottom-k sample is a
uniform sample of distinct states, and the kth-smallest fingerprint
doubles as a distinct-count estimator (the classic KMV/bottom-k sketch):
``est ≈ (k-1) * 2^64 / kth_fp``.

Device engines (tpu_bfs, tpu_simulation, the sharded mesh) capture
candidates in a small fixed on-device slab drained on the existing
once-per-era packed-params readback (the flight-recorder pattern — zero
extra round-trips). The per-era drain keeps only the bottom-k'' of that
era's candidates, which is exact for the global bottom-k: any global
bottom-k member has fewer than k candidates below it *anywhere*, hence
fewer than k below it within its own era. Device selection ranks by the
high fingerprint word only (no 64-bit compare on TPU), so the drain
carries ``SLAB_PAD`` extra entries and `SpaceSampler.drain_slab` applies
a *tie cut*: when an era had more candidates than drained entries, the
entries at the boundary h1 value are discarded (the set strictly below
the cut is exact). Losing a true bottom-k member that way would need
more than SLAB_PAD states sharing one 32-bit fingerprint prefix inside
one era — the sampler flags ``degraded`` if that astronomically unlikely
event ever happens, rather than silently lying.

Host engines offer every visited-set insertion through
`HostEngineBase`; the threshold check is one integer compare, and the
sample dict only mutates ~k·ln(N/k) times over a whole run.

The saturation detector (`detect_saturation`) is shared between the
runtime profile and speclint's static STR209 rule: a state lane whose
sampled maximum sits exactly at a natural packing boundary (2^b - 1 for
b in 8/16/24/32) is one increment away from silently wrapping its
uint32 packing — the runtime twin of the STR207 overflow check.
"""

from __future__ import annotations

import heapq
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

# Default sample size: small enough that host-side profile building and
# the device slab stay trivial, large enough for meaningful field sketches.
DEFAULT_SAMPLE_K = 64
# Extra drained entries per era beyond k: slack for h1-only device ranking
# (ties at the 32-bit boundary are resolved host-side by the tie cut).
SLAB_PAD = 64
# Device per-step candidate compaction width (tpu_bfs / mesh): candidates
# per loop step are compacted to this many slots before the slab scatter.
# Pre-threshold floods clamp `take` so a step never produces more.
DEVICE_STEP_CAP = 512
# Action sentinel for samples whose generating action is unknown
# (simulation walks, mesh receives — the action is not exchanged).
NO_ACTION = 0xFFFFFFFF

_MAX64 = (1 << 64) - 1
_U32 = 0xFFFFFFFF


def slab_entries(k: int) -> int:
    """Entries drained per era: k plus the h1-tie slack."""
    return int(k) + SLAB_PAD


def slab_high_water(k: int) -> int:
    """Era-exit occupancy gate: the loop exits (and re-enters after the
    host drain) once this many candidates accumulated, so a slab is never
    asked to hold an unbounded flood."""
    return max(2 * slab_entries(k), 512)


def slab_capacity(k: int, step_cap: int) -> int:
    """On-device slab rows: the high-water mark plus one full step's
    worth of captures (the gate is checked BEFORE the step that may
    overshoot it, so every write is guaranteed to fit)."""
    return slab_high_water(k) + int(step_cap)


# -- saturation (shared: runtime profile + speclint STR209) ------------------

# Natural packing boundaries: a sampled lane maxing out at 2^b - 1 for one
# of these widths is presumed packed in b bits and one step from wrapping.
SATURATION_BITS = (8, 16, 24, 32)


def detect_saturation(rows) -> List[Dict[str, int]]:
    """Lanes of ``rows`` ([N, S] uint32 state rows) whose observed maximum
    sits exactly at a packing boundary ``2^b - 1`` (b in SATURATION_BITS).

    Returns ``[{"lane", "bits", "max", "hits"}]`` — ``hits`` counts the
    sampled states AT the boundary value. Shared by the runtime space
    profile (`build_space_profile`) and speclint STR209, so the static
    pre-flight and the live run flag the same condition.
    """
    rows = np.asarray(rows)
    if rows.ndim != 2 or rows.size == 0:
        return []
    out: List[Dict[str, int]] = []
    for lane in range(rows.shape[1]):
        col = rows[:, lane]
        mx = int(col.max())
        for bits in SATURATION_BITS:
            if mx == (1 << bits) - 1:
                out.append(
                    {
                        "lane": lane,
                        "bits": bits,
                        "max": mx,
                        "hits": int((col == mx).sum()),
                    }
                )
                break
    return out


# -- the sampler --------------------------------------------------------------


class SpaceSampler:
    """Thread-safe exact bottom-k fingerprint sampler.

    Keeps the k smallest 64-bit fingerprints offered, with one record per
    sample: depth at first insertion, the generating action (when known),
    and the state row / predecessor row (when the offering engine has
    them in hand; device bottom-k drains carry fingerprints only and the
    rows are resolved lazily at profile-build time).
    """

    def __init__(self, k: int = DEFAULT_SAMPLE_K, enabled: bool = True):
        self.k = max(1, int(k))
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._samples: Dict[int, Dict[str, Any]] = {}
        self._heap: List[int] = []  # max-heap of kept fps (negated)
        self.offered = 0  # states seen by the offering engines
        self.candidates = 0  # offers below the then-current threshold
        self.device_drops = 0  # device slab overflow drops (sdrop)
        self.degraded = False  # tie-cut retained < k (see module doc)

    # -- threshold ----------------------------------------------------------

    def threshold(self) -> int:
        """Exclusive upper bound: a fingerprint is a candidate iff
        ``fp < threshold()``. 2^64 - 1 until the sample is full, then the
        current kth-smallest (= largest kept) fingerprint. Monotonically
        non-increasing, so a stale (looser) threshold only ever admits a
        superset of candidates — the basis of pipelined-era soundness."""
        with self._lock:
            return self._threshold_locked()

    def _threshold_locked(self) -> int:
        if len(self._samples) < self.k:
            return _MAX64
        return -self._heap[0]

    def threshold_parts(self) -> tuple:
        """(high, low) uint32 words of `threshold()` for device upload."""
        t = self.threshold()
        return (t >> 32) & _U32, t & _U32

    # -- offering -----------------------------------------------------------

    def offer(
        self,
        fp: int,
        depth: int = 0,
        action: Any = None,
        state: Any = None,
        pred: Any = None,
    ) -> bool:
        """Offer one inserted state. Returns True iff it (currently)
        entered the sample. `state`/`pred` are whatever the engine has in
        hand — uint32 row tuples for tensor engines, rich state objects
        for host models, or None (resolved later)."""
        if not self.enabled:
            return False
        fp = int(fp)
        with self._lock:
            self.offered += 1
            if len(self._samples) >= self.k and fp >= -self._heap[0]:
                return False
            self.candidates += 1
            if fp in self._samples:
                # Same state re-offered (simulation revisits, device
                # re-drains): first record wins, richer fields backfill.
                rec = self._samples[fp]
                if rec.get("state") is None and state is not None:
                    rec["state"] = state
                    rec["pred"] = pred
                    rec["action"] = action
                return False
            self._samples[fp] = {
                "fp": fp,
                "depth": int(depth),
                "action": action,
                "state": state,
                "pred": pred,
            }
            heapq.heappush(self._heap, -fp)
            if len(self._samples) > self.k:
                evicted = -heapq.heappop(self._heap)
                del self._samples[evicted]
            return True

    def note_offered(self, n: int) -> None:
        """Device engines: count states that were threshold-filtered on
        device (they never reach `offer`) toward the offered total."""
        if self.enabled and n:
            with self._lock:
                self.offered += int(n)

    def offer_array(
        self,
        fps,
        depths=None,
        states=None,
        preds=None,
        actions=None,
    ) -> None:
        """Vectorized offer (vbfs wave inserts): pre-filters by threshold
        with one array compare, then offers survivors individually."""
        if not self.enabled:
            return
        fps = np.asarray(fps, dtype=np.uint64)
        n = int(fps.size)
        if not n:
            return
        t = self.threshold()
        if t >= _MAX64:
            idx = np.arange(n)
        else:
            idx = np.flatnonzero(fps < np.uint64(t))
        with self._lock:
            self.offered += n - int(idx.size)
        for i in idx:
            i = int(i)
            self.offer(
                int(fps[i]),
                depth=int(depths[i]) if depths is not None else 0,
                action=actions[i] if actions is not None else None,
                state=(
                    tuple(int(v) for v in states[i])
                    if states is not None
                    else None
                ),
                pred=(
                    tuple(int(v) for v in preds[i])
                    if preds is not None
                    else None
                ),
            )

    def drain_slab(
        self,
        fp1,
        fp2,
        depths,
        ok,
        occupied: int,
        dropped: int = 0,
        actions=None,
        states=None,
        exact: bool = True,
    ) -> None:
        """Consume one era's device slab drain.

        ``fp1``/``fp2``/``depths`` (+ optional ``actions`` / ``states``
        [n, S] rows) are the drained entry lanes, ``ok`` the validity
        mask (1 for written slab slots, 0 for padding), ``occupied`` the
        era's true candidate count and ``dropped`` its slab-overflow
        drop count. Applies the h1 tie cut (module doc) before offering:
        when the era produced more candidates than were drained, entries
        AT the boundary h1 value may be an incomplete tie group, so only
        the exact set strictly below the cut is kept.

        ``exact=False`` skips the tie cut: for engines whose slab can
        hold DUPLICATE fingerprints (the simulation engine — walks
        revisit states, and there is no visited table to make captures
        once-only), ``occupied > n_valid`` usually means duplicates, not
        truncation, and the cut would starve the sample by forever
        discarding the boundary group. Those engines' samples are
        best-effort by nature (their visited set is itself stochastic).
        """
        if not self.enabled:
            return
        fp1 = np.asarray(fp1, dtype=np.uint64)
        fp2 = np.asarray(fp2, dtype=np.uint64)
        valid = np.asarray(ok).astype(bool)
        occupied = int(occupied)
        if dropped:
            with self._lock:
                self.device_drops += int(dropped)
        n_valid = int(valid.sum())
        if not n_valid:
            return
        if exact and occupied > n_valid:
            cut = int(fp1[valid].max())
            keep = valid & (fp1 < np.uint64(cut))
            if int(keep.sum()) < self.k:
                self.degraded = True
            valid = keep
        for i in np.flatnonzero(valid):
            i = int(i)
            fp = (int(fp1[i]) << 32) | int(fp2[i])
            act = int(actions[i]) if actions is not None else NO_ACTION
            self.offer(
                fp,
                depth=int(depths[i]),
                action=None if act == NO_ACTION else act,
                state=(
                    tuple(int(v) for v in states[i])
                    if states is not None
                    else None
                ),
            )

    def merge_records(self, records: Sequence[Dict[str, Any]]) -> None:
        """Bottom-k union: fold another sampler's records in (mesh shard
        merge, checkpoint restore, pbfs worker-table merge)."""
        for rec in records:
            self.offer(
                rec["fp"],
                depth=rec.get("depth", 0),
                action=rec.get("action"),
                state=rec.get("state"),
                pred=rec.get("pred"),
            )

    # -- queries ------------------------------------------------------------

    def size(self) -> int:
        with self._lock:
            return len(self._samples)

    def fingerprints(self) -> List[int]:
        """The sampled fingerprints, ascending — THE deterministic object
        (equal across engines/shards/pipelining on the same explored
        set; what the parity tests compare)."""
        with self._lock:
            return sorted(self._samples)

    def records(self) -> List[Dict[str, Any]]:
        """Sample records ordered by fingerprint (deterministic)."""
        with self._lock:
            return [dict(self._samples[fp]) for fp in sorted(self._samples)]

    def estimated_states(self) -> int:
        """KMV distinct-count estimate of the explored space: exact below
        k, else ``(k-1) * 2^64 / kth_smallest_fp``."""
        with self._lock:
            n = len(self._samples)
            if n < self.k:
                return n
            kth = -self._heap[0]
            return int((self.k - 1) * float(2**64) / float(max(kth, 1)))

    def snapshot(self) -> Dict[str, Any]:
        """Light summary backing ``telemetry()["space"]`` (no state
        decode — safe to poll mid-run)."""
        with self._lock:
            n = len(self._samples)
            t = self._threshold_locked()
        return {
            "k": self.k,
            "samples": n,
            # str: 64-bit values stay exact through JSON round-trips.
            "threshold": str(t),
            "est_states": self.estimated_states(),
            "offered": self.offered,
            "candidates": self.candidates,
            "device_drops": self.device_drops,
            "degraded": self.degraded,
        }

    def set_gauges(self, metrics) -> None:
        """Flat ``space_*`` twins for Prometheus/SSE (obs/metrics.py
        catalog; nested telemetry docs are skipped by render_prometheus)."""
        metrics.set_gauge("space_sample_k", self.k)
        metrics.set_gauge("space_samples", self.size())
        metrics.set_gauge("space_est_states", self.estimated_states())
        metrics.set_gauge("space_offered", self.offered)
        metrics.set_gauge("space_candidates", self.candidates)
        metrics.set_gauge("space_device_drops", self.device_drops)
        metrics.set_gauge("space_degraded", int(self.degraded))

    # -- checkpointing ------------------------------------------------------

    def export_state(self) -> Dict[str, Any]:
        """JSON-safe sampler state for checkpoint meta: kill -> resume
        must restore the threshold and kept set exactly, or the resumed
        run's sample set would diverge from an uninterrupted one."""
        recs = []
        for rec in self.records():
            recs.append(
                {
                    "fp": str(rec["fp"]),
                    "depth": int(rec["depth"]),
                    "action": (
                        int(rec["action"])
                        if isinstance(rec["action"], (int, np.integer))
                        else None
                    ),
                    "state": (
                        [int(v) for v in rec["state"]]
                        if isinstance(rec["state"], (tuple, list))
                        else None
                    ),
                }
            )
        return {
            "k": self.k,
            "records": recs,
            "offered": self.offered,
            "candidates": self.candidates,
            "device_drops": self.device_drops,
            "degraded": bool(self.degraded),
        }

    def restore_state(self, st: Dict[str, Any]) -> None:
        if not st:
            return
        with self._lock:
            self._samples.clear()
            self._heap = []
        for rec in st.get("records", ()):
            self.offer(
                int(rec["fp"]),
                depth=rec.get("depth", 0),
                action=rec.get("action"),
                state=(
                    tuple(rec["state"]) if rec.get("state") is not None else None
                ),
            )
        with self._lock:
            self.offered = int(st.get("offered", 0))
            self.candidates = int(st.get("candidates", 0))
            self.device_drops = int(st.get("device_drops", 0))
            self.degraded = bool(st.get("degraded", False))


# -- profile building ---------------------------------------------------------

# Field-flattening caps: a pathological decode_state cannot balloon the
# profile (leaves beyond the cap are dropped, counted in "fields_dropped").
_MAX_FIELDS = 64
_MAX_FLATTEN_DEPTH = 3


def _flatten_fields(value, prefix: str, out: Dict[str, Any], depth: int) -> None:
    """Decompose a decoded state into named scalar leaves, mirroring the
    precedence of path._state_fields (dataclass -> namedtuple -> dict ->
    sequence -> scalar) but keeping RAW values for sketching."""
    import dataclasses

    if len(out) >= _MAX_FIELDS:
        return
    if depth < _MAX_FLATTEN_DEPTH:
        if dataclasses.is_dataclass(value) and not isinstance(value, type):
            for k, v in vars(value).items():
                name = f"{prefix}{k}"
                if _is_composite(v):
                    _flatten_fields(v, name + ".", out, depth + 1)
                else:
                    _leaf(out, name, v)
            return
        if hasattr(value, "_asdict"):  # namedtuple
            for k, v in value._asdict().items():
                name = f"{prefix}{k}"
                if _is_composite(v):
                    _flatten_fields(v, name + ".", out, depth + 1)
                else:
                    _leaf(out, name, v)
            return
        if isinstance(value, dict):
            for k, v in value.items():
                name = f"{prefix}{k}"
                if _is_composite(v):
                    _flatten_fields(v, name + ".", out, depth + 1)
                else:
                    _leaf(out, name, v)
            return
        if isinstance(value, (tuple, list)) or (
            isinstance(value, np.ndarray) and value.ndim == 1
        ):
            for i, v in enumerate(value):
                name = f"{prefix}[{i}]" if prefix else f"[{i}]"
                if _is_composite(v):
                    _flatten_fields(v, name + ".", out, depth + 1)
                else:
                    _leaf(out, name, v)
            return
    _leaf(out, prefix or "state", value)


def _is_composite(v) -> bool:
    import dataclasses

    return (
        (dataclasses.is_dataclass(v) and not isinstance(v, type))
        or hasattr(v, "_asdict")
        or isinstance(v, (dict, tuple, list))
        or (isinstance(v, np.ndarray) and v.ndim >= 1)
    )


def _leaf(out: Dict[str, Any], name: str, v: Any) -> None:
    if len(out) >= _MAX_FIELDS:
        return
    # Strip trailing "." left by dataclass recursion on scalar members.
    out[name.rstrip(".")] = v


def _decoded(model, rec) -> Any:
    """Human view of a sample's state: decode_state for tensor-backed
    rows (the same view the Explorer uses), the raw object otherwise."""
    state = rec.get("state")
    if state is None:
        return None
    tm = getattr(model, "tm", None)
    if tm is not None and hasattr(tm, "decode_state"):
        try:
            return tm.decode_state(np.asarray(state, dtype=np.uint32))
        except Exception:
            return state
    return state


class _FieldSketch:
    """Distribution sketch of one decoded field over the sample: exact
    below k samples (the sample IS the population for tiny spaces —
    locked by the sketch-exactness test), a uniform-sample sketch above."""

    __slots__ = ("kind", "count", "vmin", "vmax", "values", "true", "false")

    def __init__(self):
        self.kind = None  # "int" | "bool" | "other"
        self.count = 0
        self.vmin = None
        self.vmax = None
        self.values: set = set()
        self.true = 0
        self.false = 0

    def add(self, v: Any) -> None:
        self.count += 1
        if isinstance(v, (bool, np.bool_)):
            self.kind = self.kind or "bool"
            if v:
                self.true += 1
            else:
                self.false += 1
            if len(self.values) < 4096:
                self.values.add(bool(v))
            return
        if isinstance(v, (int, np.integer)):
            self.kind = "int" if self.kind in (None, "int", "bool") else self.kind
            v = int(v)
            self.vmin = v if self.vmin is None else min(self.vmin, v)
            self.vmax = v if self.vmax is None else max(self.vmax, v)
            if len(self.values) < 4096:
                self.values.add(v)
            return
        self.kind = "other"
        if len(self.values) < 4096:
            self.values.add(repr(v))

    def render(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "kind": self.kind or "other",
            "count": self.count,
            "distinct": len(self.values),
        }
        if self.kind == "bool":
            out["true"] = self.true
            out["false"] = self.false
        elif self.kind == "int":
            out["min"] = self.vmin
            out["max"] = self.vmax
            # Log2-bucketed histogram: bucket b holds values with
            # bit_length b (0 -> bucket 0, 1 -> 1, 2..3 -> 2, ...).
            hist: Dict[str, int] = {}
            for v in sorted(self.values):
                b = str(int(v).bit_length() if v > 0 else 0)
                hist[b] = hist.get(b, 0) + 1
            out["log2_hist"] = hist
        return out


def build_space_profile(
    model,
    sampler: SpaceSampler,
    resolver: Optional[Callable[[int], Optional[Dict[str, Any]]]] = None,
) -> Dict[str, Any]:
    """Render a sampler's kept set into the SpaceProfile document behind
    `Checker.space_profile()` / the Explorer's ``GET /space``.

    ``resolver(fp) -> {"state":..., "pred":..., "action":...} | None``
    backfills rows for samples captured fingerprint-only (device bottom-k
    drains); the device engines pass their path reconstructor.
    """
    if sampler is None or not sampler.enabled:
        return {}
    recs = sampler.records()
    profile: Dict[str, Any] = dict(sampler.snapshot())
    profile["fingerprints"] = [str(r["fp"]) for r in recs]
    if not recs:
        profile.update(fields={}, depths={}, actions={}, saturated=[])
        return profile

    unresolved = 0
    for rec in recs:
        if rec.get("state") is None and resolver is not None:
            try:
                extra = resolver(rec["fp"])
            except Exception:
                extra = None
            if extra:
                rec.update(
                    {k: v for k, v in extra.items() if v is not None}
                )
        if rec.get("state") is None:
            unresolved += 1
    profile["unresolved"] = unresolved

    # -- field sketches over the decoded sample ----------------------------
    sketches: Dict[str, _FieldSketch] = {}
    rows: List[Any] = []
    for rec in recs:
        decoded = _decoded(model, rec)
        if decoded is None:
            continue
        state = rec.get("state")
        if isinstance(state, (tuple, list)) and all(
            isinstance(v, (int, np.integer)) for v in state
        ):
            rows.append(state)
        leaves: Dict[str, Any] = {}
        _flatten_fields(decoded, "", leaves, 0)
        rec["_fields"] = leaves
        for name, v in leaves.items():
            sketches.setdefault(name, _FieldSketch()).add(v)
    profile["fields"] = {
        name: sk.render() for name, sk in sorted(sketches.items())
    }

    # -- packing saturation (raw uint32 lanes; shared with STR209) ---------
    saturated = detect_saturation(np.asarray(rows, dtype=np.uint64)) if rows else []
    # Best-effort lane -> decoded-field naming: when the decode flattens
    # positionally (one leaf per lane), the lane index maps to its name.
    names = list(sketches)
    width = len(rows[0]) if rows else 0
    for ent in saturated:
        if len(names) == width:
            ent["field"] = names[ent["lane"]]
    profile["saturated"] = saturated

    # -- per-depth exemplars (min-fp state at each depth: deterministic) ---
    depths: Dict[int, Dict[str, Any]] = {}
    for rec in recs:  # recs are fp-ascending, so first-seen is min-fp
        d = int(rec["depth"])
        ent = depths.setdefault(d, {"count": 0})
        ent["count"] += 1
        if "exemplar_fp" not in ent and rec.get("_fields"):
            ent["exemplar_fp"] = str(rec["fp"])
            ent["exemplar"] = {
                k: repr(v) for k, v in rec["_fields"].items()
            }
    profile["depths"] = {str(d): depths[d] for d in sorted(depths)}

    # -- per-action exemplar transitions -----------------------------------
    actions: Dict[str, Dict[str, Any]] = {}
    for rec in recs:
        act = rec.get("action")
        if act is None:
            continue
        try:
            label = model.format_action(act)
        except Exception:
            label = repr(act)
        ent = actions.setdefault(label, {"count": 0})
        ent["count"] += 1
        if "exemplar" in ent or rec.get("pred") is None:
            continue
        exemplar: Dict[str, Any] = {
            "fp": str(rec["fp"]),
            "action": label,
        }
        try:
            from ..path import Path, _state_fields

            pred, succ = rec["pred"], rec["state"]
            exemplar["pred"] = _state_fields(model, pred)
            exemplar["succ"] = _state_fields(model, succ)
            exemplar["explain"] = Path([(pred, act), (succ, None)]).explain(
                model
            )
        except Exception:
            pass
        ent["exemplar"] = exemplar
    profile["actions"] = dict(sorted(actions.items()))
    return profile
