"""Era-granularity flight recorder: what is the search doing, era by era?

The device engines already sync with the host exactly once per era — one
packed uint32 params readback. The flight recorder turns that existing
sync point into a bounded in-memory ring of per-era records at ZERO
extra device round-trips: every field below is either a value the engine
just read out of the packed params vector or a host-side wall-clock
delta.

The load-bearing split is per-era wall time into

  ``device_era_secs``  the dispatch + readback wait the engine measured
                       around its era block, and
  ``host_gap_secs``    everything else since the previous readback —
                       host bookkeeping, spill/refill uploads,
                       checkpoint writes, and the dispatch launch
                       latency itself (wall minus device time).

ROADMAP item 1 claims the engines are dispatch/launch-bound, not
bandwidth-bound; ``host_gap_secs`` is the direct per-era measurement of
that claim, and the instrument any mega-era/dispatch-pipelining work
must attribute its gains against. The accounting is OVERLAP-AWARE: with
speculative era pipelining the engine reports each era's MARGINAL
device time (readback-to-readback), but a clock skew or an engine that
reports a device span larger than the wall delta since the previous
record books the excess as ``overlap_secs`` instead of silently
clamping. By construction every record satisfies

    device_era_secs - overlap_secs + host_gap_secs == wall_secs

(both the gap and the overlap are clamped at zero, exactly one of them
is nonzero), and bench.py asserts the run-level
``device - overlap + gap`` sum reconciles with the externally timed
wall clock within 5%.

One record per era::

    {"era": 17, "ts": 3.71, "wall_secs": 0.21,
     "device_era_secs": 0.19, "host_gap_secs": 0.02,
     "overlap_secs": 0.0,
     "steps": 12, "generated": 48210, "unique": 181032,
     "frontier": 52104, "load_factor": 0.173, "take_cap": 6144,
     "spill_rows": 0, "refill_rows": 0, "table_growths": 0,
     "checkpoint_saves": 0}

The sharded engine additionally attaches a ``shards`` dict mapping
shard index -> ``{"frontier", "load_factor", "exchange_rows"}`` so
cross-shard imbalance is visible record by record. With the memory
ledger on (obs/memory.py, the default), each record also carries a
``memory`` dict — bytes by component, headroom, and the forecaster's
grow/exhaustion horizons — derived from the same readback.

Surfaces: ``Checker.flight()`` returns the records,
``telemetry()["flight"]`` carries the summary (which also rides the SSE
``event: metrics`` stream and, via flat ``flight_*`` gauges, Prometheus),
``export_jsonl`` / ``chrome_counter_events`` feed the same files
``.trace()`` writes (Perfetto renders the counter events as stacked
counter tracks under the engine's phase lanes), and the Explorer serves
``GET /flight`` for its timeline panel.
"""

import json
import threading
import time
from collections import deque

__all__ = ["DEFAULT_FLIGHT_CAPACITY", "FlightRecorder"]

DEFAULT_FLIGHT_CAPACITY = 4096


class FlightRecorder:
    """Bounded ring of per-era flight records (thread-safe).

    The ring keeps the most recent ``capacity`` records; the summary
    totals (era count, device/gap/wall seconds) accumulate across the
    whole run regardless of eviction, so ``summary()`` stays exact even
    after the ring wraps (``dropped`` says how many records fell off).
    """

    def __init__(self, capacity=DEFAULT_FLIGHT_CAPACITY, engine="engine"):
        if int(capacity) < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.engine = str(engine)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._eras = 0
        self._dropped = 0
        self._t_start = None  # monotonic run origin
        self._t_last = None  # monotonic timestamp of the last record
        self._wall0 = None  # epoch pair of _t_start (Chrome ts alignment)
        self._device_secs = 0.0
        self._gap_secs = 0.0
        self._overlap_secs = 0.0
        self._wall_secs = 0.0

    def start(self, t=None):
        """Mark the run origin; the first era's host gap is measured
        from here (so seeding uploads and the first dispatch latency
        land in the recording instead of vanishing)."""
        now = time.monotonic() if t is None else float(t)
        with self._lock:
            self._t_start = now
            self._t_last = now
            self._wall0 = time.time() - (time.monotonic() - now)

    def record(
        self,
        *,
        device_era_secs,
        steps=0,
        generated=0,
        unique=0,
        frontier=0,
        load_factor=0.0,
        take_cap=0,
        spill_rows=0,
        refill_rows=0,
        table_growths=0,
        checkpoint_saves=0,
        shards=None,
        memory=None,
        t=None,
    ):
        """Append one era record; returns the record dict."""
        now = time.monotonic() if t is None else float(t)
        device = max(0.0, float(device_era_secs))
        with self._lock:
            if self._t_last is None:
                # Engine skipped start(): anchor the origin so the first
                # record's wall time equals its device time (zero gap).
                self._t_start = now - device
                self._t_last = self._t_start
                self._wall0 = time.time() - device
            wall = max(0.0, now - self._t_last)
            gap = max(0.0, wall - device)
            # Overlap-aware split: device time in excess of the wall delta
            # (a pipelined engine's dispatch overlapping the previous
            # readback, or a clock hiccup) is booked explicitly rather
            # than clamped away, keeping device-overlap+gap == wall exact.
            overlap = max(0.0, device - wall)
            self._t_last = now
            self._eras += 1
            rec = {
                "era": self._eras,
                "ts": round(now - self._t_start, 6),
                "wall_secs": round(wall, 6),
                "device_era_secs": round(device, 6),
                "host_gap_secs": round(gap, 6),
                "overlap_secs": round(overlap, 6),
                "steps": int(steps),
                "generated": int(generated),
                "unique": int(unique),
                "frontier": int(frontier),
                "load_factor": float(load_factor),
                "take_cap": int(take_cap),
                "spill_rows": int(spill_rows),
                "refill_rows": int(refill_rows),
                "table_growths": int(table_growths),
                "checkpoint_saves": int(checkpoint_saves),
            }
            if shards:
                rec["shards"] = shards
            if memory:
                rec["memory"] = memory
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(rec)
            self._device_secs += device
            self._gap_secs += gap
            self._overlap_secs += overlap
            self._wall_secs += wall
            return rec

    def record_fused(
        self,
        *,
        device_era_secs,
        inner,
        take_cap=0,
        spill_rows=0,
        refill_rows=0,
        table_growths=0,
        checkpoint_saves=0,
        shards=None,
        memory=None,
        t=None,
    ):
        """Split ONE fused dispatch into ``len(inner)`` consecutive era
        records; returns the last record dict.

        ``inner`` is the per-inner-era attribution the engine read from
        the fusion tail — dicts with ``steps``/``generated``/``unique``/
        ``frontier``/``load_factor``. The dispatch's wall window (since
        the previous record) and its measured device time are
        apportioned by each inner era's share of the executed steps
        (evenly when no steps ran), with the LAST record pinned to the
        true readback timestamp and the device remainder — so the
        per-record ``device - overlap + gap == wall`` identity AND the
        run-level totals stay exact: N fused records reconcile to the
        same sums as N serial eras. Counter fields that happen once per
        dispatch (spill/refill/growths/checkpoints, shards, memory) land
        on the last record only, mirroring where the host work actually
        sits.
        """
        n = len(inner)
        if n <= 1:
            r0 = dict(inner[0]) if inner else {}
            return self.record(
                device_era_secs=device_era_secs,
                take_cap=take_cap,
                spill_rows=spill_rows,
                refill_rows=refill_rows,
                table_growths=table_growths,
                checkpoint_saves=checkpoint_saves,
                shards=shards,
                memory=memory,
                t=t,
                **r0,
            )
        now = time.monotonic() if t is None else float(t)
        device = max(0.0, float(device_era_secs))
        with self._lock:
            t_prev = self._t_last
        if t_prev is None:
            t_prev = now - device  # same anchoring record() would apply
        wall = max(0.0, now - t_prev)
        tot = sum(max(0, int(r.get("steps", 0))) for r in inner)
        cumw = 0.0
        dev_used = 0.0
        last = None
        for j, r in enumerate(inner):
            w = (
                max(0, int(r.get("steps", 0))) / tot if tot else 1.0 / n
            )
            cumw += w
            is_last = j == n - 1
            t_j = now if is_last else t_prev + wall * cumw
            d_j = (device - dev_used) if is_last else device * w
            dev_used += d_j
            last = self.record(
                device_era_secs=d_j,
                steps=int(r.get("steps", 0)),
                generated=int(r.get("generated", 0)),
                unique=int(r.get("unique", 0)),
                frontier=int(r.get("frontier", 0)),
                load_factor=float(r.get("load_factor", 0.0)),
                take_cap=take_cap,
                spill_rows=spill_rows if is_last else 0,
                refill_rows=refill_rows if is_last else 0,
                table_growths=table_growths if is_last else 0,
                checkpoint_saves=checkpoint_saves if is_last else 0,
                shards=shards if is_last else None,
                memory=memory if is_last else None,
                t=t_j,
            )
        return last

    def records(self):
        """Copies of the retained records, oldest first."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def summary(self):
        """Run-level totals (exact even after the ring wraps)."""
        with self._lock:
            wall = self._wall_secs
            return {
                "eras": self._eras,
                "recorded": len(self._ring),
                "dropped": self._dropped,
                "capacity": self.capacity,
                "device_secs": round(self._device_secs, 6),
                "host_gap_secs": round(self._gap_secs, 6),
                "overlap_secs": round(self._overlap_secs, 6),
                "wall_secs": round(wall, 6),
                "host_gap_pct": (
                    round(100.0 * self._gap_secs / wall, 2) if wall else 0.0
                ),
                "mean_era_secs": (
                    round(wall / self._eras, 6) if self._eras else 0.0
                ),
            }

    # -- export ----------------------------------------------------------

    def export_jsonl(self, path):
        """One JSON line per retained record, then a final summary line
        (``{"summary": ..., "engine": ...}``) — same flush-as-written
        discipline as the run trace."""
        with open(path, "w") as f:
            for rec in self.records():
                f.write(json.dumps(rec) + "\n")
            f.write(
                json.dumps({"summary": self.summary(), "engine": self.engine})
                + "\n"
            )

    def chrome_counter_events(self, pid=1):
        """Chrome trace-event counter samples ("ph": "C"), one set per
        era, on the same epoch-microsecond clock the engine's trace
        writer uses — so appending these to a ``.trace(format="chrome")``
        file lines the counter tracks up under the phase lanes."""
        with self._lock:
            wall0 = self._wall0 if self._wall0 is not None else time.time()
        events = []
        for rec in self.records():
            ts = (wall0 + rec["ts"]) * 1e6
            events.append(
                {
                    "name": "flight era (ms)",
                    "ph": "C",
                    "pid": pid,
                    "tid": 0,
                    "ts": ts,
                    "args": {
                        "device_era": rec["device_era_secs"] * 1e3,
                        "host_gap": rec["host_gap_secs"] * 1e3,
                    },
                }
            )
            events.append(
                {
                    "name": "flight frontier",
                    "ph": "C",
                    "pid": pid,
                    "tid": 0,
                    "ts": ts,
                    "args": {"rows": rec["frontier"]},
                }
            )
            events.append(
                {
                    "name": "flight load_factor",
                    "ph": "C",
                    "pid": pid,
                    "tid": 0,
                    "ts": ts,
                    "args": {"load_factor": rec["load_factor"]},
                }
            )
        return events

    def export_chrome(self, path, pid=1):
        """A standalone Chrome trace-event JSON array of the counter
        samples (loadable in Perfetto / chrome://tracing on its own)."""
        with open(path, "w") as f:
            json.dump(self.chrome_counter_events(pid=pid), f)
            f.write("\n")
