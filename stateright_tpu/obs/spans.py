"""The run ledger's span recorder: hierarchical traces with causal ids.

A *span* is one timed operation (`name`, wall-clock `start`/`end`,
`attributes`, point-in-time `events`) linked into a *trace* by three ids:

  ``trace_id``   one request/job end-to-end (every serve job gets one at
                 submit; it is persisted into the job journal so retries,
                 backoff waits, and crash→restart replay all land in the
                 SAME trace)
  ``span_id``    this span
  ``parent_id``  the enclosing span (None for a trace's root)

`SpanRecorder` is thread-safe and cheap: recording a span is a dict
build plus one deque append under a lock, so engines can afford one span
per era and the serve layer one per job phase. Durations use
`time.monotonic()` deltas (immune to wall clocks stepping); the epoch
anchor is `time.time()` captured once per open span, so spans from
different components align on one wall timeline.

Exports:

  - `to_dicts()` / `export_jsonl(path)` — OTel-compatible JSONL (one
    span object per line: traceId/spanId/parentSpanId camelCase ids,
    start/end in unix nanos) an OpenTelemetry collector ingests as-is;
  - `export_chrome(path)` / `chrome_events()` — Chrome trace-event
    B/E duration pairs (same format obs/trace.py writes) loadable in
    Perfetto / chrome://tracing; `ChromeTraceWriter.embed_spans` uses
    `chrome_events()` to merge request spans into an engine phase trace
    on one aligned clock;
  - `subscribe()` — a Queue receiving every COMPLETED span as a dict,
    feeding the servers' `GET /events` SSE streams.

`attach_phase_spans` turns a MetricsRegistry ``phase_ms`` dict (the
engines' existing per-phase wall-time accounting) into one child span
per phase, so an engine run shows up in a job's waterfall without the
hot loops knowing anything about tracing.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "SpanRecorder",
    "attach_phase_spans",
    "new_span_id",
    "new_trace_id",
]


def new_trace_id() -> str:
    """A fresh 32-hex-char trace id (OTel width)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """A fresh 16-hex-char span id (OTel width)."""
    return uuid.uuid4().hex[:16]


class _OpenSpan:
    """An in-flight span handle; `finish()` (or the context manager)
    seals it into the recorder. Mutating `attributes` / `add_event`
    before the finish is allowed and lock-free (single-owner)."""

    __slots__ = (
        "recorder", "name", "trace_id", "span_id", "parent_id",
        "start", "attributes", "events", "_mono0", "_finished",
    )

    def __init__(self, recorder: "SpanRecorder", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 attributes: Optional[Dict[str, Any]]):
        self.recorder = recorder
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.time()
        self.attributes = dict(attributes or {})
        self.events: List[Dict[str, Any]] = []
        self._mono0 = time.monotonic()
        self._finished = False

    def add_event(self, name: str, **attributes: Any) -> None:
        """A point-in-time annotation inside the span (OTel span event)."""
        self.events.append(
            {"name": name, "ts": time.time(), "attributes": attributes}
        )

    def finish(self, status: str = "ok", **attributes: Any) -> Dict[str, Any]:
        if self._finished:
            return {}
        self._finished = True
        self.attributes.update(attributes)
        # Monotonic duration anchored at the wall-clock start: wall steps
        # cannot produce negative or inflated span widths.
        end = self.start + (time.monotonic() - self._mono0)
        return self.recorder.record(
            self.name, start=self.start, end=end,
            trace_id=self.trace_id, span_id=self.span_id,
            parent_id=self.parent_id, attributes=self.attributes,
            events=self.events, status=status,
        )

    def __enter__(self) -> "_OpenSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.finish(status="error", error=repr(exc))
        else:
            self.finish()


class SpanRecorder:
    """Thread-safe ledger of completed spans (bounded ring) + live feed."""

    def __init__(self, capacity: int = 8192, metrics=None):
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._subscribers: List[queue.Queue] = []
        self._metrics = metrics

    # -- recording -----------------------------------------------------------

    def start_span(self, name: str, *, trace_id: Optional[str] = None,
                   parent_id: Optional[str] = None,
                   span_id: Optional[str] = None,
                   attributes: Optional[Dict[str, Any]] = None) -> _OpenSpan:
        """Open a span now; close it with `.finish()` or `with`."""
        return _OpenSpan(
            self, name, trace_id or new_trace_id(),
            span_id or new_span_id(), parent_id, attributes,
        )

    def record(self, name: str, *, start: float, end: float,
               trace_id: Optional[str] = None,
               span_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               attributes: Optional[Dict[str, Any]] = None,
               events: Optional[List[Dict[str, Any]]] = None,
               status: str = "ok") -> Dict[str, Any]:
        """Record an already-timed span (after-the-fact spans: queue
        waits, backoff windows, journal-replayed history). Returns the
        completed span dict (also fanned out to subscribers)."""
        span = {
            "name": name,
            "trace_id": trace_id or new_trace_id(),
            "span_id": span_id or new_span_id(),
            "parent_id": parent_id,
            "start": float(start),
            "end": float(max(end, start)),
            "status": status,
        }
        if attributes:
            span["attributes"] = dict(attributes)
        if events:
            span["events"] = list(events)
        with self._lock:
            self._spans.append(span)
            subs = list(self._subscribers)
        if self._metrics is not None:
            self._metrics.inc("spans_recorded")
        for q in subs:
            try:
                q.put_nowait(dict(span))
            except queue.Full:
                pass  # a stalled SSE client must not block recording
        return span

    # -- live feed -----------------------------------------------------------

    def subscribe(self, maxsize: int = 1024) -> queue.Queue:
        """A Queue receiving every span completed from now on (dicts).
        Unsubscribe when done; a full queue drops, never blocks."""
        q: queue.Queue = queue.Queue(maxsize=maxsize)
        with self._lock:
            self._subscribers.append(q)
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            try:
                self._subscribers.remove(q)
            except ValueError:
                pass

    # -- queries -------------------------------------------------------------

    def spans(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Completed spans, oldest first; `trace_id` filters to one trace."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """One trace's spans sorted by start time (the waterfall order)."""
        return sorted(self.spans(trace_id), key=lambda s: s["start"])

    def trace_ids(self) -> List[str]:
        """Distinct trace ids in completion order (oldest first)."""
        seen: Dict[str, None] = {}
        for s in self.spans():
            seen.setdefault(s["trace_id"], None)
        return list(seen)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- export --------------------------------------------------------------

    def to_dicts(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """OTel-compatible span objects (ids camelCased, times in unix
        nanos) — what `export_jsonl` writes one-per-line."""
        return [_otel(s) for s in self.spans(trace_id)]

    def export_jsonl(self, path: str, trace_id: Optional[str] = None) -> int:
        """Write the ledger as OTel-compatible JSONL; returns span count."""
        rows = self.to_dicts(trace_id)
        with open(path, "w", encoding="utf-8") as fh:
            for row in rows:
                fh.write(json.dumps(row, default=repr) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return len(rows)

    def chrome_events(
        self, trace_id: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """The ledger as Chrome trace-event records: one B/E duration
        pair per span (ts in microseconds), tracks (tid) keyed by trace
        so each request reads as one lane in Perfetto."""
        return spans_to_chrome(self.spans(trace_id))

    def export_chrome(self, path: str,
                      trace_id: Optional[str] = None) -> int:
        """Write a standalone Chrome trace-event JSON file of the ledger
        (Perfetto / chrome://tracing); returns the event count."""
        events = self.chrome_events(trace_id)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(events, fh, default=repr)
        return len(events)


def _otel(span: Dict[str, Any]) -> Dict[str, Any]:
    out = {
        "traceId": span["trace_id"],
        "spanId": span["span_id"],
        "parentSpanId": span.get("parent_id") or "",
        "name": span["name"],
        "startTimeUnixNano": int(span["start"] * 1e9),
        "endTimeUnixNano": int(span["end"] * 1e9),
        "status": {"code": "OK" if span.get("status") == "ok" else "ERROR"},
    }
    attrs = span.get("attributes") or {}
    if attrs:
        out["attributes"] = [
            {"key": k, "value": {"stringValue": str(v)}}
            for k, v in attrs.items()
        ]
    events = span.get("events") or []
    if events:
        out["events"] = [
            {
                "name": e["name"],
                "timeUnixNano": int(e.get("ts", span["start"]) * 1e9),
                "attributes": [
                    {"key": k, "value": {"stringValue": str(v)}}
                    for k, v in (e.get("attributes") or {}).items()
                ],
            }
            for e in events
        ]
    return out


def spans_to_chrome(spans: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Span dicts -> Chrome trace-event B/E pairs on per-trace tracks.

    Events are sorted so begins nest outermost-first and ends close
    innermost-first at equal timestamps, which is what the trace-event
    format's per-track stack discipline expects."""
    raw = []
    for s in spans:
        ts = s["start"] * 1e6
        dur = max(0.0, (s["end"] - s["start"]) * 1e6)
        tid = f"trace:{s['trace_id'][:8]}"
        args: Dict[str, Any] = {
            "trace_id": s["trace_id"],
            "span_id": s["span_id"],
        }
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        for k, v in (s.get("attributes") or {}).items():
            args[k] = v
        raw.append((ts, 1, -dur, {
            "name": s["name"], "ph": "B", "ts": round(ts, 1),
            "pid": 1, "tid": tid, "args": args,
        }))
        raw.append((ts + dur, 0, -dur, {
            "name": s["name"], "ph": "E", "ts": round(ts + dur, 1),
            "pid": 1, "tid": tid,
        }))
    # Sort: time, then E before B at ties, then longer spans open first /
    # close last (the -dur key inverts for E via the tuple above).
    raw.sort(key=lambda r: (r[0], r[1], r[2] if r[1] else -r[2]))
    return [r[3] for r in raw]


def attach_phase_spans(
    recorder: SpanRecorder,
    phase_ms: Dict[str, float],
    *,
    trace_id: str,
    parent_id: Optional[str],
    end: Optional[float] = None,
    attributes: Optional[Dict[str, Any]] = None,
) -> List[Dict[str, Any]]:
    """One child span per engine phase timer (obs/metrics.py catalog).

    The metrics registry keeps cumulative per-phase wall time, not
    per-interval timestamps, so each phase renders as one span whose
    width is the phase's total milliseconds, right-aligned at `end`
    (default now). Widths are exact; only the offsets are a layout
    convention — the waterfall reads "this run spent X ms in phase P".
    """
    end = time.time() if end is None else end
    out = []
    for phase in sorted(phase_ms):
        ms = float(phase_ms[phase])
        if ms <= 0.0:
            continue
        attrs = {"phase": phase, "ms": round(ms, 3)}
        if attributes:
            attrs.update(attributes)
        out.append(recorder.record(
            f"phase:{phase}", start=end - ms / 1e3, end=end,
            trace_id=trace_id, parent_id=parent_id, attributes=attrs,
        ))
    return out
