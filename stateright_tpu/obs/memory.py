"""Device-memory ledger, growth forecaster, and capacity planner.

The observability stack covers the *time* axis end to end (phase timers,
flight records, span ledger) but every hard exit in the device engines —
spill at the ring high-water mark, table grow at the load limit, degraded
regrow, OOM-classified serve retries — is a *memory* event. This module
makes the memory axis first-class, in three coupled layers:

``MemoryLedger``
    Exact analytic accounting of every device allocation, registered by
    component (visited table, frontier queue, packed params, coverage
    slab, spill/refill staging, per-shard tables on the mesh) with
    shape/dtype/bytes and a bounded growth-event log. The engines
    register each buffer from the SAME size formulas the planner uses,
    and keep a live reference to the underlying arrays, so

        ledger analytic bytes == sum(unique buf.nbytes)

    is an exact, test-locked invariant (``.nbytes`` is aval metadata —
    shape x itemsize — so it stays readable even on donated buffers).

``Forecaster`` / ``MemoryRecorder``
    Fit the per-era unique-state growth curve (geometric ratio over a
    sliding window) to project eras-to-grow, eras-to-exhaustion, and the
    final table size; per-era memory records ride the existing flight
    recorder readback (zero extra device round-trips) and surface as
    ``telemetry()["memory"]``, labeled ``memory_bytes{component=...}``
    Prometheus gauges, and an early warning with a concrete
    recommendation (regrow now / expect spill / use the sharded mesh)
    that fires once per approach — it re-arms after every table growth
    or proactive reshard, so the run warns again at each new wall.

``plan()``
    Static capacity planning: predict the full device footprint from the
    model's packed-state width and engine geometry BEFORE any dispatch.
    Exposed as ``python -m stateright_tpu.obs.memory SPEC``, a ``plan``
    subcommand on the example CLIs, and enforced at serve admission
    (predicted footprint > device memory -> HTTP 413; multiplex lane
    packing right-sized by per-lane footprint).

Every device buffer in this codebase is uint32, so sizes below are in
4-byte words; host staging (the spill blocks) is tracked separately from
the device total.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "MemoryLedger",
    "MemoryRecorder",
    "Forecaster",
    "plan",
    "recommend_engine",
    "device_memory_bytes",
    "format_plan",
    "bfs_component_sizes",
    "sim_component_sizes",
    "mesh_component_sizes",
    "multiplex_component_sizes",
    "main",
]

WORD_BYTES = 4  # every device buffer is uint32
#: One visited-table row is keys(2) + parent_h1(1) + parent_h2(1) words.
TABLE_ROW_BYTES = 4 * WORD_BYTES
#: Early-warning horizon: warn when exhaustion projects within this many
#: eras (or headroom is already below one further table doubling).
WARN_HORIZON_ERAS = 32
#: Proactive-reshard horizon: with a device limit set and exhaustion
#: projected, the engines front-run a table growth once the next
#: doubling is forecast within this many eras (the growth lands at a
#: host-chosen era boundary instead of a forced one — see ISSUE 20).
RESHARD_HORIZON_ERAS = 8
#: A proactive reshard additionally requires the table to have consumed
#: at least this fraction of its growth trigger.  Each doubling halves
#: the fraction, so the engine stays at most one doubling ahead of real
#: occupancy instead of chasing a diverging fit era after era.
RESHARD_MIN_LOAD_FRAC = 0.5
#: Forecast projection stops once the simulated table passes this many
#: bytes with no device limit in reach — past an exbibyte the only
#: information left is "diverging", and doubling further would overflow.
_PROJECTION_CEILING = float(1 << 62)
#: Bounded growth-event log (events beyond this are counted, not kept).
MAX_EVENTS = 512

_UNSET = object()


def device_memory_bytes(default: Optional[int] = None) -> Optional[int]:
    """Best-effort device memory limit in bytes.

    ``STPU_DEVICE_MEMORY_BYTES`` wins (deterministic tests / CI); else the
    first local device's ``memory_stats()`` where the backend exposes it
    (TPU and GPU do, CPU does not); else ``default`` (no enforcement).
    """
    env = os.environ.get("STPU_DEVICE_MEMORY_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
        if stats:
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit"
            )
            if limit:
                return int(limit)
    except Exception:
        pass
    return default


# -- component size formulas ------------------------------------------------
#
# One definition per engine, used by BOTH the static planner and the live
# ledger registration inside the engines — predicted footprint equals
# ledger footprint by construction, and the ledger-vs-nbytes parity test
# locks the formulas to the real allocations.


def _entry(shape: Sequence[int], dtype: str = "uint32") -> Dict[str, Any]:
    n = 1
    for d in shape:
        n *= int(d)
    return {
        "shape": tuple(int(d) for d in shape),
        "dtype": dtype,
        "bytes": n * WORD_BYTES,
    }


def bfs_component_sizes(
    S: int,
    A: int,
    P: int,
    *,
    chunk: int = 8192,
    queue_capacity: int = 1 << 20,
    table_capacity: int = 1 << 22,
    coverage: bool = True,
    sample_k: int = 64,
    fuse: int = 1,
) -> Dict[str, Dict[str, Any]]:
    """Device buffers of the solo BFS engine (engines/tpu_bfs.py).

    The visited table is (keys[2t] | parent_h1[t] | parent_h2[t]) = 4t
    words; the frontier ring is W = S+2 lanes (state | ebits | depth);
    the packed params vector carries P_LEN counters + 2P recorded
    fingerprint halves + the coverage tail + the sample tail (one
    buffer — the coverage and sample slabs are carved out analytically
    but share the params allocation). The sample tail is
    [T1, T2, occupied, sdrop] + (fp1|fp2|depth|action|ok) x
    slab_entries(k) words (``sample_k=0`` = sampling off). With
    multi-era fusion (``fuse > 1``) the params additionally carry the
    fusion tail ([fuse_lim, n_inner] + 4 per-inner-era lanes) — carved
    out as its own component so the nbytes parity stays exact.
    """
    from ..engines.tpu_bfs import P_LEN, _cov_len, fuse_tail_len

    A = max(1, int(A))
    chunk = min(int(chunk), int(queue_capacity) // (2 * A))
    W = int(S) + 2
    ncov = _cov_len(A, P) if coverage else 0
    sizes = {
        "visited_table": _entry((4 * int(table_capacity),)),
        "frontier_queue": _entry((W, int(queue_capacity))),
        "record_fps": _entry((2, int(P))),
        "packed_params": _entry((P_LEN + 2 * int(P),)),
    }
    if coverage:
        sizes["coverage_slab"] = _entry((ncov,))
    if sample_k:
        from .sample import slab_entries

        sizes["sample_slab"] = _entry((4 + 5 * slab_entries(int(sample_k)),))
    if int(fuse) > 1:
        sizes["fusion_tail"] = _entry((fuse_tail_len(int(fuse)),))
    return sizes


def sim_component_sizes(
    S: int,
    A: int,
    P: int,
    *,
    walks: int = 1024,
    walk_cap: int = 256,
    target_max_depth: Optional[int] = None,
    coverage: bool = True,
    sample_k: int = 64,
) -> Dict[str, Dict[str, Any]]:
    """Device buffers of the simulation engine (engines/tpu_simulation.py).

    The walk block is S+4 lanes (state | seed | ptr | ebits | frozen) x B
    walks; the path-fingerprint ring is B*L per hash half (L clamps to
    the depth target); params is P_LEN + 2P + (A + P + DEPTH_CAP)
    coverage words + the sample tail — [T1, T2, occupied, sdrop] +
    (fp1|fp2|depth|S state lanes|ok) x slab_entries(k) words (the walk
    slab carries full state rows: walks revisit states and there is no
    visited table to reconstruct them from later). Static footprint —
    no growth, no spill.
    """
    from ..engines.tpu_simulation import P_LEN
    from .coverage import DEPTH_CAP

    B = int(walks)
    L = (
        min(int(walk_cap), int(target_max_depth))
        if target_max_depth
        else int(walk_cap)
    )
    sizes = {
        "walk_lanes": _entry((int(S) + 4, B)),
        "path_fps": _entry((2, B * L)),
        "packed_params": _entry((P_LEN + 2 * int(P),)),
    }
    if coverage:
        sizes["coverage_slab"] = _entry((int(A) + int(P) + DEPTH_CAP,))
    if sample_k:
        from .sample import slab_entries

        sizes["sample_slab"] = _entry(
            (4 + (4 + int(S)) * slab_entries(int(sample_k)),)
        )
    return sizes


def mesh_component_sizes(
    S: int,
    A: int,
    P: int,
    *,
    chunk: int = 1024,
    queue_capacity_per_shard: int = 1 << 16,
    table_capacity_per_shard: int = 1 << 18,
    n_shards: int = 8,
    coverage: bool = True,
    sample_k: int = 64,
    fuse: int = 1,
) -> Dict[str, Dict[str, Any]]:
    """Device buffers of the sharded mesh engine (parallel/mesh.py).

    Every component carries the shard dimension N: per-shard visited
    tables (keys[N,2t] | p1[N,t] | p2[N,t]), the W = S+2 queue lanes at
    [N, qcap] each, and the per-shard packed params rows (counters + a
    coverage tail of A + P + 1 + DEPTH_CAP words, psum'd on device, +
    per-shard sample tails of 4 + 4*slab_entries(k) words — fp1|fp2|
    depth|ok, un-reduced: the host unions the per-shard bottom-k).
    With multi-era fusion (``fuse > 1``) each params row additionally
    carries the fusion tail ([fuse_lim, n_inner] + 4 per-inner-era
    lanes + P per-shard discovery-era indices), carved out as its own
    component.
    """
    from .coverage import DEPTH_CAP

    MESH_P_LEN = 17  # parallel/mesh.py P_LEN (pinned by the parity test)
    N = int(n_shards)
    t = int(table_capacity_per_shard)
    W = int(S) + 2
    ncov = (int(A) + int(P) + 1 + DEPTH_CAP) if coverage else 0
    sizes = {
        "visited_table": _entry((N, 4 * t)),
        "frontier_queue": _entry((W, N, int(queue_capacity_per_shard))),
        "record_fps": _entry((2, N, int(P))),
        "packed_params": _entry((N, MESH_P_LEN)),
    }
    if coverage:
        sizes["coverage_slab"] = _entry((N, ncov))
    if sample_k:
        from .sample import slab_entries

        sizes["sample_slab"] = _entry(
            (N, 4 + 4 * slab_entries(int(sample_k)))
        )
    if int(fuse) > 1:
        from ..parallel.mesh import shard_fuse_tail_len

        sizes["fusion_tail"] = _entry(
            (N, shard_fuse_tail_len(int(fuse), int(P)))
        )
    return sizes


def multiplex_component_sizes(
    S: int,
    A: int,
    P: int,
    *,
    lanes: int = 32,
    chunk: int = 256,
    queue_capacity: int = 1 << 13,
    table_capacity: int = 1 << 16,
    init_capacity: int = 64,
    coverage: bool = True,
) -> Dict[str, Dict[str, Any]]:
    """Device buffers of one multiplexed lane batch (engines/multiplex.py).

    Everything scales linearly with the lane count: stacked [N,4,t] lane
    tables, W = S+2 queue lanes at [N, qcap], the padded init slab
    (qinit + hash rows at icap width), per-lane packed params (P_LEN +
    2P + coverage tail), and the recorded-fingerprint rows. Used for
    footprint-based lane packing, not nbytes parity (lane batches are
    transient inside one fused dispatch).
    """
    from ..engines.tpu_bfs import P_LEN, _cov_len

    A = max(1, int(A))
    chunk = min(int(chunk), int(queue_capacity) // (2 * A))
    N = int(lanes)
    W = int(S) + 2
    icap = int(init_capacity)
    ncov = _cov_len(A, P) if coverage else 0
    plen = P_LEN + 2 * int(P) + ncov
    return {
        "lane_tables": _entry((N, 4, int(table_capacity))),
        "lane_queues": _entry((N, W, int(queue_capacity))),
        "lane_params": _entry((N, plen)),
        "lane_init_slab": _entry((N, (W + 2) * icap)),
        "record_fps": _entry((2, N, int(P))),
    }


# -- the ledger -------------------------------------------------------------


def _iter_arrays(ref) -> List[Any]:
    if ref is None:
        return []
    if isinstance(ref, (tuple, list)):
        out: List[Any] = []
        for r in ref:
            out.extend(_iter_arrays(r))
        return out
    return [ref]


class MemoryLedger:
    """Per-component device/host byte accounting with a growth-event log.

    Thread-safe: the engine thread registers/updates while telemetry
    polls snapshot from serve/Explorer threads.
    """

    def __init__(self, engine: str = "engine"):
        self.engine = str(engine)
        self._lock = threading.RLock()
        # name -> {"bytes", "shape", "dtype", "kind": "device"|"host"}
        self._components: Dict[str, Dict[str, Any]] = {}
        # name -> live array (or tuple/list of arrays) backing the entry
        self._arrays: Dict[str, Any] = {}
        self._events: List[Dict[str, Any]] = []
        self._events_dropped = 0
        self._peak_bytes = 0

    def register(
        self,
        name: str,
        *,
        nbytes: Optional[int] = None,
        shape: Optional[Sequence[int]] = None,
        dtype: str = "uint32",
        array: Any = None,
        kind: str = "device",
    ) -> None:
        """Add or replace one component entry; re-registering at a new
        size appends a resize event (table growth, staging churn)."""
        if nbytes is None:
            if shape is None:
                raise ValueError(f"component {name!r} needs nbytes or shape")
            nbytes = _entry(shape)["bytes"]
        entry = {
            "bytes": int(nbytes),
            "shape": tuple(int(d) for d in shape) if shape is not None else None,
            "dtype": dtype,
            "kind": kind,
        }
        with self._lock:
            prev = self._components.get(name)
            self._components[name] = entry
            if array is not None:
                self._arrays[name] = array
            elif prev is None:
                self._arrays.pop(name, None)
            if prev is not None and prev["bytes"] != entry["bytes"]:
                self._append_event(
                    {
                        "event": "resize",
                        "component": name,
                        "from_bytes": prev["bytes"],
                        "to_bytes": entry["bytes"],
                    }
                )
            self._peak_bytes = max(self._peak_bytes, self._total_locked())

    def register_sizes(
        self,
        sizes: Dict[str, Dict[str, Any]],
        arrays: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Bulk-register from a ``*_component_sizes`` dict, attaching the
        live arrays per component where the engine has them."""
        arrays = arrays or {}
        for name, entry in sizes.items():
            self.register(
                name,
                nbytes=entry["bytes"],
                shape=entry.get("shape"),
                dtype=entry.get("dtype", "uint32"),
                array=arrays.get(name),
            )

    def attach(self, name: str, array: Any) -> None:
        """Update only the live array reference behind a component (the
        engines' era loops rebind buffers every dispatch)."""
        with self._lock:
            if name in self._components:
                self._arrays[name] = array

    def event(self, kind: str, **fields: Any) -> None:
        """Append one growth-log event (grow / spill / refill /
        checkpoint_load / ...)."""
        rec = {"event": kind}
        rec.update(fields)
        with self._lock:
            self._append_event(rec)

    def _append_event(self, rec: Dict[str, Any]) -> None:
        if len(self._events) >= MAX_EVENTS:
            self._events_dropped += 1
            self._events.pop(0)
        self._events.append(rec)

    def _total_locked(self, kind: str = "device") -> int:
        return sum(
            c["bytes"] for c in self._components.values() if c["kind"] == kind
        )

    def total_bytes(self) -> int:
        """Analytic device bytes across all registered components."""
        with self._lock:
            return self._total_locked("device")

    def host_bytes(self) -> int:
        """Host-side staging bytes (spill blocks waiting for refill)."""
        with self._lock:
            return self._total_locked("host")

    def disk_bytes(self) -> int:
        """Disk-tier spill bytes (npz segments below the host budget)."""
        with self._lock:
            return self._total_locked("disk")

    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak_bytes

    def component_bytes(self, name: str) -> int:
        with self._lock:
            c = self._components.get(name)
            return c["bytes"] if c else 0

    def live_nbytes(self) -> int:
        """Sum of ``.nbytes`` over the UNIQUE live arrays behind device
        components (components carved from one buffer — packed_params /
        coverage_slab — are deduplicated by identity). ``.nbytes`` is
        aval metadata, safe on donated buffers. The parity invariant:
        ``live_nbytes() == total_bytes()``."""
        seen = set()
        total = 0
        with self._lock:
            refs = [
                self._arrays.get(name)
                for name, c in self._components.items()
                if c["kind"] == "device"
            ]
        for arr in _iter_arrays(refs):
            if arr is None or id(arr) in seen:
                continue
            seen.add(id(arr))
            total += int(arr.nbytes)
        return total

    def components(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: dict(entry) for name, entry in self._components.items()
            }

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            comps = {n: dict(c) for n, c in self._components.items()}
            return {
                "engine": self.engine,
                "components": comps,
                "total_bytes": self._total_locked("device"),
                "host_bytes": self._total_locked("host"),
                "disk_bytes": self._total_locked("disk"),
                "peak_bytes": self._peak_bytes,
                "events": [dict(e) for e in self._events],
                "events_dropped": self._events_dropped,
            }


# -- the forecaster ---------------------------------------------------------


class Forecaster:
    """Fit per-era unique-row growth; project grow/exhaustion horizons.

    The model is a damped geometric: recent deltas d_i with mean ratio
    r = mean(d_{i+1}/d_i). BFS frontiers expand geometrically until the
    wavefront saturates, then decay — both regimes are one ratio. The
    projection simulates forward era by era, doubling the table whenever
    ``unique + reserve_rows > max_load * rows`` (the engines' exact grow
    trigger), until growth dies out, the device limit is crossed, or the
    era bound is hit.
    """

    def __init__(self, window: int = 8):
        self.window = max(2, int(window))
        self._unique: List[int] = []

    def observe(self, unique: int) -> None:
        self._unique.append(int(unique))
        if len(self._unique) > self.window + 1:
            self._unique.pop(0)

    def fit(self) -> Tuple[Optional[float], Optional[int]]:
        """(ratio, last_delta), or (None, None) below 3 observations."""
        us = self._unique
        if len(us) < 3:
            return None, None
        deltas = [us[i + 1] - us[i] for i in range(len(us) - 1)]
        ratios = [
            deltas[i + 1] / deltas[i]
            for i in range(len(deltas) - 1)
            if deltas[i] > 0
        ]
        if not ratios:
            return 0.0, deltas[-1]
        r = sum(ratios) / len(ratios)
        # Clamp: a wild early ratio (tiny first deltas) must not overflow
        # the forward simulation.
        return max(0.0, min(r, 8.0)), deltas[-1]

    def forecast(
        self,
        *,
        unique: int,
        rows: int,
        max_load: float,
        reserve_rows: int,
        table_bytes: int,
        fixed_bytes: int = 0,
        device_limit: Optional[int] = None,
        max_eras: int = 4096,
    ) -> Dict[str, Any]:
        """Project forward from the current era.

        ``unique``/``rows``/``reserve_rows`` are in the grow trigger's own
        units (per-shard rows on the mesh); ``table_bytes`` is the global
        table allocation (doubles in lockstep with ``rows``) and
        ``fixed_bytes`` everything else on device.
        """
        r, d = self.fit()
        out: Dict[str, Any] = {
            "ratio": None if r is None else round(r, 4),
            "delta_rows": d,
            "eras_to_grow": None,
            "eras_to_exhaustion": None,
            "projected_unique": None,
            "projected_table_bytes": None,
            "projected_total_bytes": None,
            # Fraction of the growth trigger the CURRENT occupancy has
            # consumed (1.0 == a load-factor growth is due right now).
            # Measured, not simulated — the reshard gate keys off this.
            "load_frac": round(
                (max(0, unique) + reserve_rows)
                / max(1.0, max_load * max(1, int(rows))),
                4,
            ),
        }
        if r is None or d is None:
            return out
        u = float(max(0, unique))
        step = float(max(0, d))
        if 0.0 <= r < 1.0:
            out["projected_unique"] = int(u + (step * r / (1.0 - r) if r else 0.0))
        # The simulation runs in floats: a diverging fit (r >= 1) with no
        # device limit doubles cap_rows every era, and int arithmetic
        # would overflow the float comparison long before max_eras.
        cap_rows = float(max(1, int(rows)))
        t_bytes = float(table_bytes)
        eras_to_grow: Optional[int] = None
        eras_to_exhaustion: Optional[int] = None
        if u + reserve_rows > max_load * cap_rows:
            eras_to_grow = 0
        for era in range(1, int(max_eras) + 1):
            u += step
            step *= r
            grew = False
            while u + reserve_rows > max_load * cap_rows:
                cap_rows *= 2
                t_bytes *= 2
                grew = True
                if eras_to_grow is None:
                    eras_to_grow = era
                if (
                    device_limit is not None
                    and fixed_bytes + t_bytes > device_limit
                ):
                    eras_to_exhaustion = era
                    break
            if eras_to_exhaustion is not None:
                break
            if step < 1.0 and not grew:
                break  # growth died out before any limit
            if t_bytes > _PROJECTION_CEILING:
                break  # diverging with no limit in reach; enough signal
        out["eras_to_grow"] = eras_to_grow
        out["eras_to_exhaustion"] = eras_to_exhaustion
        out["projected_table_bytes"] = int(t_bytes)
        out["projected_total_bytes"] = int(fixed_bytes + t_bytes)
        return out


# -- the engine-facing recorder ---------------------------------------------


class MemoryRecorder:
    """Ledger + forecaster + gauges + once-per-approach warning, as one
    object the engines feed at their existing once-per-era readback."""

    def __init__(
        self,
        engine: str = "engine",
        metrics=None,
        device_limit_bytes=_UNSET,
    ):
        self.ledger = MemoryLedger(engine)
        self.forecaster = Forecaster()
        self._metrics = metrics
        self.device_limit_bytes = (
            device_memory_bytes()
            if device_limit_bytes is _UNSET
            else device_limit_bytes
        )
        # Table-growth geometry, set by engines with a growable table:
        # {"rows", "max_load", "reserve_rows"} in the grow trigger's units.
        self._geometry: Optional[Dict[str, Any]] = None
        self._eras = 0
        self._warning: Optional[str] = None
        self._last_forecast: Dict[str, Any] = {}
        self._last_record: Dict[str, Any] = {}

    # -- registration passthroughs (engine call sites stay one-liners) --

    def register_components(self, sizes, arrays=None) -> None:
        self.ledger.register_sizes(sizes, arrays)

    def attach(self, name: str, array: Any) -> None:
        self.ledger.attach(name, array)

    def set_geometry(
        self, *, rows: int, max_load: float, reserve_rows: int
    ) -> None:
        prev = self._geometry
        self._geometry = {
            "rows": int(rows),
            "max_load": float(max_load),
            "reserve_rows": int(reserve_rows),
        }
        # A growth/reshard changed the wall the warning was about: re-arm
        # it so a second approach to the (new) wall warns again instead
        # of staying silent behind the one-shot latch.
        if prev is not None and int(rows) > prev["rows"]:
            self.rearm_warning()

    def staging(self, nbytes: int, event: Optional[str] = None, **fields) -> None:
        """Update the host spill-staging component; optionally log the
        spill/refill event that moved it."""
        self.ledger.register("spill_staging", nbytes=int(nbytes), kind="host")
        if event:
            self.ledger.event(event, host_bytes=int(nbytes), **fields)

    def event(self, kind: str, **fields) -> None:
        self.ledger.event(kind, **fields)

    @property
    def warning(self) -> Optional[str]:
        return self._warning

    def rearm_warning(self) -> None:
        """Clear a fired memory-pressure warning so the NEXT approach to
        the wall warns again (called after growth/reshard events — via
        ``set_geometry`` — and by the engines' proactive reshard)."""
        if self._warning is None:
            return
        self._warning = None
        self.ledger.event("memory_warning_rearmed")
        if self._metrics is not None:
            self._metrics.set_gauge("memory_warning", 0)

    def last_forecast(self) -> Dict[str, Any]:
        """The most recent ``on_era`` forecast (empty before the fit has
        enough observations) — the engines' proactive-reshard trigger."""
        return dict(self._last_forecast)

    # -- the per-era hook ------------------------------------------------

    def on_era(
        self,
        *,
        unique: int = 0,
        load_factor: float = 0.0,
        grow_rows: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Called once per era at the readback; returns the compact memory
        record that rides the flight record. ``grow_rows`` is the row
        count the engine's grow trigger actually compares (max per-shard
        unique on the mesh); defaults to ``unique``."""
        self._eras += 1
        rows_now = int(grow_rows if grow_rows is not None else unique)
        self.forecaster.observe(rows_now)
        led = self.ledger
        total = led.total_bytes()
        host = led.host_bytes()
        limit = self.device_limit_bytes
        headroom = (limit - total) if limit is not None else None
        fc: Dict[str, Any] = {}
        if self._geometry is not None:
            g = self._geometry
            table_bytes = led.component_bytes("visited_table")
            fc = self.forecaster.forecast(
                unique=rows_now,
                rows=g["rows"],
                max_load=g["max_load"],
                reserve_rows=g["reserve_rows"],
                table_bytes=table_bytes,
                fixed_bytes=total - table_bytes,
                device_limit=limit,
            )
            self._last_forecast = fc
        self._maybe_warn(total, headroom, fc)
        rec = {
            "total_bytes": total,
            "host_bytes": host,
            "by_component": {
                name: c["bytes"]
                for name, c in led.components().items()
                if c["kind"] == "device"
            },
            "load_factor": float(load_factor),
        }
        if headroom is not None:
            rec["headroom_bytes"] = headroom
        if fc.get("eras_to_grow") is not None:
            rec["eras_to_grow"] = fc["eras_to_grow"]
        if fc.get("eras_to_exhaustion") is not None:
            rec["eras_to_exhaustion"] = fc["eras_to_exhaustion"]
        self._last_record = rec
        m = self._metrics
        if m is not None:
            m.set_gauge("memory_bytes", dict(rec["by_component"]))
            m.set_gauge("memory_total_bytes", total)
            m.set_gauge("memory_host_bytes", host)
            m.set_gauge("memory_peak_bytes", led.peak_bytes())
            if headroom is not None:
                m.set_gauge("memory_headroom_bytes", headroom)
            m.set_gauge(
                "memory_eta_exhaustion_eras",
                fc.get("eras_to_exhaustion")
                if fc.get("eras_to_exhaustion") is not None
                else -1,
            )
            m.set_gauge("memory_warning", 1 if self._warning else 0)
        return rec

    def _maybe_warn(
        self,
        total: int,
        headroom: Optional[int],
        fc: Dict[str, Any],
    ) -> None:
        if self._warning is not None or headroom is None:
            return
        eta = fc.get("eras_to_exhaustion")
        projected = fc.get("projected_total_bytes")
        limit = self.device_limit_bytes
        # One more table doubling is the next allocation the engine will
        # attempt; no room for it (or a projected exhaustion inside the
        # horizon) is the warn condition.
        table_bytes = self.ledger.component_bytes("visited_table")
        imminent = table_bytes > 0 and headroom < table_bytes
        horizon = eta is not None and eta <= WARN_HORIZON_ERAS
        over = projected is not None and limit is not None and projected > limit
        if not (imminent or horizon or over):
            return
        if over or horizon:
            if self.ledger.engine in ("ShardedBfsChecker",):
                rec = "expect spill past the device (out-of-core tiering)"
            else:
                rec = "use the sharded mesh (spawn_sharded_bfs)"
        else:
            rec = (
                "regrow now (reduce table_capacity or pre-size it: the next "
                "doubling will not fit)"
            )
        eta_s = f" exhaustion in ~{eta} eras;" if eta is not None else ""
        self._warning = (
            f"device memory pressure: {_fmt_bytes(total)} resident, "
            f"{_fmt_bytes(headroom)} headroom;{eta_s} recommendation: {rec}"
        )
        try:
            from .log import get_logger

            get_logger("obs.memory").warning(self._warning)
        except Exception:
            pass

    def snapshot(self) -> Dict[str, Any]:
        """``telemetry()["memory"]``: ledger snapshot + forecast + the
        one-shot warning (when fired)."""
        snap = self.ledger.snapshot()
        snap["eras"] = self._eras
        snap["live_nbytes"] = self.ledger.live_nbytes()
        if self.device_limit_bytes is not None:
            snap["device_limit_bytes"] = self.device_limit_bytes
            snap["headroom_bytes"] = self.device_limit_bytes - snap["total_bytes"]
        if self._last_forecast:
            snap["forecast"] = dict(self._last_forecast)
        if self._warning:
            snap["warning"] = self._warning
        return snap


# -- the capacity planner ---------------------------------------------------

_ENGINE_ALIASES = {
    "tpu_bfs": "tpu_bfs",
    "bfs": "tpu_bfs",
    "device": "tpu_bfs",
    "solo": "tpu_bfs",
    "tpu_simulation": "tpu_simulation",
    "simulation": "tpu_simulation",
    "sim": "tpu_simulation",
    "sharded": "sharded",
    "mesh": "sharded",
    "tpu_sharded_bfs": "sharded",
    "multiplex": "multiplex",
    "lanes": "multiplex",
}


def _tensor_model(model):
    from ..tensor import TensorModel, TensorModelAdapter

    if isinstance(model, TensorModelAdapter):
        return model.tm
    if isinstance(model, TensorModel):
        return model
    tm = getattr(model, "tm", None)
    if tm is not None and isinstance(tm, TensorModel):
        return tm
    raise TypeError(
        f"plan() needs a TensorModel (or its adapter), got "
        f"{type(model).__name__}; host-only models have no device footprint"
    )


def plan(
    model,
    *,
    engine: str = "tpu_bfs",
    chunk: Optional[int] = None,
    queue_capacity: Optional[int] = None,
    table_capacity: Optional[int] = None,
    walks: Optional[int] = None,
    walk_cap: Optional[int] = None,
    lanes: Optional[int] = None,
    init_capacity: Optional[int] = None,
    n_shards: Optional[int] = None,
    coverage: bool = True,
    sample_k: int = 64,
    device_limit_bytes=_UNSET,
) -> Dict[str, Any]:
    """Predict the full device footprint for ``model`` on ``engine``
    BEFORE any dispatch, from the model's packed-state width and the
    engine geometry (engine defaults where not given). Returns the plan
    dict: per-component sizes, total bytes, and — where a device limit
    is known — fit verdict and headroom.
    """
    tm = _tensor_model(model)
    S = int(tm.state_width)
    A = int(tm.max_actions)
    P = len(tm.tensor_properties())
    kind = _ENGINE_ALIASES.get(str(engine).lower())
    if kind is None:
        raise ValueError(
            f"unknown engine {engine!r}; one of "
            f"{sorted(set(_ENGINE_ALIASES.values()))}"
        )
    limit = (
        device_memory_bytes()
        if device_limit_bytes is _UNSET
        else device_limit_bytes
    )
    if kind == "tpu_bfs":
        geometry = {
            "chunk": chunk if chunk is not None else 8192,
            "queue_capacity": (
                queue_capacity if queue_capacity is not None else 1 << 20
            ),
            "table_capacity": (
                table_capacity if table_capacity is not None else 1 << 22
            ),
        }
        sizes = bfs_component_sizes(
            S, A, P, coverage=coverage, sample_k=sample_k, **geometry
        )
    elif kind == "tpu_simulation":
        geometry = {
            "walks": walks if walks is not None else 1024,
            "walk_cap": walk_cap if walk_cap is not None else 256,
        }
        sizes = sim_component_sizes(
            S, A, P, coverage=coverage, sample_k=sample_k, **geometry
        )
    elif kind == "sharded":
        geometry = {
            "chunk": chunk if chunk is not None else 1024,
            "queue_capacity_per_shard": (
                queue_capacity if queue_capacity is not None else 1 << 16
            ),
            "table_capacity_per_shard": (
                table_capacity if table_capacity is not None else 1 << 18
            ),
            "n_shards": n_shards if n_shards is not None else 8,
        }
        sizes = mesh_component_sizes(
            S, A, P, coverage=coverage, sample_k=sample_k, **geometry
        )
    else:  # multiplex
        geometry = {
            "lanes": lanes if lanes is not None else 32,
            "chunk": chunk if chunk is not None else 256,
            "queue_capacity": (
                queue_capacity if queue_capacity is not None else 1 << 13
            ),
            "table_capacity": (
                table_capacity if table_capacity is not None else 1 << 16
            ),
            "init_capacity": init_capacity if init_capacity is not None else 64,
        }
        sizes = multiplex_component_sizes(S, A, P, coverage=coverage, **geometry)
    total = sum(e["bytes"] for e in sizes.values())
    result: Dict[str, Any] = {
        "engine": kind,
        "model": type(tm).__name__,
        "state_width": S,
        "max_actions": A,
        "properties": P,
        "coverage": bool(coverage),
        "sample_k": int(sample_k) if kind != "multiplex" else 0,
        "geometry": geometry,
        "components": sizes,
        "total_bytes": total,
        "device_limit_bytes": limit,
        "fits": (total <= limit) if limit is not None else None,
        "headroom_bytes": (limit - total) if limit is not None else None,
    }
    if kind == "multiplex":
        result["per_lane_bytes"] = total // max(1, geometry["lanes"])
    return result


def recommend_engine(
    model, device_limit_bytes=_UNSET, exclude: Sequence[str] = ()
) -> Optional[str]:
    """The first engine (at default geometry) whose predicted footprint
    fits the device limit, in escalation order; None when nothing fits
    or no limit is known."""
    for engine in ("tpu_bfs", "sharded", "tpu_simulation"):
        if engine in exclude:
            continue
        p = plan(model, engine=engine, device_limit_bytes=device_limit_bytes)
        if p["fits"]:
            return engine
    return None


def max_lanes_for_budget(
    model,
    limit_bytes: Optional[int],
    *,
    lanes: int = 32,
    safety: float = 0.9,
    **geometry,
) -> int:
    """Footprint-based lane packing for the multiplex engine: the largest
    lane count whose batch footprint stays under ``safety * limit``.
    Returns ``lanes`` unchanged when no limit is known; always >= 1 (a
    single lane that does not fit is the admission gate's problem)."""
    if limit_bytes is None:
        return int(lanes)
    p = plan(
        model,
        engine="multiplex",
        lanes=lanes,
        device_limit_bytes=limit_bytes,
        **geometry,
    )
    per_lane = max(1, p["per_lane_bytes"])
    fit = int((limit_bytes * safety) // per_lane)
    return max(1, min(int(lanes), fit))


# -- rendering + CLI --------------------------------------------------------


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    v = float(n)
    sign = "-" if v < 0 else ""
    v = abs(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if v < 1024 or unit == "GiB":
            return (
                f"{sign}{v:.0f} {unit}"
                if unit == "B"
                else f"{sign}{v:.1f} {unit}"
            )
        v /= 1024
    return f"{sign}{v:.1f} GiB"


def format_plan(p: Dict[str, Any]) -> str:
    """Human-readable rendering of a ``plan()`` dict (the CLI output)."""
    lines = [
        f"capacity plan · engine={p['engine']} · model={p['model']}",
        (
            f"  state_width={p['state_width']} words  "
            f"max_actions={p['max_actions']}  properties={p['properties']}  "
            f"coverage={'on' if p['coverage'] else 'off'}"
        ),
        "  geometry: "
        + " ".join(f"{k}={v}" for k, v in p["geometry"].items()),
        f"  {'component':<18} {'shape':<22} {'bytes':>14}",
    ]
    for name, e in p["components"].items():
        shape = "x".join(str(d) for d in e["shape"]) if e.get("shape") else "-"
        lines.append(
            f"  {name:<18} {shape:<22} {_fmt_bytes(e['bytes']):>14}"
        )
    lines.append(f"  {'total':<18} {'':<22} {_fmt_bytes(p['total_bytes']):>14}")
    if p.get("per_lane_bytes") is not None:
        lines.append(f"  per-lane footprint: {_fmt_bytes(p['per_lane_bytes'])}")
    limit = p.get("device_limit_bytes")
    if limit is not None:
        verdict = "fits" if p["fits"] else "DOES NOT FIT"
        lines.append(
            f"  device limit {_fmt_bytes(limit)}: {verdict} "
            f"(headroom {_fmt_bytes(p['headroom_bytes'])})"
        )
    else:
        lines.append(
            "  device limit unknown (set STPU_DEVICE_MEMORY_BYTES or run "
            "on a backend with memory_stats)"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m stateright_tpu.obs.memory SPEC [--engine E] ...``:
    static capacity planning from the command line. Exit 0 = fits (or no
    limit known), 3 = predicted footprint exceeds the device limit."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m stateright_tpu.obs.memory",
        description=(
            "predict a model's device memory footprint before any dispatch"
        ),
    )
    parser.add_argument(
        "model", help="bundled shorthand (2pc:7) or pkg.module:Factory:ARGS"
    )
    parser.add_argument(
        "--engine",
        default="tpu_bfs",
        help="tpu_bfs | tpu_simulation | sharded | multiplex (default tpu_bfs)",
    )
    parser.add_argument("--chunk", type=int, default=None)
    parser.add_argument("--queue-capacity", type=int, default=None)
    parser.add_argument("--table-capacity", type=int, default=None)
    parser.add_argument("--walks", type=int, default=None)
    parser.add_argument("--walk-cap", type=int, default=None)
    parser.add_argument("--lanes", type=int, default=None)
    parser.add_argument("--shards", type=int, default=None)
    parser.add_argument(
        "--no-coverage", action="store_true", help="plan without coverage slabs"
    )
    parser.add_argument(
        "--sample-k",
        type=int,
        default=64,
        help="bottom-k sample size the run will use (0 = sampling off; "
        "default matches CheckerBuilder.sample())",
    )
    parser.add_argument(
        "--limit-bytes",
        type=int,
        default=None,
        help="override the detected device memory limit",
    )
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    from ..analysis.__main__ import resolve_model

    model = resolve_model(args.model)
    kw: Dict[str, Any] = dict(
        engine=args.engine,
        chunk=args.chunk,
        queue_capacity=args.queue_capacity,
        table_capacity=args.table_capacity,
        walks=args.walks,
        walk_cap=args.walk_cap,
        lanes=args.lanes,
        n_shards=args.shards,
        coverage=not args.no_coverage,
        sample_k=max(0, args.sample_k),
    )
    if args.limit_bytes is not None:
        kw["device_limit_bytes"] = args.limit_bytes
    try:
        p = plan(model, **kw)
    except (TypeError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(p, indent=2, default=list))
    else:
        print(format_plan(p))
    if p["fits"] is False:
        alt = recommend_engine(
            model,
            device_limit_bytes=p["device_limit_bytes"],
            exclude=(p["engine"],),
        )
        if alt:
            print(f"  recommended alternative: --engine {alt}")
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
