"""The metrics registry: counters, gauges, and monotonic phase timers.

One `MetricsRegistry` per checker run (created in `HostEngineBase.__init__`)
backs `Checker.telemetry()` for every engine, replacing the old
`tpu_bfs`-only `_telemetry` dict. The API is deliberately tiny — engines hot
loops must stay hot — and every method is thread-safe (host engines mutate
from worker threads while `Checker.report()` polls from the caller's).

Metric-name catalog
===================

Counters (`inc`) — monotonic totals:

  =====================  =====================================================
  name                   meaning
  =====================  =====================================================
  ``eras``               device dispatch+readback round-trips (device engines)
  ``waves``              host frontier blocks processed (bfs/dfs/vbfs/on_demand)
  ``rounds``             coordinator polling epochs (pbfs)
  ``traces``             completed random walks (simulation engines)
  ``steps``              device loop iterations actually executed
  ``states_generated``   successor states generated (incl. duplicates)
  ``spill_rows``         frontier rows spilled device -> host
  ``refill_rows``        frontier rows refilled host -> device
  ``table_growths``      visited-table doublings (grow + rehash)
  ``expand_requests``    on-demand fingerprint expansions served
  ``lint_<CODE>``        speclint diagnostics by stable code (e.g.
                         ``lint_STR303``) when the run was linted — strict
                         mode or an explicit `CheckerBuilder.lint()`
                         (catalog: analysis/README.md)
  ``conformance_events``  trace events consumed by `conformance.check_trace`
  ``conformance_steps``   trace events explained as model transitions
  ``conformance_stutters``  events the model prunes as no-ops (duplicate
                         redeliveries, pure timer re-arms) — expected under
                         fault injection, not divergences
  ``conformance_faults``  injected-fault events recorded in the trace
  ``conformance_divergences``  trace events the model could NOT explain
                         (catalog: conformance/README.md)
  ``serve_requests``     run-service submissions received (serve/service.py)
  ``serve_rejected_lint``  submissions rejected by the speclint admission
                         gate (422; STRxxx codes in the response body)
  ``serve_rejected_quota``  submissions rejected by per-tenant quotas or
                         rate limits (429)
  ``serve_completed``    jobs finished with results available
  ``serve_failed``       jobs that errored during execution
  ``serve_cancelled``    jobs cancelled while queued
  ``serve_exec_cache_hits``    executable-cache hits (a warm `CompiledCheck`
                         served the run; engines/compiled.py)
  ``serve_exec_cache_misses``  executable-cache misses (trace + lower paid)
  ``serve_multiplexed_jobs``  jobs executed as lanes of a fused vmapped
                         batch (engines/multiplex.py)
  ``serve_batches``      multiplexed batch dispatches executed
  ``serve_tenant_requests``  dict counter (`inc_labeled`): submissions per
                         tenant id — rendered as a labeled
                         ``{tenant="..."}`` series in the Prometheus
                         exposition
  ``checkpoint_saves``   crash-safe checkpoints written (tmp + fsync +
                         generation rotation + rename; engines/common.py)
  ``checkpoint_bytes``   total bytes of checkpoint payloads written
  ``checkpoint_corrupt_rejected``  checkpoint generations rejected by the
                         content digest (truncated/corrupt files)
  ``checkpoint_fallbacks``  resumes that fell back to a previous rolling
                         generation after the newest failed verification
  ``degraded_regrow``    probe-budget exhaustions recovered by reloading
                         the last checkpoint and doubling the table
                         instead of aborting (graceful degradation)
  ``journal_records`` / ``journal_bytes``  serve job-journal appends /
                         bytes fsynced (serve/durability.py)
  ``journal_compactions``  atomic journal rewrites to the folded state
  ``journal_replayed_jobs``  jobs reconstructed from the journal at
                         service restart
  ``journal_recovered_queued``  replayed jobs re-enqueued (were queued)
  ``journal_recovered_running``  replayed jobs re-enqueued as retries
                         (were mid-flight when the service died)
  ``journal_recovered_done``  replayed jobs whose persisted results were
                         reloaded without re-running
  ``retry_scheduled``    transient job failures scheduled for a backoff
                         retry (invisible to the client)
  ``retry_escalated_solo``  retries escalated from a multiplex lane to
                         the solo engine (lane capacity failures)
  ``retry_exhausted``    transient failures out of retry attempts
                         (surfaced as failed)
  ``serve_breaker_fastfail``  jobs fast-failed by an open per-signature
                         circuit breaker
  ``serve_worker_crashes``  dead worker threads detected and replaced by
                         the guard
  ``serve_admin_retries``  ``POST /jobs/{id}/retry`` re-enqueues
  ``serve_results_persisted``  finished result payloads written to the
                         on-disk result store
  ``serve_results_gc``   persisted results expired past their TTL
  =====================  =====================================================

Gauges (`set_gauge`) — last-observed values:

  =======================  ===================================================
  name                     meaning
  =======================  ===================================================
  ``frontier_size``        pending rows/jobs after the last era/wave
  ``max_depth``            deepest state visited so far
  ``take_cap``             device engines' self-tuned pop width
  ``load_factor``          visited-table occupancy / capacity
  ``table_capacity``       visited-table capacity (per shard when sharded)
  ``chunk``                device engines' data-parallel chunk width
  ``walks`` / ``walk_cap`` simulation batch width / path-buffer depth
  ``threads`` / ``workers``  host parallelism actually used
  ``n_shards`` / ``quota``   mesh engine shard count / exchange quota
  ``lint_errors`` / ``lint_warnings``  speclint finding counts by severity
                           (linted runs only)
  ``conformance_history_ops``  operations in the client history extracted
                           from a checked trace (conformance/history.py)
  ``coverage_actions_fired``  distinct actions observed firing so far
                           (obs/coverage.py; the per-action breakdown is
                           `Checker.coverage()`, not a metric)
  ``coverage_dead_actions``  registered actions with a ZERO fire count —
                           nonzero at run end means dead transitions or
                           mis-modeled guards (speclint STR306 is the
                           static twin)
  ``small_workload_hint``  set (to the state count seen) when a device-engine
                           run targets/explores fewer states than the
                           host-vs-device crossover (~10k): the host engine
                           would likely have been faster (one stderr line
                           accompanies it)
  ``stage_profile_iters``  per-stage loop repetitions used by the era stage
                           profiler (`CheckerBuilder.stage_profile(iters=)`)
  ``stage_us_per_step``    dict gauge: RAW isolated per-step cost of each era
                           stage in microseconds, before proportional
                           attribution (non-numeric; skipped by the
                           Prometheus exposition)
  ``stage_profile_model_pct``  how much of the measured era wall time the
                           isolated-stage cost model accounts for (100 =
                           stages explain the loop; low = fixed per-step
                           overhead dominates; high = fusion beats the
                           isolated kernels)
  ``stage_profile_error``  repr of the exception if stage profiling failed
                           (profiling is best-effort and never fails a run)
  ``serve_queue_depth``    run-service jobs currently queued (serve/)
  ``serve_active_jobs``    run-service jobs currently executing
  ``interrupted``          set to 1 when a run stopped early for a graceful
                           SIGTERM/SIGINT checkpoint flush
                           (`request_checkpoint_stop`); the final
                           checkpoint captures the stopping boundary
  =======================  ===================================================

Phase timers (`phase(name)` context manager / `add_phase`) — cumulative
wall milliseconds per hot-path phase, surfaced as the nested ``phase_ms``
dict in `snapshot()`:

  =====================  =====================================================
  phase                  measures
  =====================  =====================================================
  ``device_era``         one era: dispatch through params readback complete
  ``readback``           device -> host stats/result downloads
  ``upload``             host -> device parameter/frontier uploads
  ``spill``              frontier spill downloads (device -> host)
  ``refill``             frontier refill uploads (host -> device)
  ``table_grow``         visited-table grow + rehash
  ``checkpoint_save``    one crash-safe checkpoint write end-to-end
                         (serialize + fsync + rotate + rename)
  ``check_block``        one host BFS/DFS/on-demand block (pop..expand)
  ``property_eval``      batched property evaluation (vbfs)
  ``expand``             batched successor generation (vbfs)
  ``hash``               batched fingerprinting (vbfs)
  ``visited_insert``     visited-set probe + insert (vbfs native set)
  ``walk``               one host simulation trace end-to-end
  ``poll``               one pbfs coordinator polling epoch
  ``stage_<name>``       the device engines' era wall time attributed to one
                         pipeline stage (``stage_expand`` / ``stage_hash`` /
                         ``stage_probe`` / ``stage_claim`` / ``stage_compact``
                         / ``stage_ring``; plus ``stage_canon`` under
                         symmetry, ``stage_exchange`` on the sharded mesh,
                         and ``stage_cycle`` / ``stage_choose`` /
                         ``stage_record`` on the simulation engine). Present
                         only when the run used
                         `CheckerBuilder.stage_profile()`; the stage shares
                         sum to ``device_era`` by construction
                         (obs/stageprof.py documents the attribution)
  ``profiler_overhead``  wall time the stage profiler itself spent measuring
                         (outside ``device_era``; the timed run is clean)
  =====================  =====================================================

Histograms (`observe`) — log-spaced latency distributions, surfaced as
the nested ``histograms`` dict in `snapshot()` (per histogram: ``count``,
``sum``, cumulative ``buckets`` as ``[le, count]`` pairs, and
interpolated ``p50``/``p95``/``p99``), and rendered by
`render_prometheus` as classic ``_bucket{le=...}`` / ``_sum`` /
``_count`` families:

  ==========================  ================================================
  name                        observes (seconds)
  ==========================  ================================================
  ``submit_to_result_secs``   serve job latency, submission acknowledged to
                              result recorded — retries, backoff waits, and
                              queue time all included (serve/service.py);
                              ``/stats``'s ``latency`` section reports its
                              p50/p95/p99
  ``queue_wait_secs``         serve job queue residency, enqueue to worker
                              pickup (re-observed per requeue)
  ``era_secs``                one device era dispatch→readback (device
                              engines and multiplex lanes; the distribution
                              twin of the cumulative ``device_era`` phase)
  ==========================  ================================================

Span phases — when a `SpanRecorder` (obs/spans.py) is attached, every
phase timer above ALSO appears as a ``phase:<name>`` child span of the
run/job span, so a Perfetto waterfall shows where a request's wall time
went without new instrumentation in the hot loops.

Engines only populate the rows that exist on their architecture; absent
phases simply never appear in the snapshot.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple


class _PhaseTimer:
    """Context manager accumulating wall time into one phase bucket."""

    __slots__ = ("_registry", "_name", "_t0")

    def __init__(self, registry: "MetricsRegistry", name: str):
        self._registry = registry
        self._name = name

    def __enter__(self) -> "_PhaseTimer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._registry.add_phase(self._name, time.monotonic() - self._t0)


def _log_bounds(start: float, factor: float, count: int) -> Tuple[float, ...]:
    bounds = []
    edge = start
    for _ in range(count):
        bounds.append(edge)
        edge *= factor
    return tuple(bounds)


#: Default log-spaced bucket bounds (seconds): 100 µs doubling up to ~14 min.
#: 24 finite edges keep the Prometheus exposition compact while spanning
#: every latency this system produces, from one fused-era readback to a
#: deep 2pc-9 serve job with backoff retries.
DEFAULT_BOUNDS = _log_bounds(1e-4, 2.0, 24)


class Histogram:
    """Thread-safe log-spaced histogram with Prometheus semantics.

    Buckets are cumulative at export (`le` upper bounds, implicit +Inf),
    exactly the `_bucket/_sum/_count` contract scrapers expect.
    `quantile()` interpolates linearly inside the winning bucket — the
    standard Prometheus `histogram_quantile` estimate, so p99 here and
    p99 in Grafana agree."""

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count", "_max")

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        edges = tuple(sorted(bounds)) if bounds else DEFAULT_BOUNDS
        if not edges:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self.bounds = edges
        self._counts = [0] * (len(edges) + 1)  # +1 for the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1), linearly interpolated within the
        winning bucket; the +Inf bucket clamps to the observed max."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return 0.0
            rank = q * self._count
            cum = 0
            for idx, n in enumerate(self._counts):
                cum += n
                if cum >= rank and n:
                    if idx >= len(self.bounds):
                        return self._max
                    hi = self.bounds[idx]
                    lo = self.bounds[idx - 1] if idx else 0.0
                    frac = (rank - (cum - n)) / n
                    return min(lo + (hi - lo) * frac, self._max or hi)
            return self._max

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs, +Inf last (Prometheus shape)."""
        with self._lock:
            out = []
            cum = 0
            for edge, n in zip(self.bounds, self._counts):
                cum += n
                out.append((edge, cum))
            out.append((float("inf"), cum + self._counts[-1]))
            return out

    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly export: count/sum/max, cumulative buckets, and
        the three operator quantiles (p50/p95/p99)."""
        buckets = self.buckets()
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
        return {
            "count": count,
            "sum": round(total, 6),
            "max": round(mx, 6),
            "buckets": [
                ["+Inf" if le == float("inf") else le, n] for le, n in buckets
            ],
            "p50": round(self.quantile(0.50), 6),
            "p95": round(self.quantile(0.95), 6),
            "p99": round(self.quantile(0.99), 6),
        }


class MetricsRegistry:
    """Thread-safe counters + gauges + phase timers for one checker run."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Any] = {}
        self._phase_secs: Dict[str, float] = {}
        self._phase_calls: Dict[str, int] = {}
        self._histograms: Dict[str, Histogram] = {}

    # -- counters ------------------------------------------------------------

    def inc(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(delta)

    def get(self, name: str, default: int = 0) -> int:
        with self._lock:
            return self._counters.get(name, default)

    def inc_labeled(self, name: str, key: str, delta: int = 1) -> None:
        """Increment one series of a dict-valued counter (e.g. per-tenant
        request totals). The snapshot carries the whole dict under `name`;
        `render_prometheus(..., labels={name: "tenant"})` turns it into a
        labeled Prometheus family."""
        with self._lock:
            series = self._counters.get(name)
            if not isinstance(series, dict):
                series = {}
                self._counters[name] = series
            series[key] = series.get(key, 0) + int(delta)

    # -- gauges --------------------------------------------------------------

    def set_gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self._gauges[name] = value

    # -- phase timers --------------------------------------------------------

    def phase(self, name: str) -> _PhaseTimer:
        """`with registry.phase("device_era"): ...` accumulates wall time."""
        return _PhaseTimer(self, name)

    def add_phase(self, name: str, secs: float) -> None:
        with self._lock:
            self._phase_secs[name] = self._phase_secs.get(name, 0.0) + secs
            self._phase_calls[name] = self._phase_calls.get(name, 0) + 1

    def phase_ms(self) -> Dict[str, float]:
        """Cumulative milliseconds per phase (sorted by name)."""
        with self._lock:
            return {
                k: round(v * 1000.0, 3)
                for k, v in sorted(self._phase_secs.items())
            }

    # -- histograms ----------------------------------------------------------

    def histogram(
        self, name: str, bounds: Optional[Sequence[float]] = None
    ) -> Histogram:
        """The named histogram, created on first use (catalog above)."""
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = Histogram(bounds)
            return hist

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the named histogram."""
        self.histogram(name).observe(value)

    # -- export --------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Flat counters + gauges, plus nested ``phase_ms`` when any phase
        has been timed and nested ``histograms`` when any sample has been
        observed. This is what `Checker.telemetry()` returns."""
        with self._lock:
            out: Dict[str, Any] = {
                k: dict(v) if isinstance(v, dict) else v
                for k, v in self._counters.items()
            }
            out.update(self._gauges)
            if self._phase_secs:
                out["phase_ms"] = {
                    k: round(v * 1000.0, 3)
                    for k, v in sorted(self._phase_secs.items())
                }
            hists = dict(self._histograms)
        if hists:
            out["histograms"] = {
                name: hists[name].snapshot() for name in sorted(hists)
            }
        return out


# -- Prometheus exposition ----------------------------------------------------

_PROM_BAD = frozenset(" .-/:")


def _prom_name(name: str, prefix: str) -> str:
    safe = "".join("_" if ch in _PROM_BAD else ch for ch in name)
    return prefix + safe


def render_prometheus(
    snapshot: Dict[str, Any],
    prefix: str = "stateright_",
    labels: Dict[str, str] | None = None,
) -> str:
    """Render a telemetry snapshot (flat counters/gauges + nested
    ``phase_ms``) in the Prometheus text exposition format (v0.0.4).

    Every numeric metric becomes ``<prefix><name> <value>``; the phase
    timers flatten to ``<prefix>phase_ms{phase="<name>"}``. Snapshots
    merge counters and gauges into one namespace, so everything is
    emitted untyped; non-numeric values (the ``engine`` tag) become
    labels on an info-style gauge. ``labels`` maps the name of a
    dict-valued metric (`MetricsRegistry.inc_labeled`) to the label key
    its series render under, e.g. ``{"serve_tenant_requests": "tenant"}``
    -> ``serve_tenant_requests{tenant="acme"} 3``. Serve it from the
    Explorer via ``GET /metrics?format=prometheus`` (alias
    ``/metrics.prom``).
    """
    labels = labels or {}
    lines = []
    engine = snapshot.get("engine")
    if engine:
        name = _prom_name("engine_info", prefix)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{engine="{engine}"}} 1')
    for key in sorted(snapshot):
        value = snapshot[key]
        if key == "phase_ms" and isinstance(value, dict):
            name = _prom_name("phase_ms", prefix)
            lines.append(f"# TYPE {name} untyped")
            for phase in sorted(value):
                lines.append(f'{name}{{phase="{phase}"}} {value[phase]}')
            continue
        if key == "histograms" and isinstance(value, dict):
            for hist_name in sorted(value):
                snap = value[hist_name]
                if not isinstance(snap, dict) or "buckets" not in snap:
                    continue
                name = _prom_name(hist_name, prefix)
                lines.append(f"# TYPE {name} histogram")
                for le, n in snap["buckets"]:
                    lines.append(f'{name}_bucket{{le="{le}"}} {n}')
                lines.append(f'{name}_sum {snap.get("sum", 0)}')
                lines.append(f'{name}_count {snap.get("count", 0)}')
            continue
        if key in labels and isinstance(value, dict):
            name = _prom_name(key, prefix)
            label = labels[key]
            lines.append(f"# TYPE {name} untyped")
            for series in sorted(value):
                v = value[series]
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, (int, float)):
                    safe = str(series).replace("\\", "\\\\").replace('"', '\\"')
                    lines.append(f'{name}{{{label}="{safe}"}} {v}')
            continue
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        name = _prom_name(key, prefix)
        lines.append(f"# TYPE {name} untyped")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"
