"""The metrics registry: counters, gauges, and monotonic phase timers.

One `MetricsRegistry` per checker run (created in `HostEngineBase.__init__`)
backs `Checker.telemetry()` for every engine, replacing the old
`tpu_bfs`-only `_telemetry` dict. The API is deliberately tiny — engines hot
loops must stay hot — and every method is thread-safe (host engines mutate
from worker threads while `Checker.report()` polls from the caller's).

The full metric-name catalog — every counter, gauge, phase timer, and
histogram with its meaning, plus the flight-recorder record schema —
lives consolidated in ``stateright_tpu/obs/README.md`` (it used to be a
docstring table here, with README.md and serve/README.md both pointing
at it; one catalog now serves all three). Engines only populate the
names that exist on their architecture; absent names simply never
appear in the snapshot.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple


class _PhaseTimer:
    """Context manager accumulating wall time into one phase bucket."""

    __slots__ = ("_registry", "_name", "_t0")

    def __init__(self, registry: "MetricsRegistry", name: str):
        self._registry = registry
        self._name = name

    def __enter__(self) -> "_PhaseTimer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._registry.add_phase(self._name, time.monotonic() - self._t0)


def _log_bounds(start: float, factor: float, count: int) -> Tuple[float, ...]:
    bounds = []
    edge = start
    for _ in range(count):
        bounds.append(edge)
        edge *= factor
    return tuple(bounds)


#: Default log-spaced bucket bounds (seconds): 100 µs doubling up to ~14 min.
#: 24 finite edges keep the Prometheus exposition compact while spanning
#: every latency this system produces, from one fused-era readback to a
#: deep 2pc-9 serve job with backoff retries.
DEFAULT_BOUNDS = _log_bounds(1e-4, 2.0, 24)


class Histogram:
    """Thread-safe log-spaced histogram with Prometheus semantics.

    Buckets are cumulative at export (`le` upper bounds, implicit +Inf),
    exactly the `_bucket/_sum/_count` contract scrapers expect.
    `quantile()` interpolates linearly inside the winning bucket — the
    standard Prometheus `histogram_quantile` estimate, so p99 here and
    p99 in Grafana agree."""

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count", "_max")

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        edges = tuple(sorted(bounds)) if bounds else DEFAULT_BOUNDS
        if not edges:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self.bounds = edges
        self._counts = [0] * (len(edges) + 1)  # +1 for the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._max = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if value > self._max:
                self._max = value

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's observations into this one.

        Both histograms must share bucket bounds — merging across
        different bucketings would silently mis-bucket every count, so
        a mismatch raises ``ValueError`` instead. The other histogram is
        snapshotted under its own lock first, then applied under ours
        (sequentially, never nested), so concurrent observers on either
        side — or a self-merge — cannot deadlock."""
        if other.bounds != self.bounds:
            raise ValueError(
                "cannot merge histograms with different bucket bounds: "
                f"{len(self.bounds)} edges vs {len(other.bounds)}"
            )
        with other._lock:
            counts = list(other._counts)
            total, count, mx = other._sum, other._count, other._max
        with self._lock:
            for idx, n in enumerate(counts):
                self._counts[idx] += n
            self._sum += total
            self._count += count
            if mx > self._max:
                self._max = mx

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1), linearly interpolated within the
        winning bucket; the +Inf bucket clamps to the observed max."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return 0.0
            rank = q * self._count
            cum = 0
            for idx, n in enumerate(self._counts):
                cum += n
                if cum >= rank and n:
                    if idx >= len(self.bounds):
                        return self._max
                    hi = self.bounds[idx]
                    lo = self.bounds[idx - 1] if idx else 0.0
                    frac = (rank - (cum - n)) / n
                    return min(lo + (hi - lo) * frac, self._max or hi)
            return self._max

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs, +Inf last (Prometheus shape)."""
        with self._lock:
            out = []
            cum = 0
            for edge, n in zip(self.bounds, self._counts):
                cum += n
                out.append((edge, cum))
            out.append((float("inf"), cum + self._counts[-1]))
            return out

    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly export: count/sum/max, cumulative buckets, and
        the three operator quantiles (p50/p95/p99)."""
        buckets = self.buckets()
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
        return {
            "count": count,
            "sum": round(total, 6),
            "max": round(mx, 6),
            "buckets": [
                ["+Inf" if le == float("inf") else le, n] for le, n in buckets
            ],
            "p50": round(self.quantile(0.50), 6),
            "p95": round(self.quantile(0.95), 6),
            "p99": round(self.quantile(0.99), 6),
        }


class MetricsRegistry:
    """Thread-safe counters + gauges + phase timers for one checker run."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, Any] = {}
        self._phase_secs: Dict[str, float] = {}
        self._phase_calls: Dict[str, int] = {}
        self._histograms: Dict[str, Histogram] = {}

    # -- counters ------------------------------------------------------------

    def inc(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(delta)

    def get(self, name: str, default: int = 0) -> int:
        with self._lock:
            return self._counters.get(name, default)

    def inc_labeled(self, name: str, key: str, delta: int = 1) -> None:
        """Increment one series of a dict-valued counter (e.g. per-tenant
        request totals). The snapshot carries the whole dict under `name`;
        `render_prometheus(..., labels={name: "tenant"})` turns it into a
        labeled Prometheus family."""
        with self._lock:
            series = self._counters.get(name)
            if not isinstance(series, dict):
                series = {}
                self._counters[name] = series
            series[key] = series.get(key, 0) + int(delta)

    # -- gauges --------------------------------------------------------------

    def set_gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self._gauges[name] = value

    # -- phase timers --------------------------------------------------------

    def phase(self, name: str) -> _PhaseTimer:
        """`with registry.phase("device_era"): ...` accumulates wall time."""
        return _PhaseTimer(self, name)

    def add_phase(self, name: str, secs: float) -> None:
        with self._lock:
            self._phase_secs[name] = self._phase_secs.get(name, 0.0) + secs
            self._phase_calls[name] = self._phase_calls.get(name, 0) + 1

    def phase_ms(self) -> Dict[str, float]:
        """Cumulative milliseconds per phase (sorted by name)."""
        with self._lock:
            return {
                k: round(v * 1000.0, 3)
                for k, v in sorted(self._phase_secs.items())
            }

    # -- histograms ----------------------------------------------------------

    def histogram(
        self, name: str, bounds: Optional[Sequence[float]] = None
    ) -> Histogram:
        """The named histogram, created on first use (catalog above)."""
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = Histogram(bounds)
            return hist

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the named histogram."""
        self.histogram(name).observe(value)

    # -- export --------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Flat counters + gauges, plus nested ``phase_ms`` when any phase
        has been timed and nested ``histograms`` when any sample has been
        observed. This is what `Checker.telemetry()` returns."""
        with self._lock:
            out: Dict[str, Any] = {
                k: dict(v) if isinstance(v, dict) else v
                for k, v in self._counters.items()
            }
            out.update(self._gauges)
            if self._phase_secs:
                out["phase_ms"] = {
                    k: round(v * 1000.0, 3)
                    for k, v in sorted(self._phase_secs.items())
                }
            hists = dict(self._histograms)
        if hists:
            out["histograms"] = {
                name: hists[name].snapshot() for name in sorted(hists)
            }
        return out


# -- Prometheus exposition ----------------------------------------------------

_PROM_BAD = frozenset(" .-/:")


def _prom_name(name: str, prefix: str) -> str:
    safe = "".join("_" if ch in _PROM_BAD else ch for ch in name)
    return prefix + safe


#: Dict-valued metric name -> Prometheus label key for the sharded
#: engine's per-shard series (`MetricsRegistry.inc_labeled` counters and
#: dict gauges populated by parallel/mesh.py). Merge this into the
#: ``labels=`` argument of `render_prometheus` so the per-shard series
#: render as ``stateright_shard_steps{shard="3"} 1021`` instead of being
#: skipped as non-numeric; the serve and Explorer endpoints do.
SHARD_SERIES_LABELS = {
    "shard_steps": "shard",
    "shard_states_generated": "shard",
    "shard_exchange_rows": "shard",
    "shard_frontier_rows": "shard",
    "shard_load_factor": "shard",
}

#: Dict-valued memory-ledger gauge (obs/memory.py MemoryRecorder) ->
#: Prometheus label key, so per-component residency renders as
#: ``stateright_memory_bytes{component="visited_table"} 67108864``.
#: Merge alongside SHARD_SERIES_LABELS wherever snapshots are rendered.
MEMORY_SERIES_LABELS = {
    "memory_bytes": "component",
}

#: Dict-valued deployment metric (obs/netobs.py NetObs) -> Prometheus
#: label key: the per-actor and per-fault-kind series a spawned actor
#: system populates live, rendering as e.g.
#: ``stateright_actor_messages_sent{actor="1"} 42`` and
#: ``stateright_fault_injected{kind="drop"} 3``. Merge alongside the
#: other *_SERIES_LABELS wherever deployment snapshots are rendered.
NETOBS_SERIES_LABELS = {
    "actor_handlers": "actor",
    "actor_messages_sent": "actor",
    "actor_messages_delivered": "actor",
    "actor_timer_set": "actor",
    "actor_timer_fired": "actor",
    "actor_mailbox_depth": "actor",
    "fault_injected": "kind",
    "conformance_fault_kinds": "kind",
}


def render_prometheus(
    snapshot: Dict[str, Any],
    prefix: str = "stateright_",
    labels: Dict[str, str] | None = None,
) -> str:
    """Render a telemetry snapshot (flat counters/gauges + nested
    ``phase_ms``) in the Prometheus text exposition format (v0.0.4).

    Every numeric metric becomes ``<prefix><name> <value>``; the phase
    timers flatten to ``<prefix>phase_ms{phase="<name>"}``. Snapshots
    merge counters and gauges into one namespace, so everything is
    emitted untyped; non-numeric values (the ``engine`` tag) become
    labels on an info-style gauge. ``labels`` maps the name of a
    dict-valued metric (`MetricsRegistry.inc_labeled`) to the label key
    its series render under, e.g. ``{"serve_tenant_requests": "tenant"}``
    -> ``serve_tenant_requests{tenant="acme"} 3``. Serve it from the
    Explorer via ``GET /metrics?format=prometheus`` (alias
    ``/metrics.prom``).
    """
    labels = labels or {}
    lines = []
    engine = snapshot.get("engine")
    if engine:
        name = _prom_name("engine_info", prefix)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{engine="{engine}"}} 1')
    for key in sorted(snapshot):
        value = snapshot[key]
        if key == "phase_ms" and isinstance(value, dict):
            name = _prom_name("phase_ms", prefix)
            lines.append(f"# TYPE {name} untyped")
            for phase in sorted(value):
                lines.append(f'{name}{{phase="{phase}"}} {value[phase]}')
            continue
        if key == "histograms" and isinstance(value, dict):
            for hist_name in sorted(value):
                snap = value[hist_name]
                if not isinstance(snap, dict) or "buckets" not in snap:
                    continue
                name = _prom_name(hist_name, prefix)
                lines.append(f"# TYPE {name} histogram")
                for le, n in snap["buckets"]:
                    lines.append(f'{name}_bucket{{le="{le}"}} {n}')
                lines.append(f'{name}_sum {snap.get("sum", 0)}')
                lines.append(f'{name}_count {snap.get("count", 0)}')
            continue
        if key in labels and isinstance(value, dict):
            name = _prom_name(key, prefix)
            label = labels[key]
            lines.append(f"# TYPE {name} untyped")
            for series in sorted(value):
                v = value[series]
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, (int, float)):
                    safe = str(series).replace("\\", "\\\\").replace('"', '\\"')
                    lines.append(f'{name}{{{label}="{safe}"}} {v}')
            continue
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        name = _prom_name(key, prefix)
        lines.append(f"# TYPE {name} untyped")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"
