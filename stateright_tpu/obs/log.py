"""Leveled JSON-lines structured logger for library code.

Library components (engines, serve, durability) must not `print()`: a
run server's operator needs grep-able, machine-parseable lines with a
component name, a level, and — when the message concerns a job — the
job's trace_id, so a log line joins the span ledger on the same key.

One line per event::

    {"ts": 1754380800.123, "level": "warning", "component": "serve.http",
     "msg": "journal replay recovered jobs", "recovered": 3,
     "trace_id": "9f86d081..."}

Usage::

    from stateright_tpu.obs.log import get_logger
    log = get_logger("engines.common")
    log.warning("checkpoint rejected", path=path, error=str(err))

Configuration is environment-first (no setup call needed):

  ``STATERIGHT_LOG``       minimum level: debug|info|warning|error|off
                           (default ``warning`` — library code stays
                           quiet unless something needs attention)
  ``STATERIGHT_LOG_FILE``  sink path (append mode); default stderr.

`configure(level=..., sink=...)` overrides both at runtime (tests use a
list sink to capture records). Loggers are cheap views over one shared
module-level config, so `configure` affects every component at once.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Union

__all__ = ["Logger", "configure", "get_logger", "LEVELS"]

LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40, "off": 100}

_lock = threading.Lock()
_state: Dict[str, Any] = {"threshold": None, "sink": None}


def _env_threshold() -> int:
    name = os.environ.get("STATERIGHT_LOG", "warning").strip().lower()
    return LEVELS.get(name, LEVELS["warning"])


def configure(
    level: Optional[str] = None,
    sink: Optional[Union[str, List[Dict[str, Any]], Callable, io.IOBase]] = None,
) -> None:
    """Override the env config. `level` is a LEVELS name; `sink` is a
    file path (append), a file-like object, a callable taking the record
    dict, or a list to append record dicts to (test capture). Pass
    nothing to reset back to environment-driven behavior."""
    with _lock:
        if level is None and sink is None:
            _state["threshold"] = None
            _state["sink"] = None
            return
        if level is not None:
            if level not in LEVELS:
                raise ValueError(f"unknown log level {level!r}; use one of {sorted(LEVELS)}")
            _state["threshold"] = LEVELS[level]
        if sink is not None:
            _state["sink"] = sink


def _emit(record: Dict[str, Any]) -> None:
    with _lock:
        sink = _state["sink"]
        if sink is None:
            sink = os.environ.get("STATERIGHT_LOG_FILE") or None
        if isinstance(sink, list):
            sink.append(record)
            return
        if callable(sink) and not isinstance(sink, io.IOBase):
            sink(record)
            return
        line = json.dumps(record, default=repr)
        if isinstance(sink, str):
            with open(sink, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
            return
        stream = sink if sink is not None else sys.stderr
        try:
            stream.write(line + "\n")
            stream.flush()
        except (ValueError, OSError):
            pass  # a closed stderr (test teardown) must not crash the caller


class Logger:
    """A component-scoped view over the shared log config."""

    __slots__ = ("component",)

    def __init__(self, component: str):
        self.component = component

    def enabled(self, level: str) -> bool:
        with _lock:
            threshold = _state["threshold"]
        if threshold is None:
            threshold = _env_threshold()
        return LEVELS.get(level, 0) >= threshold

    def log(self, level: str, msg: str, **fields: Any) -> None:
        if not self.enabled(level):
            return
        self.force(level, msg, **fields)

    def force(self, level: str, msg: str, **fields: Any) -> None:
        """Emit regardless of the configured threshold — for channels
        with their own explicit opt-in gate (e.g. the device engine's
        ``STPU_DEBUG`` stream), where setting the gate IS the request
        for output."""
        record: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "level": level,
            "component": self.component,
            "msg": msg,
        }
        record.update(fields)
        _emit(record)

    def debug(self, msg: str, **fields: Any) -> None:
        self.log("debug", msg, **fields)

    def info(self, msg: str, **fields: Any) -> None:
        self.log("info", msg, **fields)

    def warning(self, msg: str, **fields: Any) -> None:
        self.log("warning", msg, **fields)

    def error(self, msg: str, **fields: Any) -> None:
        self.log("error", msg, **fields)


_loggers: Dict[str, Logger] = {}


def get_logger(component: str) -> Logger:
    """The (cached) logger for a dotted component name."""
    with _lock:
        logger = _loggers.get(component)
        if logger is None:
            logger = _loggers[component] = Logger(component)
        return logger
