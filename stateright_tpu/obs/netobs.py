"""Network flight recorder: live deployment observability for real actor runs.

The checker side has stageprof/flight/spans/memory; this module is the
*deployment* side's equivalent lens — what actually happened on the wire
when a system ran over loopback UDP under a seeded `FaultPlan`:

  `NetObs`              per-deployment labeled runtime metrics (counters,
                        gauges, histograms in a `MetricsRegistry`),
                        populated live by both spawn engines' handler
                        hooks, by the `FaultInjector` at injection time,
                        and by the `TraceRecorder`'s send/deliver matcher
  `assign_lamport`      the deterministic causal-order reconstructor:
                        Lamport-stamps a trace's events (recomputing
                        exactly what a schema-v2 recorder wrote, and
                        backfilling v1 traces that carry no stamps)
  `causal_order`        a total-order extension of happened-before —
                        events sorted by (lc, actor, seq); a pure
                        function of the trace, so two engines that made
                        the same logical run reconstruct the same order
  `causal_past`         the last K events that happened-before a given
                        event (per-actor program order + send->deliver
                        edges, transitively) — divergence forensics
  `flow_pairs`          every (send event, deliver event) match; drops
                        never pair, duplicates pair as redeliveries
  `export_chrome_trace` Perfetto-loadable Chrome trace: one lane per
                        actor, handler slices, fault instants, and
                        ph:"s"/"f" flow arrows from each send to its
                        deliver — a faulted run opens as a message-
                        sequence diagram
  `deployment_view`     the Explorer's ``GET /deployment`` payload:
                        actor topology, per-edge delivered/fault counts,
                        and a formatted live event tail

The matching discipline shared by the recorder, the reconstructor, and
the flow exporter: a ``deliver`` event is paired with the earliest
unconsumed ``send`` event carrying the same (src, dst, canonical msg)
key — valid because the recorder writes an actor's ``send`` line before
the datagram hits the wire, so the send line always precedes its deliver
line in the file, and loopback UDP is FIFO per socket pair. A deliver
with no unconsumed send is a *redelivery* (a duplicated datagram) and
pairs with the most recently consumed send for its key.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry

#: Default number of happened-before events rendered with a divergence.
DEFAULT_CAUSAL_PAST_K = 8

#: Slice duration (µs) drawn for handler events that carry no ``dur``
#: (v1 traces): wide enough for Perfetto to anchor flow arrows.
_DEFAULT_SLICE_US = 30.0


# ---------------------------------------------------------------------------
# Live per-deployment metrics.
# ---------------------------------------------------------------------------

class NetObs:
    """Per-deployment runtime metrics (see obs/README.md, "Deployment
    observability"). One instance per `spawn`; both engines call the same
    hooks, so on an identical logical run the counters are identical.

    Data sources:

      - engine handler hooks: `handler(index, kind, duration)` after
        every on_start/on_msg/on_timeout/on_random;
      - engine command dispatch: `command(index, kind)` per Out command,
        `transmit()` per datagram actually written to the wire;
      - `FaultInjector`: `fault(kind)` at decision time;
      - `TraceRecorder`'s matcher: `latency(secs)` per matched deliver
        and `mailbox(outstanding)` with per-actor in-flight depth.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self._lock = threading.Lock()
        self._transmissions = 0
        self._delivered = 0

    # -- engine hooks --------------------------------------------------------

    def attach(self, actors, engine: str) -> None:
        """Called once per spawn with the resolved (Id, Actor) roster."""
        self.registry.set_gauge("deployment_actors", len(actors))
        self.registry.set_gauge("engine", engine)

    def handler(self, index: int, kind: str, duration: Optional[float] = None) -> None:
        """One handler execution on actor `index` (init/deliver/timeout/random)."""
        key = str(index)
        self.registry.inc_labeled("actor_handlers", key)
        if kind == "deliver":
            self.registry.inc_labeled("actor_messages_delivered", key)
            with self._lock:
                self._delivered += 1
                in_flight = self._transmissions - self._delivered
            self.registry.set_gauge("net_in_flight", max(in_flight, 0))
        elif kind == "timeout":
            self.registry.inc_labeled("actor_timer_fired", key)
        if duration is not None:
            self.registry.observe("handler_duration_secs", duration)

    def command(self, index: int, kind: str) -> None:
        """One Out command dispatched by actor `index` (send/timer_set/...)."""
        if kind == "send":
            self.registry.inc_labeled("actor_messages_sent", str(index))
        elif kind == "timer_set":
            self.registry.inc_labeled("actor_timer_set", str(index))

    def transmit(self) -> None:
        """One datagram actually written to the wire (post-injector: drops
        never transmit, duplicates transmit twice)."""
        self.registry.inc("net_transmissions")
        with self._lock:
            self._transmissions += 1
            in_flight = self._transmissions - self._delivered
        self.registry.set_gauge("net_in_flight", max(in_flight, 0))

    def fault(self, kind: str) -> None:
        """One fault-injector decision that was not a clean deliver."""
        self.registry.inc_labeled("fault_injected", kind)

    def latency(self, secs: float) -> None:
        """Send-line-to-deliver-line latency of one matched transmission."""
        self.registry.observe("delivery_latency_secs", secs)

    def mailbox(self, outstanding: Dict[int, int]) -> None:
        """Per-actor in-flight depth (sends recorded, not yet delivered)."""
        self.registry.set_gauge(
            "actor_mailbox_depth", {str(k): v for k, v in outstanding.items()}
        )

    # -- export --------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return self.registry.snapshot()


def as_netobs(netobs, default: bool = False) -> Optional[NetObs]:
    """Normalize `spawn`'s ``netobs=`` argument: ``None`` auto-creates one
    when `default` says the deployment is instrumented (recording or
    fault-injecting), ``False`` disables, ``True`` forces one, and an
    existing `NetObs` is used as-is."""
    if isinstance(netobs, NetObs):
        return netobs
    if netobs is False:
        return None
    if netobs is True:
        return NetObs()
    if netobs is None:
        return NetObs() if default else None
    raise TypeError(f"netobs must be a NetObs, True/False, or None; got {netobs!r}")


# ---------------------------------------------------------------------------
# Causal reconstruction (shared by both engines: a pure trace function).
# ---------------------------------------------------------------------------

def _msg_key(msg: Any) -> str:
    return json.dumps(msg, sort_keys=True)


def assign_lamport(events: List[dict]) -> List[dict]:
    """Lamport-stamp a trace's events: returns copies in file order with
    ``lc`` on every handler/command event, ``sent_by`` ([src actor, send
    seq]) on every matched deliver, and ``redelivery`` on duplicates.

    This recomputes exactly what a schema-v2 `TraceRecorder` stamped at
    record time (locked by tests/test_netobs.py), so v1 traces load into
    the same causal structure. Fault events pass through unstamped —
    they are link metadata, not handler occurrences."""
    clocks: Dict[int, int] = {}
    pending: Dict[Tuple[Any, Any, str], deque] = {}
    consumed: Dict[Tuple[Any, Any, str], dict] = {}
    out: List[dict] = []
    for ev in events:
        kind = ev.get("kind")
        if kind in ("fault", "meta"):
            out.append(ev)
            continue
        actor = ev.get("actor")
        stamped = dict(ev)
        stamped.pop("lc", None)
        stamped.pop("sent_by", None)
        stamped.pop("redelivery", None)
        if "cause" in ev:  # command child
            lc = clocks.get(actor, 0) + 1
            clocks[actor] = lc
            stamped["lc"] = lc
            if kind == "send":
                key = (actor, ev.get("dst"), _msg_key(ev.get("msg")))
                pending.setdefault(key, deque()).append(
                    {"actor": actor, "seq": ev.get("seq"), "lc": lc, "ts": ev.get("ts")}
                )
        else:  # handler event
            entry = None
            if kind == "deliver":
                key = (ev.get("src"), actor, _msg_key(ev.get("msg")))
                queue = pending.get(key)
                if queue:
                    entry = queue.popleft()
                    consumed[key] = entry
                else:
                    entry = consumed.get(key)
                    if entry is not None:
                        stamped["redelivery"] = True
            if entry is not None:
                lc = max(clocks.get(actor, 0), entry["lc"]) + 1
                stamped["sent_by"] = [entry["actor"], entry["seq"]]
            else:
                lc = clocks.get(actor, 0) + 1
            clocks[actor] = lc
            stamped["lc"] = lc
        out.append(stamped)
    return out


def causal_order(events: List[dict]) -> List[dict]:
    """A deterministic total-order extension of happened-before: the
    stamped handler/command events sorted by (lc, actor, seq). A pure
    function of the trace — two engines that made the same logical run
    (same seeded FaultPlan, same message chain) reconstruct byte-identical
    orders even though their wall-clock timestamps differ."""
    stamped = [ev for ev in assign_lamport(events) if "lc" in ev]
    return sorted(stamped, key=lambda ev: (ev["lc"], ev["actor"], ev["seq"]))


def causal_past(
    events: List[dict],
    actor: int,
    seq: int,
    k: int = DEFAULT_CAUSAL_PAST_K,
) -> List[dict]:
    """The last `k` events that happened-before the (actor, seq) event:
    the transitive closure of per-actor program order plus the
    send->deliver edges `assign_lamport` matched, sorted causally.
    `events` may be raw (v1) or already stamped — stamps are recomputed."""
    stamped = [ev for ev in assign_lamport(events) if "lc" in ev]
    by_ref = {(ev["actor"], ev["seq"]): ev for ev in stamped}
    per_actor: Dict[int, List[dict]] = {}
    for ev in stamped:
        per_actor.setdefault(ev["actor"], []).append(ev)
    for seqs in per_actor.values():
        seqs.sort(key=lambda ev: ev["seq"])

    target = by_ref.get((actor, seq))
    if target is None:
        return []

    def predecessors(ev: dict) -> List[dict]:
        preds = []
        lane = per_actor[ev["actor"]]
        pos = next(
            (i for i, cand in enumerate(lane) if cand["seq"] == ev["seq"]), 0
        )
        if pos > 0:
            preds.append(lane[pos - 1])
        sent_by = ev.get("sent_by")
        if sent_by is not None:
            src_ev = by_ref.get((sent_by[0], sent_by[1]))
            if src_ev is not None:
                preds.append(src_ev)
        return preds

    seen = set()
    frontier = predecessors(target)
    ancestors: List[dict] = []
    while frontier:
        ev = frontier.pop()
        ref = (ev["actor"], ev["seq"])
        if ref in seen:
            continue
        seen.add(ref)
        ancestors.append(ev)
        frontier.extend(predecessors(ev))
    ancestors.sort(key=lambda ev: (ev["lc"], ev["actor"], ev["seq"]))
    return ancestors[-k:]


def format_event(ev: dict) -> str:
    """One-line rendering of a (stamped) trace event for causal-past
    reports and the deployment view's event tail."""
    kind = ev.get("kind", "?")
    parts = [f"lc={ev.get('lc', '?')}", f"actor={ev.get('actor')}"]
    if "seq" in ev:
        parts.append(f"seq={ev['seq']}")
    parts.append(kind)
    if kind == "deliver":
        parts.append(f"src={ev.get('src')}")
        parts.append(f"msg={json.dumps(ev.get('msg'))}")
        if ev.get("redelivery"):
            parts.append("(redelivery)")
    elif kind == "send":
        parts.append(f"dst={ev.get('dst')}")
        parts.append(f"msg={json.dumps(ev.get('msg'))}")
    elif kind in ("timeout", "timer_set", "timer_cancel"):
        parts.append(f"timer={json.dumps(ev.get('timer'))}")
    elif kind == "random":
        parts.append(f"value={json.dumps(ev.get('value'))}")
    elif kind == "choose":
        parts.append(f"key={ev.get('key')}")
    elif kind == "fault":
        parts = [f"actor={ev.get('actor')}", "fault", ev.get("fault", "?"),
                 f"dst={ev.get('dst')}", f"link_seq={ev.get('link_seq')}"]
    return " ".join(str(p) for p in parts)


def flow_pairs(events: List[dict]) -> List[Tuple[dict, dict]]:
    """Every (send event, deliver event) matched pair in the trace. Each
    non-dropped transmission that was delivered contributes exactly one
    pair (duplicates pair as redeliveries of the same send); dropped
    datagrams contribute none."""
    stamped = assign_lamport(events)
    sends = {
        (ev["actor"], ev["seq"]): ev
        for ev in stamped
        if ev.get("kind") == "send"
    }
    pairs: List[Tuple[dict, dict]] = []
    for ev in stamped:
        if ev.get("kind") != "deliver":
            continue
        sent_by = ev.get("sent_by")
        if sent_by is None:
            continue
        send_ev = sends.get((sent_by[0], sent_by[1]))
        if send_ev is not None:
            pairs.append((send_ev, ev))
    return pairs


# ---------------------------------------------------------------------------
# Chrome trace export (Perfetto message-sequence diagram).
# ---------------------------------------------------------------------------

def _load(trace) -> Tuple[dict, List[dict]]:
    if isinstance(trace, (str, bytes)) or hasattr(trace, "__fspath__"):
        from ..conformance.events import load_trace  # lazy: avoids a cycle

        return load_trace(trace)
    return trace


def export_chrome_trace(trace, path: str) -> int:
    """Write a recorded deployment trace (a path or ``(meta, events)``)
    as a Chrome trace-event JSON array at `path`: one lane (tid) per
    actor, handler events as duration slices, command/fault events as
    instants, and one ``ph:"s"`` / ``ph:"f"`` flow pair per matched
    send->deliver (the arrows Perfetto draws as a sequence diagram).
    Returns the number of flow pairs emitted."""
    meta, events = _load(trace)
    stamped = assign_lamport(events)
    handler_ts = [ev.get("ts", 0.0) for ev in stamped]
    t0 = min(handler_ts) if handler_ts else 0.0

    def us(ev: dict) -> float:
        return round((ev.get("ts", t0) - t0) * 1e6, 1)

    records: List[dict] = [
        {"ph": "M", "pid": 1, "tid": 0, "name": "process_name",
         "args": {"name": f"deployment ({meta.get('engine', '?')})"}}
    ]
    for entry in meta.get("actors", []):
        records.append(
            {"ph": "M", "pid": 1, "tid": entry["index"], "name": "thread_name",
             "args": {"name": f"actor {entry['index']} ({entry['actor']}) "
                              f"{entry.get('addr', '')}"}}
        )
    for ev in stamped:
        kind = ev.get("kind")
        if kind == "fault":
            records.append(
                {"ph": "i", "s": "t", "pid": 1, "tid": ev.get("actor", 0),
                 "ts": us(ev), "cat": "fault", "name": f"fault:{ev.get('fault')}",
                 "args": {"dst": ev.get("dst"), "link_seq": ev.get("link_seq"),
                          "seed_key": ev.get("seed_key")}}
            )
            continue
        if "cause" in ev:
            records.append(
                {"ph": "i", "s": "t", "pid": 1, "tid": ev["actor"], "ts": us(ev),
                 "cat": "cmd", "name": kind,
                 "args": {"seq": ev["seq"], "lc": ev.get("lc"),
                          "dst": ev.get("dst"), "msg": ev.get("msg"),
                          "timer": ev.get("timer")}}
            )
            continue
        dur_us = max(float(ev.get("dur", 0.0)) * 1e6, _DEFAULT_SLICE_US)
        records.append(
            {"ph": "X", "pid": 1, "tid": ev["actor"], "ts": us(ev),
             "dur": round(dur_us, 1), "cat": "handler", "name": kind,
             "args": {"seq": ev["seq"], "lc": ev.get("lc"),
                      "src": ev.get("src"), "msg": ev.get("msg"),
                      "timer": ev.get("timer"), "value": ev.get("value")}}
        )
    # Flow arrows: the "s" anchors inside the sending handler's slice (the
    # send instant shares its parent's ts), the "f" inside the deliver slice.
    pairs = flow_pairs(events)
    for flow_id, (send_ev, deliver_ev) in enumerate(pairs):
        common = {"cat": "net", "name": "msg", "id": flow_id, "pid": 1}
        records.append(
            {**common, "ph": "s", "tid": send_ev["actor"], "ts": us(send_ev) + 1.0}
        )
        records.append(
            {**common, "ph": "f", "bp": "e", "tid": deliver_ev["actor"],
             "ts": us(deliver_ev) + 1.0}
        )
    with open(path, "w", encoding="utf-8") as f:
        f.write("[\n")
        f.write(",\n".join(json.dumps(r) for r in records))
        f.write("\n]\n")
    return len(pairs)


# ---------------------------------------------------------------------------
# The Explorer's GET /deployment payload.
# ---------------------------------------------------------------------------

def deployment_view(
    trace_path: Optional[str] = None,
    handle=None,
    tail: int = 40,
) -> Dict[str, Any]:
    """Actor topology + per-edge delivery/fault counts + live event tail.

    `trace_path` names a recorded (possibly still-growing — `load_trace`
    tolerates a torn final line) conformance trace; `handle` is a live
    `SpawnHandle`/`NativeSpawnHandle` whose `telemetry()` contributes the
    NetObs metric snapshot. At least one must be given."""
    if trace_path is None and handle is None:
        raise KeyError(
            "no deployment attached (start the Explorer with --trace PATH "
            "or serve(..., deployment=handle))"
        )
    view: Dict[str, Any] = {"ts": time.time()}
    if handle is not None:
        telemetry = getattr(handle, "telemetry", None)
        if callable(telemetry):
            view["telemetry"] = telemetry()
    if trace_path is None:
        return view

    meta, events = _load(trace_path)
    stamped = assign_lamport(events)
    actors = [
        {"index": entry["index"], "actor": entry["actor"],
         "addr": entry.get("addr", ""), "handlers": 0, "sent": 0, "delivered": 0}
        for entry in meta.get("actors", [])
    ]

    def actor_row(index) -> Optional[dict]:
        return actors[index] if isinstance(index, int) and 0 <= index < len(actors) else None

    edges: Dict[Tuple[Any, Any], dict] = {}

    def edge(src, dst) -> dict:
        key = (src, dst)
        if key not in edges:
            edges[key] = {"src": src, "dst": dst, "sent": 0, "delivered": 0,
                          "faults": {}}
        return edges[key]

    for ev in stamped:
        kind = ev.get("kind")
        if kind == "fault":
            counts = edge(ev.get("actor"), ev.get("dst"))["faults"]
            fault = ev.get("fault", "?")
            counts[fault] = counts.get(fault, 0) + 1
        elif "cause" in ev:
            if kind == "send":
                edge(ev["actor"], ev.get("dst"))["sent"] += 1
                row = actor_row(ev["actor"])
                if row is not None:
                    row["sent"] += 1
        else:
            row = actor_row(ev["actor"])
            if row is not None:
                row["handlers"] += 1
                if kind == "deliver":
                    row["delivered"] += 1
            if kind == "deliver":
                edge(ev.get("src"), ev["actor"])["delivered"] += 1

    view.update(
        {
            "path": str(trace_path),
            "engine": meta.get("engine"),
            "v": meta.get("v", 1),
            "faults_plan": meta.get("faults"),
            "actors": actors,
            "edges": [edges[key] for key in sorted(edges, key=str)],
            "events": len(events),
            "tail": [format_event(ev) for ev in stamped[-max(tail, 0):]],
        }
    )
    return view
