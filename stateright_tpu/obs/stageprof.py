"""Per-stage era profiling: isolated-stage microbenches + attribution.

The device engines run their whole search inside `lax.while_loop` eras —
one dispatch, thousands of fused steps — so there is no place to put a
host timer *inside* a step: XLA fuses the stages and the platform has no
device-side timestamp primitive the loop could carry. What CAN be
measured is each stage in isolation, at the exact shapes the era loop
compiles for: each engine builds one jitted kernel per stage (successor
expansion, fingerprint/hash, visited-set probe, claim dedup, validity
compaction, ring append, canonicalization — see the engine's
`_build_stage_kernels`) that repeats that single stage `iters` times
inside a `lax.fori_loop`, with a data dependence chaining the iterations
so XLA can neither elide nor overlap them. Amortizing `iters` repetitions
behind one dispatch matters on the target platform, where every dispatch
costs a ~100ms tunnel round-trip that would otherwise swamp sub-ms
stages; an empty-loop null kernel measures that fixed dispatch cost and
is subtracted out.

Attribution is PROPORTIONAL: the isolated per-step stage costs give each
stage's share, and those shares scale the run's measured `device_era`
wall time — so the reported `stage_*` phase timers sum to the era total
by construction, while the raw isolated measurements stay visible as the
`stage_us_per_step` gauge. The `stage_profile_model_pct` gauge reports
how much of the measured era time the isolated-stage cost model predicts
(per-step sum x steps / era wall time): near 100 means the stages account
for the loop; far below means fixed per-step overheads (loop condition,
carry bookkeeping) or fusion effects dominate, far above means the
isolated kernels run slower than the fused loop (fusion wins).

Surfacing is automatic once the phases are in the registry: the
`stage_*` keys ride `Checker.telemetry()['phase_ms']`, the JSONL trace's
`run_end` event, the Chrome trace's per-phase duration lanes, and
Prometheus exposition — see the phase catalog in obs/metrics.py.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Tuple

# Canonical display order for the per-stage breakdown (engines populate
# the subset their architecture has; e.g. `canon` only under symmetry,
# `exchange` only on the sharded engine, the walk stages only on the
# simulation engine).
STAGE_ORDER = (
    "expand",
    "hash",
    "probe",
    "claim",
    "compact",
    "ring",
    "canon",
    "exchange",
    "cycle",
    "choose",
    "record",
)


def build_null_kernel(iters: int):
    """An empty `iters`-round fori loop: measures the fixed dispatch +
    readback cost a stage kernel pays regardless of its work."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def null(seed):
        def body(_i, c):
            return c + jnp.uint32(1)

        return lax.fori_loop(0, iters, body, seed)

    return null


def time_dispatch(fn: Callable, args: Tuple, repeats: int = 2) -> float:
    """Best-of-`repeats` wall seconds for one dispatch of a jitted kernel.

    The first (untimed) call compiles and warms; every timed call is
    bracketed by a host readback of the kernel's small output, because on
    the target platform `jax.block_until_ready` does not actually block
    (README "known platform limits") — call + readback is the honest
    completion signal.
    """
    import numpy as np

    np.asarray(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def measure_stage_kernels(
    kernels: Dict[str, Tuple[Callable, Tuple]],
    iters: int,
    repeats: int = 2,
) -> Dict[str, float]:
    """Time each stage kernel; returns per-ITERATION seconds per stage,
    with the null-kernel dispatch baseline subtracted (floored at 0)."""
    import jax.numpy as jnp

    null = build_null_kernel(iters)
    seed = jnp.asarray(1, dtype=jnp.uint32)
    base = time_dispatch(null, (seed,), repeats)
    out: Dict[str, float] = {}
    for name, (fn, args) in kernels.items():
        secs = time_dispatch(fn, args, repeats)
        out[name] = max(0.0, secs - base) / max(1, iters)
    return out


def attribute_stages(
    metrics,
    per_step_secs: Dict[str, float],
    era_secs: float,
    steps: int,
    iters: int,
) -> Dict[str, float]:
    """Record the breakdown into the metrics registry as `stage_<name>`
    phase timers scaled so their sum equals `era_secs` exactly, plus the
    raw-measurement gauges. Returns the scaled seconds per stage."""
    total = sum(per_step_secs.values())
    scaled: Dict[str, float] = {}
    if total > 0.0 and era_secs > 0.0:
        for name, secs in per_step_secs.items():
            share = era_secs * (secs / total)
            metrics.add_phase("stage_" + name, share)
            scaled["stage_" + name] = share
    metrics.set_gauge("stage_profile_iters", int(iters))
    metrics.set_gauge(
        "stage_us_per_step",
        {k: round(v * 1e6, 3) for k, v in per_step_secs.items()},
    )
    if steps and era_secs > 0.0:
        metrics.set_gauge(
            "stage_profile_model_pct",
            round(100.0 * total * steps / era_secs, 1),
        )
    return scaled


def stage_rows(phase_ms: Dict[str, float]):
    """(name, ms) rows for every populated stage phase, in STAGE_ORDER
    then alphabetically for any stage this module doesn't know."""
    rows = []
    seen = set()
    for name in STAGE_ORDER:
        key = "stage_" + name
        if key in phase_ms:
            rows.append((name, phase_ms[key]))
            seen.add(key)
    for key in sorted(phase_ms):
        if key.startswith("stage_") and key not in seen:
            rows.append((key[len("stage_"):], phase_ms[key]))
    return rows
