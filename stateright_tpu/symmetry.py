"""Symmetry reduction: rewrite plans and equivalence-class representatives.

Reference: src/checker/{representative,rewrite,rewrite_plan}.rs. A state's
`representative()` returns a canonical member of its symmetry equivalence
class (e.g. under permutation of process ids); the DFS and simulation engines
insert representative fingerprints into the visited set so symmetric states
are explored once (dfs.rs:309-318, simulation.rs:285-289).

`RewritePlan` is the workhorse: built from the values whose sorted order
defines the canonical permutation (`from_values_to_sort`,
rewrite_plan.rs:77-106), it rewrites id-valued data recursively through
containers (the role of the `Rewrite` blanket impls, rewrite.rs:18-163) and
permutes id-indexed sequences via `reindex` (rewrite_plan.rs:108-124).

Python adaptation: Rust drives rewriting by the static type `Rewrite<R>`;
here the plan carries the id *type* (`domain`, e.g. `Id`) and rewriting
walks values structurally — instances of the domain type are remapped,
containers/dataclasses recurse, everything else passes through. Custom
classes can implement `rewrite_with(plan)` to control their own rewriting.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence


class Representative:
    """Mixin/protocol: states that can produce a canonical representative.

    Reference: representative.rs:65-68.
    """

    def representative(self) -> "Representative":
        raise NotImplementedError


class RewritePlan:
    """A permutation of a dense id space, applied recursively to values.

    `mapping[i]` is the new id for old id `i`.
    """

    __slots__ = ("domain", "mapping", "_inverse")

    def __init__(self, domain: type, mapping: Sequence[int]):
        if domain is int:
            raise TypeError(
                "RewritePlan domain must be a dedicated id type (e.g. Id), "
                "not int: rewriting would remap every integer in the state."
            )
        self.domain = domain
        self.mapping = list(mapping)
        inv = [0] * len(self.mapping)
        for old, new in enumerate(self.mapping):
            inv[new] = old
        self._inverse = inv

    @staticmethod
    def from_values_to_sort(domain: type, values: Sequence[Any]) -> "RewritePlan":
        """Canonical permutation from sorting `values` (stable).

        Old id i maps to the rank of values[i] in the sorted order —
        mirroring rewrite_plan.rs:84-106.
        """
        order = sorted(range(len(values)), key=lambda i: values[i])
        mapping = [0] * len(values)
        for rank, old in enumerate(order):
            mapping[old] = rank
        return RewritePlan(domain, mapping)

    # -- application ---------------------------------------------------------

    def rewrite(self, x: Any) -> Any:
        """Recursively rewrite domain-typed ids inside `x`."""
        if isinstance(x, self.domain):
            return self.domain(self.mapping[int(x)])
        if hasattr(x, "rewrite_with"):
            return x.rewrite_with(self)
        if isinstance(x, tuple):
            if hasattr(x, "_fields"):  # NamedTuple: preserve the type
                return type(x)(*(self.rewrite(v) for v in x))
            return tuple(self.rewrite(v) for v in x)
        if isinstance(x, list):
            return [self.rewrite(v) for v in x]
        if isinstance(x, frozenset):
            return frozenset(self.rewrite(v) for v in x)
        if isinstance(x, set):
            return {self.rewrite(v) for v in x}
        if isinstance(x, dict):
            return {self.rewrite(k): self.rewrite(v) for k, v in x.items()}
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            return dataclasses.replace(
                x,
                **{
                    f.name: self.rewrite(getattr(x, f.name))
                    for f in dataclasses.fields(x)
                },
            )
        return x

    def reindex(self, indexed: Sequence[Any]) -> List[Any]:
        """Permute an id-indexed sequence into canonical order, rewriting
        each element along the way. new[mapping[i]] = rewrite(old[i]).

        Reference: rewrite_plan.rs:108-124.
        """
        return [self.rewrite(indexed[old]) for old in self._inverse]

    def __repr__(self) -> str:
        return f"RewritePlan(domain={self.domain.__name__}, mapping={self.mapping})"
