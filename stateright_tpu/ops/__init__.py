"""Device-side kernels for the batched frontier engine.

The TPU-native replacements for the reference's concurrent data structures:
the sharded DashMap visited set (src/checker/bfs.rs:29-30) becomes an
open-addressing hash table in device memory with batched scatter-claim
inserts (`visited_set`), and frontier bookkeeping (dedup, compaction, ring
queue) becomes sort/scan array programs (`frontier`). Everything is uint32
and jit-compatible so XLA can fuse the whole BFS level into a handful of
kernels.

One module here is host-side: `tiering` holds the budgeted RAM + npz
disk store backing the engines' out-of-core frontier spill (the device
side of spill stays in `frontier`).
"""
