"""Shared per-chunk evaluate-and-expand core for the batched BFS engines.

Both the single-device engine (engines/tpu_bfs.py) and the sharded engine
(parallel/mesh.py) are required to be state-for-state equivalent to the
reference checker's hot loop (src/checker/bfs.rs:196-334); they share this
builder so the semantics live in exactly one place. The engines differ only
in what happens *after* expansion: the single-device engine inserts locally,
the sharded engine first exchanges candidates across the mesh.

Everything is structure-of-arrays: states are tuples of dense [C] uint32
lane arrays, and the C*A candidate batch is laid out ACTION-MAJOR
(index = a*C + c) so it is built with cheap concatenations of per-action
lanes — never a [C, A, S] materialization, whose small minor axes would
waste the TPU's 8x128 vector tiles.

Property verdicts are returned as RAW PER-ROW HIT MASKS (`prop_hits`), not
as extracted fingerprints: on the target platform, a loop-carried value
computed through a reduction -> broadcast -> reduction chain (argmax
selects, one-hot extractions, max reduces) knocks the whole loop off the
fast dispatch path (~200ms per iteration, measured). Callers carry the
masks (or mask snapshots) through their loops with pure elementwise ops
and extract fingerprints once per block, outside the loop.
"""

from __future__ import annotations

from typing import NamedTuple

from ..core import Expectation


class Expanded(NamedTuple):
    ebits: object  # [C] uint32, post property evaluation
    flat: object  # tuple of S lane arrays, each [C*A] (action-major)
    h1: object  # [C*A] candidate fingerprints
    h2: object
    parent1: object  # [C*A] parent fingerprints
    parent2: object
    child_ebits: object  # [C*A]
    child_depth: object  # [C*A]
    valid: object  # [C*A] bool: action valid & in boundary & parent live
    generated: object  # scalar uint32: number of valid candidates
    prop_hits: object  # list of P [C] bool masks (see module docstring)


class ExpandedLean(NamedTuple):
    ebits: object  # [C] uint32, post property evaluation
    flat: object  # tuple of S lane arrays, each [C*A] (action-major)
    valid: object  # [C*A] bool: action valid & in boundary & parent live
    generated: object  # scalar uint32: number of valid candidates
    prop_hits: object  # list of P [C] bool masks (see module docstring)


def build_expand_lean(tm, props, chunk: int):
    """The compact-early variant of `build_eval_and_expand` (round 5).

    Returns f(rows, ebits, depth, active, depth_limit) -> ExpandedLean.

    Rationale (measured on this platform, round 5): per-kernel launch
    overhead is negligible, but EVERY random-access op costs ~7-14ns per
    padded slot of its width — so the old contract, which materialized
    fingerprints, parent tiles, and ebits/depth tiles at the padded [C*A]
    width, made the engine pay the full padded width in a dozen wide ops
    per step while only ~20% of slots are valid. This builder returns only
    what is genuinely [C*A]-wide by nature (the successor lanes and the
    validity mask); the engine compacts ONCE and derives hashes, parents,
    and queue rows at the compacted width. Fingerprints of popped rows are
    recomputed elementwise on pop instead of being carried in the ring —
    elementwise work is effectively free here, ring lanes are not.

    Semantics are identical to `build_eval_and_expand` (the reference hot
    loop, bfs.rs:196-334): property evaluation with eventually-bit
    clearing, depth limiting, boundary filtering, the terminal rule, and
    terminal eventually-bit discoveries.
    """
    import jax.numpy as jnp

    S = tm.state_width
    A = tm.max_actions

    def expand_lean(rows, ebits, depth, active, depth_limit):
        u = jnp.uint32
        live = active & (depth < depth_limit)

        prop_hits = []
        e_idx = 0
        e_slot = {}
        for i, p in enumerate(props):
            if p.expectation == Expectation.EVENTUALLY:
                vals = p.check(jnp, rows) & live
                ebits = jnp.where(vals, ebits & ~u(1 << e_idx), ebits)
                e_slot[i] = e_idx
                e_idx += 1
                prop_hits.append(None)
                continue
            if p.expectation == Expectation.ALWAYS:
                prop_hits.append(live & ~p.check(jnp, rows))
            else:  # SOMETIMES
                prop_hits.append(live & p.check(jnp, rows))

        succs, amask = tm.step_lanes(jnp, rows)
        valid_per_a = []
        any_valid = None
        for a in range(A):
            v = amask[a] & live & tm.within_boundary_lanes(jnp, succs[a])
            valid_per_a.append(v)
            any_valid = v if any_valid is None else (any_valid | v)
        valid = jnp.concatenate(valid_per_a)  # [A*C], action-major
        generated = valid.sum(dtype=u)

        terminal = live & ~any_valid
        for i, p in enumerate(props):
            if p.expectation != Expectation.EVENTUALLY:
                continue
            bit = u(1 << e_slot[i])
            prop_hits[i] = terminal & ((ebits & bit) != 0)

        flat = tuple(
            jnp.concatenate([succs[a][s] for a in range(A)]) for s in range(S)
        )
        return ExpandedLean(
            ebits=ebits,
            flat=flat,
            valid=valid,
            generated=generated,
            prop_hits=prop_hits,
        )

    return expand_lean


def build_eval_and_expand(tm, props, chunk: int):
    """Returns f(rows, row_h1, row_h2, ebits, depth, active, depth_limit)
    -> Expanded, where `rows` is a tuple of S [C] lane arrays.

    `row_h1`/`row_h2` are the popped rows' fingerprint halves, computed when
    the rows were first enqueued (the frontier ring carries them), so popped
    states are never re-hashed.

    Implements, batched: property evaluation with eventually-bit clearing
    (bfs.rs:231-277), depth limiting (bfs.rs:219-224), successor generation
    with boundary filtering, the terminal rule (no successor passed the
    boundary, dups included — bfs.rs:283-333), and terminal eventually-bit
    discoveries (bfs.rs:326-333). `prop_hits[i]` marks the rows whose visit
    discovers property i: a violated always / satisfied sometimes condition,
    or a terminal state with property i's eventually-bit still pending.
    """
    import jax.numpy as jnp

    from ..fingerprint import hash_lanes_jnp

    S = tm.state_width
    A = tm.max_actions

    def eval_and_expand(rows, row_h1, row_h2, ebits, depth, active, depth_limit):
        u = jnp.uint32
        # Depth-limited rows are popped but neither evaluated nor expanded.
        live = active & (depth < depth_limit)

        prop_hits = []
        e_idx = 0
        e_slot = {}
        for i, p in enumerate(props):
            if p.expectation == Expectation.EVENTUALLY:
                vals = p.check(jnp, rows) & live
                ebits = jnp.where(vals, ebits & ~u(1 << e_idx), ebits)
                e_slot[i] = e_idx
                e_idx += 1
                prop_hits.append(None)  # filled in after terminal rule
                continue
            if p.expectation == Expectation.ALWAYS:
                prop_hits.append(live & ~p.check(jnp, rows))
            else:  # SOMETIMES
                prop_hits.append(live & p.check(jnp, rows))

        # succs: list over A of S-lane tuples; masks: list over A of [C] bool
        succs, amask = tm.step_lanes(jnp, rows)
        valid_per_a = []
        any_valid = None
        for a in range(A):
            v = amask[a] & live & tm.within_boundary_lanes(jnp, succs[a])
            valid_per_a.append(v)
            any_valid = v if any_valid is None else (any_valid | v)
        valid = jnp.concatenate(valid_per_a)  # [A*C], action-major
        generated = valid.sum(dtype=u)

        terminal = live & ~any_valid
        for i, p in enumerate(props):
            if p.expectation != Expectation.EVENTUALLY:
                continue
            bit = u(1 << e_slot[i])
            prop_hits[i] = terminal & ((ebits & bit) != 0)

        flat = tuple(
            jnp.concatenate([succs[a][s] for a in range(A)]) for s in range(S)
        )
        h1, h2 = hash_lanes_jnp(flat)
        return Expanded(
            ebits=ebits,
            flat=flat,
            h1=h1,
            h2=h2,
            parent1=jnp.tile(row_h1, A),
            parent2=jnp.tile(row_h2, A),
            child_ebits=jnp.tile(ebits, A),
            child_depth=jnp.tile(depth + u(1), A),
            valid=valid,
            generated=generated,
            prop_hits=prop_hits,
        )

    return eval_and_expand
