"""Shared per-chunk evaluate-and-expand core for the batched BFS engines.

Both the single-device engine (engines/tpu_bfs.py) and the sharded engine
(parallel/mesh.py) are required to be state-for-state equivalent to the
reference checker's hot loop (src/checker/bfs.rs:196-334); they share this
builder so the semantics live in exactly one place. The engines differ only
in what happens *after* expansion: the single-device engine inserts locally,
the sharded engine first exchanges candidates across the mesh.
"""

from __future__ import annotations

from typing import NamedTuple

from ..core import Expectation


class Expanded(NamedTuple):
    ebits: object  # [C] uint32, post property evaluation
    flat: object  # [C*A, S] candidate states
    h1: object  # [C*A] candidate fingerprints
    h2: object
    parent1: object  # [C*A] parent fingerprints
    parent2: object
    child_ebits: object  # [C*A]
    child_depth: object  # [C*A]
    valid: object  # [C*A] bool: action valid & in boundary & parent live
    generated: object  # scalar uint32: number of valid candidates
    max_depth_seen: object  # scalar uint32
    prop_found: object  # [P] bool
    prop_fp1: object  # [P] uint32
    prop_fp2: object  # [P] uint32


def build_eval_and_expand(tm, props, chunk: int):
    """Returns f(rows, ebits, depth, active, depth_limit) -> Expanded.

    Implements, batched: property evaluation with eventually-bit clearing
    (bfs.rs:231-277), depth limiting (bfs.rs:219-224), successor generation
    with boundary filtering, the terminal rule (no successor passed the
    boundary, dups included — bfs.rs:283-333), and terminal eventually-bit
    discoveries (bfs.rs:326-333).
    """
    import jax.numpy as jnp

    from ..fingerprint import hash_words_jnp

    S = tm.state_width
    A = tm.max_actions

    def eval_and_expand(rows, ebits, depth, active, depth_limit):
        u = jnp.uint32
        max_depth_seen = jnp.max(jnp.where(active, depth, u(0)))
        # Depth-limited rows are popped but neither evaluated nor expanded.
        live = active & (depth < depth_limit)
        row_h1, row_h2 = hash_words_jnp(rows)

        prop_found = []
        prop_fp1 = []
        prop_fp2 = []
        e_idx = 0
        e_slot = {}
        for i, p in enumerate(props):
            if p.expectation == Expectation.EVENTUALLY:
                vals = p.check(jnp, rows) & live
                ebits = jnp.where(vals, ebits & ~u(1 << e_idx), ebits)
                e_slot[i] = e_idx
                e_idx += 1
                prop_found.append(None)  # filled in after terminal rule
                prop_fp1.append(None)
                prop_fp2.append(None)
                continue
            if p.expectation == Expectation.ALWAYS:
                hits = live & ~p.check(jnp, rows)
            else:  # SOMETIMES
                hits = live & p.check(jnp, rows)
            sel = jnp.argmax(hits)
            prop_found.append(jnp.any(hits))
            prop_fp1.append(row_h1[sel])
            prop_fp2.append(row_h2[sel])

        succs, amask = tm.step_batch(jnp, rows)  # [C, A, S], [C, A]
        amask = amask & live[:, None]
        flat = succs.reshape(chunk * A, S)
        inb = tm.within_boundary_batch(jnp, flat).reshape(chunk, A)
        valid = amask & inb
        generated = valid.sum(dtype=jnp.uint32)

        terminal = live & ~jnp.any(valid, axis=1)
        for i, p in enumerate(props):
            if p.expectation != Expectation.EVENTUALLY:
                continue
            bit = u(1 << e_slot[i])
            fails = terminal & ((ebits & bit) != 0)
            sel = jnp.argmax(fails)
            prop_found[i] = jnp.any(fails)
            prop_fp1[i] = row_h1[sel]
            prop_fp2[i] = row_h2[sel]

        h1, h2 = hash_words_jnp(flat)
        n_props = len(props)
        return Expanded(
            ebits=ebits,
            flat=flat,
            h1=h1,
            h2=h2,
            parent1=jnp.repeat(row_h1, A),
            parent2=jnp.repeat(row_h2, A),
            child_ebits=jnp.repeat(ebits, A),
            child_depth=jnp.repeat(depth + u(1), A),
            valid=valid.reshape(chunk * A),
            generated=generated,
            max_depth_seen=max_depth_seen,
            prop_found=jnp.stack(prop_found) if n_props else jnp.zeros(0, bool),
            prop_fp1=(
                jnp.stack(prop_fp1) if n_props else jnp.zeros(0, jnp.uint32)
            ),
            prop_fp2=(
                jnp.stack(prop_fp2) if n_props else jnp.zeros(0, jnp.uint32)
            ),
        )

    return eval_and_expand
