"""Device-resident visited set: batched open-addressing hash table.

TPU-native replacement for the reference BFS's concurrent visited map
(DashMap<Fingerprint, Option<Fingerprint>> at src/checker/bfs.rs:29-30).
Fingerprints are (h1, h2) uint32 pairs (64-bit effective, nonzero as a
pair). The table is structure-of-arrays: a paired-lane key buffer
`keys[2 * capacity]` (slot i's h1 word at `keys[i]`, its h2 word at
`keys[capacity + i]`) plus two dense [capacity] parent lanes (parent_h1,
parent_h2), with the all-zero key pair meaning "empty" and parent (0, 0)
meaning "no parent" (initial state) — mirroring the reference's
Option<Fingerprint> parent pointers used for path reconstruction
(bfs.rs:380-409). SoA matters: a [capacity, 4] row table makes every
gather/scatter move 4-wide rows that waste the TPU's 8x128 vector tiles
(measured >1000x slower than flat 1-D accesses). The paired-lane key
buffer goes one further: each probe round reads BOTH key words with ONE
gather over the concatenated index vector [idx, capacity + idx] (and
claims them with one scatter), halving the dependent-gather chain that
dominates insert cost. The on-disk checkpoint format keeps the original
four flat lanes (table0..3); the engines split/concat the key buffer at
the save/load boundary, so checkpoint meta geometry is unchanged.

Probing is DOUBLE HASHING: slot_0 = h1 & mask, stride = h2 | 1 (odd, so it
cycles the whole power-of-two table). Unlike linear probing there is no
cluster growth, so probe chains stay geometric in the load factor and a
small fixed probe budget suffices at load <= MAX_LOAD.

Batched insert uses claim-arbitrated probe rounds. Each round every
pending candidate:

  1. reads its slot; a key match means "already visited" (done, not new),
  2. if the slot is empty, scatters its candidate index into a claim
     scratch array at that slot — among same-slot contenders exactly one
     index survives the scatter,
  3. the claim winner (readback == own index) scatters its lanes into the
     table (winner slots are unique, so these scatters take the fast
     unique-indices path), and
  4. losers wait one round: re-reading the slot next round either reveals
     a key match (the winner carried the same fingerprint — an in-batch
     duplicate, resolved exactly like the reference's benign insert races,
     bfs.rs:302-315) or a foreign key (probe advances by the stride).

Duplicate keys *within* a batch therefore need no separate dedup pass:
the claim protocol guarantees exactly one winner per distinct key, and
`is_new` counts each distinct new key exactly once.

PALLAS NOTE (round 4, measured on this platform): a hand-written Pallas
probe kernel was prototyped and is NOT viable here. Pallas itself works
(basic elementwise kernels compile and run), but TPU Pallas rejects
vector dynamic indexing into a ref ("Cannot do int indexing on TPU"), so
the open-addressing probe's random gathers cannot be expressed inside a
kernel — they must go through XLA's native gather, which is exactly what
this module does. The insert's cost is dependent-gather latency
(~65ns/element at rcap widths, chained per probe round), a bound a kernel
could only beat with scatter/gather DMA primitives TPU Pallas does not
expose for this access pattern.

The probe loops are COUNTED fori loops in phases: a short full-width
phase resolves the overwhelming majority, then the rare stragglers are
cumsum-compacted into a cascade of count-gated tail stages at narrowing
widths that probe further. Two constraints force this shape on the target
platform: (a) a top-level `lax.while_loop` with a data-dependent
predicate costs a host round-trip per iteration on remote-attached
devices, and (b) compiled programs whose probe loop exceeds ~10 rounds
fall off the runtime's fast dispatch path entirely (measured: 8 rounds =
10us/step, 12 rounds = 270ms/step) — so the stages that WOULD push past
that budget must stay behind count gates that keep them out of the
common-case step. The candidates that no phase resolves are reported
`unresolved`; callers must grow the table and keep load <= MAX_LOAD so
that outcome stays (measurably) one-in-millions — and fail loudly if it
happens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

PRIMARY_ROUNDS = 3  # primary probe rounds (platform fast-path limit ~10/loop)
# At MAX_LOAD=0.25, P(probe chain > 3) ~ 1.6%: three full-width rounds keep
# the straggler population under the first tail stage's cap even for the
# largest bench batches (2pc-10: rcap ~ 85K distinct candidates at load
# 0.23 leaves ~1K stragglers; TWO rounds left ~4.5K — overflowing the
# 4096-wide tail and force-engaging every later stage on every step, the
# stage-profiled cause of the 2pc-10 per-step cliff).
REHASH_ROUNDS = 8  # deeper primary phase for whole-table rehashes
# Tail stages: (rounds, width) pairs at GEOMETRICALLY NARROWING widths.
# Each stage re-compacts the candidates still unresolved at that point
# into its own [width] batch, so late stages probe at the width of the
# straggler population they actually face (tens of candidates) instead of
# the first stage's worst-case cap. The whole stage — compaction, probe
# rounds, and fold-back — is GATED on its live straggler count
# (lax.cond): a stage with nothing to do costs one scalar reduction
# instead of a cumsum + gathers + probe rounds. Total probe budget per
# insert is PRIMARY (or REHASH) + sum of stage rounds; stages engage
# automatically as the load factor pushes chains longer.
TAIL_STAGES = ((4, 4096), (4, 1024), (8, 256))
# Lookups must probe at least as deep as the deepest possible placement:
# a rehash insert can place a key up to REHASH_ROUNDS + sum(tail) probes
# along its sequence. (Keep this >= the budget of every table written by
# older builds: checkpointed tables are probed with TODAY'S constant.)
MAX_PROBES = REHASH_ROUNDS + sum(r for r, _ in TAIL_STAGES)
# First-stage width: stragglers after the primary phase scale with the
# batch (expected ~ n * load^PRIMARY_ROUNDS near MAX_LOAD), so giant
# batches at high load CAN overflow it — overflow surfaces as
# `unresolved` candidates, which engine callers must treat as RETRYABLE
# (shrink the batch via the partial-commit take_cap protocol and redo;
# inserts are idempotent), not as instant failure.
# Probe chains stay within these budgets when the load factor stays under
# MAX_LOAD (double hashing => geometric chains: P(len>3) ~ MAX_LOAD^3 per
# candidate, and the tail phase absorbs the stragglers).
MAX_LOAD = 0.25


def empty_table(capacity: int):
    """Packed zero table: (keys[2*capacity], parent_h1[capacity],
    parent_h2[capacity]); capacity must be a power of two."""
    if capacity & (capacity - 1):
        raise ValueError("visited-set capacity must be a power of two")
    # Distinct buffers (not one aliased zeros array): the lanes are
    # donated independently by the jitted insert/loop programs.
    return (
        jnp.zeros(2 * capacity, dtype=jnp.uint32),
        jnp.zeros(capacity, dtype=jnp.uint32),
        jnp.zeros(capacity, dtype=jnp.uint32),
    )


def abstract_table(capacity: int):
    """`jax.ShapeDtypeStruct` twin of `empty_table` — the shapes without
    the buffers, for tracing/lowering insert/rehash programs statically
    (analysis/program.py STR6xx)."""
    import jax

    if capacity & (capacity - 1):
        raise ValueError("visited-set capacity must be a power of two")
    sds = jax.ShapeDtypeStruct
    return (
        sds((2 * capacity,), jnp.uint32),
        sds((capacity,), jnp.uint32),
        sds((capacity,), jnp.uint32),
    )


def table_capacity(table) -> int:
    return table[1].shape[0]


def pack_lanes(k1, k2, v1, v2):
    """Build the packed device table from four flat key/parent lanes (the
    checkpoint / host-seeding representation)."""
    return (
        jnp.concatenate([jnp.asarray(k1), jnp.asarray(k2)]),
        jnp.asarray(v1),
        jnp.asarray(v2),
    )


def unpack_lanes_np(table):
    """Download a packed device table into the four flat numpy lanes used
    by checkpoints and `lookup_parent_np` (key halves are free views)."""
    import numpy as np

    keys = np.asarray(table[0])
    cap = keys.shape[0] // 2
    return keys[:cap], keys[cap:], np.asarray(table[1]), np.asarray(table[2])


def _probe_rounds(table, claim, h1, h2, p1, p2, idx, done, is_new, rounds):
    """One counted phase of the claim protocol over one candidate set.

    The probe stride is DERIVED here (`h2 | 1`) rather than passed in:
    every probe sequence in this module uses the same double-hashing
    stride, so deriving it from the gathered h2 words keeps the tail-stage
    cascade free of a per-stage stride gather (loop-invariant hoisting).
    """
    keys, v1, v2 = table
    capacity = v1.shape[0]
    u = jnp.uint32
    mask = u(capacity - 1)
    claim_cap = claim.shape[0]
    cmask = u(claim_cap - 1)
    n = h1.shape[0]
    my_id = jnp.arange(n, dtype=u)
    stride = h2 | u(1)
    # The claim scratch and the table have DIFFERENT sizes, so each needs
    # its own out-of-bounds drop-target range (an index that is OOB for
    # the claim would land INSIDE the larger table and corrupt it). For
    # the packed [2*capacity] key buffer the drop targets start at
    # 2*capacity — `capacity + my_id` would land inside the h2 half — and
    # the two key-scatter halves get DISJOINT ranges ([2c, 2c+n) and
    # [2c+n, 2c+2n)) so the concatenated scatter keeps unique indices.
    claim_oob = u(claim_cap) + my_id
    table_oob = u(2 * capacity) + my_id
    table_oob2 = u(2 * capacity) + u(n) + my_id
    hcap = u(capacity)

    def body(_r, carry):
        keys, v1, v2, claim, idx, done, is_new = carry
        # ONE gather reads both key words: h1 at idx, h2 at capacity+idx.
        rk = keys[jnp.concatenate([idx, hcap + idx])]
        rk1 = rk[:n]
        rk2 = rk[n:]
        slot_match = (rk1 == h1) & (rk2 == h2)
        done = done | slot_match  # already visited (or in-batch dup winner)
        slot_empty = (rk1 == 0) & (rk2 == 0)
        want = ~done & slot_empty
        # Same-slot contenders intentionally collide here — the surviving
        # write is the arbitration (no unique-indices promise). The claim
        # scratch is a HASHED domain much smaller than the table (see
        # `_claim_cap`): contenders for DIFFERENT table slots may collide
        # on one claim slot, in which case all but one harmlessly lose and
        # retry the same still-empty table slot next round — soundness
        # never depends on the claim being collision-free, only on "claim
        # readback == my id" being unforgeable within a round, which a
        # per-candidate unique id guarantees.
        ci = idx & cmask
        claim = claim.at[jnp.where(want, ci, claim_oob)].set(my_id, mode="drop")
        won = want & (claim[ci] == my_id)
        # Winner slots are unique; losers/dones get distinct out-of-bounds
        # targets so the unique-indices fast path stays valid. Both key
        # words land with ONE scatter over the concatenated targets.
        tgt = jnp.where(won, idx, table_oob)
        tgt2 = jnp.where(won, hcap + idx, table_oob2)
        keys = keys.at[jnp.concatenate([tgt, tgt2])].set(
            jnp.concatenate([h1, h2]), mode="drop", unique_indices=True
        )
        v1 = v1.at[tgt].set(p1, mode="drop", unique_indices=True)
        v2 = v2.at[tgt].set(p2, mode="drop", unique_indices=True)
        is_new = is_new | won
        done = done | won
        # Occupied-by-foreign-key probes advance by their stride; claim
        # losers re-examine the same (now occupied) slot next round to
        # learn dup-vs-foreign. Resolved candidates PIN their index to slot
        # 0: their (masked) gathers in later rounds then all hit one cache
        # line instead of scattering across HBM — the probe loop's cost
        # tracks the *unresolved* population, and fully-masked no-op steps
        # become nearly free.
        advance = ~done & ~slot_empty
        idx = jnp.where(advance, (idx + stride) & mask, idx)
        idx = jnp.where(done, u(0), idx)
        return keys, v1, v2, claim, idx, done, is_new

    out = lax.fori_loop(
        0, rounds, body, (keys, v1, v2, claim, idx, done, is_new)
    )
    return (out[0], out[1], out[2]), out[3], out[4], out[5], out[6]


def _compact_ids(mask, cap: int):
    """Pack the indices of set bits in `mask` into a [cap] id buffer.

    Returns (ids[cap], valid[cap], n_set). Entries past min(n_set, cap) are
    invalid; set bits ranked >= cap overflow (not represented).
    """
    u = jnp.uint32
    n = mask.shape[0]
    my_id = jnp.arange(n, dtype=u)
    rank = jnp.cumsum(mask.astype(u)) - 1
    # Overflowed set bits (rank >= cap) must ALSO take distinct out-of-bounds
    # positions — a bare rank could collide with an unset entry's cap+my_id,
    # violating the unique-indices promise below.
    pos = jnp.where(mask & (rank < u(cap)), rank, u(cap) + my_id)
    ids = (
        jnp.zeros(cap, dtype=u)
        .at[pos]
        .set(my_id, mode="drop", unique_indices=True)
    )
    n_set = mask.sum(dtype=u)
    valid = jnp.arange(cap, dtype=u) < jnp.minimum(n_set, u(cap))
    return ids, valid, n_set


def _probe_all(table, claim, h1, h2, p1, p2, idx, done, is_new, rounds):
    """Primary probe rounds, then a cascade of gated straggler stages at
    narrowing widths. Returns (table, claim, done, is_new)."""
    u = jnp.uint32
    n = h1.shape[0]

    table, claim, idx, done, is_new = _probe_rounds(
        table, claim, h1, h2, p1, p2, idx, done, is_new, rounds
    )

    for stage_rounds, stage_cap in TAIL_STAGES:
        # Gate each stage — INCLUDING its compaction — on the live
        # straggler count: with nothing left the stage reduces to one
        # scalar sum + a branch instead of a full-width cumsum, gathers,
        # and stage_rounds probe rounds. Candidates that overflow a
        # stage's width stay un-done and fall through to the next stage
        # (or out, reported unresolved by the caller).
        pending = (~done).sum(dtype=u)

        def run_stage(op, stage_rounds=stage_rounds, stage_cap=stage_cap):
            table, claim, idx, done, is_new = op
            tail_ids, t_valid, _n_un = _compact_ids(~done, stage_cap)
            th1 = h1[tail_ids]
            th2 = h2[tail_ids]
            tp1 = p1[tail_ids]
            tp2 = p2[tail_ids]
            # No per-stage stride gather: _probe_rounds re-derives the
            # stride from the gathered th2 words (loop-invariant hoist).
            t_idx = jnp.where(t_valid, idx[tail_ids], u(0))
            t_done = ~t_valid
            # All-false but derived from varying data so the loop carry
            # type stays consistent under shard_map (constant zeros would
            # be unvarying).
            t_new = t_valid & ~t_valid
            table, claim, t_idx, t_done, t_new = _probe_rounds(
                table, claim, th1, th2, tp1, tp2, t_idx, t_done,
                t_new, stage_rounds,
            )
            # Fold the stage's results back into the full-width masks; the
            # probe POSITION folds back too, so the next stage's batch
            # resumes each survivor's chain where this one left it.
            t_my = jnp.arange(stage_cap, dtype=u)
            upd = jnp.where(t_valid, tail_ids, u(n) + t_my)
            is_new = is_new.at[upd].max(
                t_new, mode="drop", unique_indices=True
            )
            done = done.at[upd].max(t_done, mode="drop", unique_indices=True)
            idx = idx.at[upd].set(t_idx, mode="drop", unique_indices=True)
            return table, claim, idx, done, is_new

        def skip_stage(op):
            return op

        table, claim, idx, done, is_new = lax.cond(
            pending > u(0), run_stage, skip_stage,
            (table, claim, idx, done, is_new),
        )

    return table, claim, done, is_new


def insert(table, h1, h2, p1, p2, active, rcap: int | None = None,
           primary_rounds: int = PRIMARY_ROUNDS):
    """Insert fingerprints (h1,h2) with parents (p1,p2) where `active`.

    Returns (table, is_new, unresolved, n_overflow):
      is_new[i]     — candidate i claimed a fresh slot (first visit). Among
                      in-batch duplicates exactly one wins.
      unresolved[i] — probe budget exhausted (table too full or tail
                      overflow); callers must grow + retry, otherwise
                      states would be silently lost.
      n_overflow    — active candidates beyond `rcap` that were NOT probed
                      at all this call (0 when rcap is None). Overflowed
                      candidates are neither inserted nor marked is_new;
                      callers must re-submit them (inserts are idempotent,
                      so re-running a partially-inserted batch is safe).

    Duplicate keys among active candidates are allowed (though on this
    platform every probed candidate costs width-proportional gather time,
    so pre-deduplicated, `rcap`-compacted batches are much faster: probe
    traffic then scales with the number of distinct candidates instead of
    the padded batch width).
    """
    capacity = table_capacity(table)
    u = jnp.uint32
    mask = u(capacity - 1)
    n = h1.shape[0]
    # Claim scratch: stale values are harmless — a winner check only reads
    # slots that were written earlier in the same round. Seeded from a
    # varying input (h1) so the carry type stays consistent under shard_map
    # (a constant-zeros init would be unvarying on the mesh axis).
    # Capped at 2^22 entries: table-width up to there (a tightly hashed
    # claim measured SLOWER in situ at these sizes), hashed beyond —
    # giant tables (2pc-10 needs 2^28 slots) must not pay a 1GB memset
    # plus table-width claim traffic per insert call. Aliased claim slots
    # only cost a harmless retry round (see _probe_rounds).
    claim_cap = min(capacity, 1 << 22)
    claim = jnp.zeros(claim_cap, dtype=u) + (h1[0] & u(0))

    if rcap is None:
        # Inactive candidates start pinned at slot 0 (coalesced masked
        # gathers); see the pinning note in _probe_rounds.
        idx = jnp.where(active, h1 & mask, u(0))
        table, _claim, done, is_new = _probe_all(
            table, claim, h1, h2, p1, p2, idx, ~active,
            jnp.zeros_like(active), primary_rounds,
        )
        return table, is_new, active & ~done, u(0)

    # Compacted path: probe only the active candidates, at [rcap] width.
    cids, cvalid, n_act = _compact_ids(active, rcap)
    ch1 = h1[cids]
    ch2 = h2[cids]
    cp1 = p1[cids]
    cp2 = p2[cids]
    c_idx = jnp.where(cvalid, ch1 & mask, u(0))
    table, _claim, c_done, c_new = _probe_all(
        table, claim, ch1, ch2, cp1, cp2, c_idx, ~cvalid,
        cvalid & ~cvalid, primary_rounds,
    )
    # Scatter results back to the full-width domain.
    c_my = jnp.arange(rcap, dtype=u)
    upd = jnp.where(cvalid, cids, u(n) + c_my)
    is_new = jnp.zeros_like(active).at[upd].max(
        c_new, mode="drop", unique_indices=True
    )
    resolved = jnp.zeros_like(active).at[upd].max(
        c_done & cvalid, mode="drop", unique_indices=True
    )
    probed = jnp.zeros_like(active).at[upd].max(
        cvalid, mode="drop", unique_indices=True
    )
    unresolved = active & probed & ~resolved
    n_overflow = n_act - jnp.minimum(n_act, u(rcap))
    return table, is_new, unresolved, n_overflow


def lookup_parent(table, h1, h2):
    """Probe for fingerprints; returns (found, parent_h1, parent_h2).

    Same double-hashing sequence as `insert`. NOTE: exceeds the platform's
    fast-dispatch round limit, so each call may take ~100ms — use only for
    rare host-side queries (prefer `lookup_parent_np` on a downloaded
    table for chain walks).
    """
    keys, v1, v2 = table
    capacity = v1.shape[0]
    u = jnp.uint32
    mask = u(capacity - 1)
    hcap = u(capacity)
    stride = h2 | u(1)
    idx = h1 & mask
    done = jnp.zeros(h1.shape, dtype=bool)
    found = jnp.zeros(h1.shape, dtype=bool)
    par1 = jnp.zeros_like(h1)
    par2 = jnp.zeros_like(h2)

    def body(_r, carry):
        idx, done, found, par1, par2 = carry
        rk1 = keys[idx]
        rk2 = keys[hcap + idx]
        slot_empty = (rk1 == 0) & (rk2 == 0)
        slot_match = (rk1 == h1) & (rk2 == h2)
        hit = ~done & slot_match
        par1 = jnp.where(hit, v1[idx], par1)
        par2 = jnp.where(hit, v2[idx], par2)
        found = found | hit
        done = done | slot_match | slot_empty  # empty slot ends the chain
        idx = jnp.where(done, idx, (idx + stride) & mask)
        return idx, done, found, par1, par2

    _idx, _done, found, par1, par2 = lax.fori_loop(
        0, MAX_PROBES, body, (idx, done, found, par1, par2)
    )
    return found, par1, par2


def occupied_mask(table):
    """Mask of nonempty slots — used when rehashing into a larger table."""
    cap = table_capacity(table)
    return (table[0][:cap] != 0) | (table[0][cap:] != 0)


def rehash(old_table, new_table):
    """Re-insert every occupied row of `old_table` into `new_table`.

    Runs entirely on device (table growth must not round-trip the table
    through the host). Returns (new_table, n_unresolved).
    """
    occ = occupied_mask(old_table)
    cap = table_capacity(old_table)
    k1 = old_table[0][:cap]
    k2 = old_table[0][cap:]
    v1, v2 = old_table[1], old_table[2]
    # A rehash inserts millions of rows at once; use a deeper primary phase
    # so the fixed-size tail only sees genuine stragglers.
    new_table, _is_new, unresolved, _ovf = insert(
        new_table, k1, k2, v1, v2, occ, primary_rounds=REHASH_ROUNDS
    )
    return new_table, unresolved.sum(dtype=jnp.uint32)


# Host-callable jitted twins. CRITICAL: never call `insert`/`lookup_parent`/
# `rehash` eagerly — an eagerly-traced lax loop closes over its operands as
# embedded array constants, which this platform dispatches on a ~100ms
# degraded path (and the degradation sticks for the whole process). Under jit
# the operands are tracers and the programs stay on the fast path.
# Donation is gated off on CPU: persistent-cache-deserialized executables
# corrupt donated buffers there (stateright_tpu.compat docstring).
from ..compat import donate_argnums_safe as _donate

insert_jit = jax.jit(insert, donate_argnums=_donate(0))
lookup_parent_jit = jax.jit(lookup_parent)
rehash_jit = jax.jit(rehash, donate_argnums=_donate(1))


def lookup_parent_np(table_np, h1: int, h2: int):
    """Pure-numpy probe over a host copy of the table lanes.

    Path reconstruction walks one parent per step; doing that on-device
    would cost a host round-trip per node, so the table is downloaded once
    and chains are walked here. Same double-hashing sequence as `insert`.
    Returns (found, parent_h1, parent_h2).
    """
    k1, k2, v1, v2 = table_np
    cap = len(k1)
    mask = cap - 1
    stride = (h2 | 1) & 0xFFFFFFFF
    idx = h1 & mask
    for _ in range(MAX_PROBES):
        if k1[idx] == h1 and k2[idx] == h2:
            return True, int(v1[idx]), int(v2[idx])
        if k1[idx] == 0 and k2[idx] == 0:
            return False, 0, 0
        idx = (idx + stride) & mask
    return False, 0, 0
