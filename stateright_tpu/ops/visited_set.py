"""Device-resident visited set: batched open-addressing hash table.

TPU-native replacement for the reference BFS's concurrent visited map
(DashMap<Fingerprint, Option<Fingerprint>> at src/checker/bfs.rs:29-30).
Fingerprints are (h1, h2) uint32 pairs (64-bit effective, nonzero as a
pair); the table is a [capacity, 4] uint32 array holding
(key_h1, key_h2, parent_h1, parent_h2) per slot, with the all-zero key
meaning "empty" and parent (0, 0) meaning "no parent" (initial state) —
mirroring the reference's Option<Fingerprint> parent pointers used for
path reconstruction (bfs.rs:380-409).

Batched insert uses scatter-claim rounds of linear probing:
each probe round every pending candidate (1) reads its slot, (2) resolves
hits, (3) scatters its full row into empty slots (XLA scatter applies each
update row atomically — duplicate indices resolve to one complete row),
(4) reads back to learn if it won the claim, and losers advance to the next
slot. Candidates must be pre-deduplicated within the batch (see
`frontier.dedup_sorted`) so two pending candidates never carry the same key.

All shapes are static; capacity is a power of two; the probe loop is a
`lax.fori_loop` so the whole insert compiles to one fused kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

MAX_PROBES = 64  # generous for load factor <= 0.5 (expected probes ~2)


def empty_table(capacity: int) -> jax.Array:
    """[capacity, 4] uint32 zeros; capacity must be a power of two."""
    if capacity & (capacity - 1):
        raise ValueError("visited-set capacity must be a power of two")
    return jnp.zeros((capacity, 4), dtype=jnp.uint32)


def insert(table, h1, h2, p1, p2, active):
    """Insert fingerprints (h1,h2) with parents (p1,p2) where `active`.

    Returns (table, is_new, unresolved):
      is_new[i]     — candidate i claimed a fresh slot (first visit).
      unresolved[i] — probe budget exhausted (table too full); callers must
                      grow + retry, otherwise states would be silently lost.

    Candidates must have distinct keys among active entries.
    """
    capacity = table.shape[0]
    mask = jnp.uint32(capacity - 1)
    idx = h1 & mask
    done = ~active
    is_new = jnp.zeros_like(active)

    def body(_r, carry):
        table, idx, done, is_new = carry
        row = table[idx]  # [N, 4] gather
        slot_empty = (row[:, 0] == 0) & (row[:, 1] == 0)
        slot_match = (row[:, 0] == h1) & (row[:, 1] == h2)
        done = done | slot_match  # already visited
        want = ~done & slot_empty
        # Claim: scatter full rows into empty slots; inactive rows aim
        # out-of-bounds and are dropped.
        scatter_idx = jnp.where(want, idx, capacity)
        updates = jnp.stack([h1, h2, p1, p2], axis=-1)
        table = table.at[scatter_idx].set(updates, mode="drop")
        row2 = table[idx]
        won = want & (row2[:, 0] == h1) & (row2[:, 1] == h2)
        is_new = is_new | won
        done = done | won
        idx = jnp.where(done, idx, (idx + 1) & mask)
        return table, idx, done, is_new

    table, idx, done, is_new = lax.fori_loop(
        0, MAX_PROBES, body, (table, idx, done, is_new)
    )
    unresolved = active & ~done
    return table, is_new, unresolved


def lookup_parent(table, h1, h2):
    """Probe for fingerprints; returns (found, parent_h1, parent_h2).

    Used by host-side path reconstruction to walk parent chains.
    """
    capacity = table.shape[0]
    mask = jnp.uint32(capacity - 1)
    idx = h1 & mask
    done = jnp.zeros(h1.shape, dtype=bool)
    found = jnp.zeros(h1.shape, dtype=bool)
    par1 = jnp.zeros_like(h1)
    par2 = jnp.zeros_like(h2)

    def body(_r, carry):
        idx, done, found, par1, par2 = carry
        row = table[idx]
        slot_empty = (row[:, 0] == 0) & (row[:, 1] == 0)
        slot_match = (row[:, 0] == h1) & (row[:, 1] == h2)
        hit = ~done & slot_match
        par1 = jnp.where(hit, row[:, 2], par1)
        par2 = jnp.where(hit, row[:, 3], par2)
        found = found | hit
        done = done | slot_match | slot_empty  # empty slot ends the chain
        idx = jnp.where(done, idx, (idx + 1) & mask)
        return idx, done, found, par1, par2

    _idx, _done, found, par1, par2 = lax.fori_loop(
        0, MAX_PROBES, body, (idx, done, found, par1, par2)
    )
    return found, par1, par2


def occupied_rows(table):
    """Mask of nonempty slots — used when rehashing into a larger table."""
    return (table[:, 0] != 0) | (table[:, 1] != 0)
