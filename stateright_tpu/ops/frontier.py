"""Frontier array programs: masked ring-queue append/pop, in-batch dedup.

These are the TPU-shaped replacements for the reference's per-thread
VecDeque pending queues and entry-API dedup (src/checker/bfs.rs:177-335):
ragged per-state successor sets become fixed-shape candidate batches that
are filtered by a claim-arbitrated visited-set insert and appended to a
power-of-two ring buffer that lives in device memory.

The ring is structure-of-arrays: a tuple of dense [qcap] uint32 lane
arrays. Gathers and scatters touch each lane as a flat 1-D vector — the
layout TPU tiling is fast at — and the ring index math is computed once
and shared across lanes.
"""

from __future__ import annotations

import jax.numpy as jnp


def claim_dedup(h1, h2, valid, scratch_cap: int):
    """Cheap claim-arbitrated in-batch dedup (hot-loop replacement for
    `dedup_mask`): each valid candidate scatters its index into a scratch
    slot derived from its key; the surviving write wins the slot, and a
    loser whose winner carries the SAME key is an in-batch duplicate.

    APPROXIMATE by design: two distinct keys colliding on one scratch slot
    both survive (the loser sees a foreign key) — retained duplicates are
    then arbitrated exactly by the visited-set insert's claim protocol, so
    correctness never depends on this mask being minimal. What it buys is
    four linear-width memory ops instead of `dedup_mask`'s full lexsort
    (O(width log^2 width) bitonic stages, ~15ms at 2pc-7 widths, measured) —
    the sort was the single largest fixed cost in the BFS hot loop.
    """
    u = jnp.uint32
    n = h1.shape[0]
    mask = u(scratch_cap - 1)
    # Mix both halves so keys differing only in h2 spread across slots.
    slot = (h1 ^ (h2 * u(0x9E3779B9))) & mask
    my_id = jnp.arange(n, dtype=u)
    oob = u(scratch_cap) + my_id  # distinct drop targets for invalid rows
    # Seeded from varying input so the value stays mesh-varying under
    # shard_map (see ops/visited_set.py for the same pattern).
    claim = jnp.zeros(scratch_cap, dtype=u) + (h1[0] & u(0))
    claim = claim.at[jnp.where(valid, slot, oob)].set(my_id, mode="drop")
    win = claim[slot]  # for any valid row, its slot was written
    same_key = (h1[win] == h1) & (h2[win] == h2)
    return valid & ((win == my_id) | ~same_key)


def ring_indices(head, n, cap):
    """[n] ring positions starting at `head` in a power-of-two ring."""
    return (head + jnp.arange(n, dtype=jnp.uint32)) & jnp.uint32(cap - 1)


def ring_gather(lanes, head, n):
    """Pop-view `n` consecutive ring rows: returns (lane tuples, indices)."""
    cap = lanes[0].shape[0]
    idx = ring_indices(head, n, cap)
    return tuple(l[idx] for l in lanes), idx


def ring_scatter(lanes, tail, cand_lanes, valid):
    """Append candidate rows where `valid`, packed at tail..tail+count.

    Valid rows land at consecutive ring positions in candidate order
    (cumsum compaction); invalid rows scatter out of bounds and drop. The
    target positions are unique, which keeps the scatters on the fast
    TPU path.
    """
    cap = lanes[0].shape[0]
    n = valid.shape[0]
    offsets = jnp.cumsum(valid.astype(jnp.uint32)) - 1
    idx = (tail + offsets) & jnp.uint32(cap - 1)
    # Dropped rows get DISTINCT out-of-bounds indices so the unique_indices
    # promise holds even for the discarded entries.
    oob = jnp.uint32(cap) + jnp.arange(n, dtype=jnp.uint32)
    idx = jnp.where(valid, idx, oob)
    return tuple(
        l.at[idx].set(c, mode="drop", unique_indices=True)
        for l, c in zip(lanes, cand_lanes)
    )
