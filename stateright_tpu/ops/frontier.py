"""Frontier array programs: in-batch dedup, masked compaction, ring queue.

These are the TPU-shaped replacements for the reference's per-thread
VecDeque pending queues and entry-API dedup (src/checker/bfs.rs:177-335):
ragged per-state successor sets become fixed-shape candidate batches that
are deduplicated by sort, filtered by a visited-set insert, compacted by
stable argsort, and appended to a power-of-two ring buffer that lives in
device memory.
"""

from __future__ import annotations

import jax.numpy as jnp


def dedup_mask(h1, h2, valid):
    """First-occurrence mask over (h1, h2) keys, restricted to `valid`.

    Sort-based: a lexsort with validity as the primary key pushes invalid
    rows to the end; equal valid neighbors are duplicates. Which duplicate
    survives is arbitrary-but-deterministic, matching the reference's
    benign insert races (bfs.rs:243-244, 302-315).
    """
    invalid = (~valid).astype(jnp.uint8)
    perm = jnp.lexsort((h2, h1, invalid))  # last key is primary
    sv = valid[perm]
    s1 = h1[perm]
    s2 = h2[perm]
    dup = (s1[1:] == s1[:-1]) & (s2[1:] == s2[:-1]) & sv[1:] & sv[:-1]
    first = jnp.ones(h1.shape[0], dtype=bool).at[1:].set(~dup)
    return jnp.zeros(h1.shape[0], dtype=bool).at[perm].set(first & sv)


def compact_indices(keep):
    """Stable indices of kept rows, packed to the front; count of kept.

    Returns (indices[N], count) where indices[:count] are the positions of
    kept rows in order and the tail repeats the last kept index (callers
    mask by count).
    """
    order = jnp.argsort(~keep, stable=True)
    count = keep.sum(dtype=jnp.uint32)
    return order, count


def ring_gather(queue, head, n):
    """Gather `n` rows starting at `head` from a power-of-two ring buffer."""
    cap = queue.shape[0]
    idx = (head + jnp.arange(n, dtype=jnp.uint32)) & jnp.uint32(cap - 1)
    return queue[idx], idx


def ring_scatter(queue, tail, rows, valid):
    """Append rows where `valid` at positions tail..tail+count in ring order.

    `rows` must already be compacted (valid rows first); returns the updated
    queue. Invalid rows scatter out of bounds and are dropped.
    """
    cap = queue.shape[0]
    offsets = jnp.cumsum(valid.astype(jnp.uint32)) - 1
    idx = (tail + offsets) & jnp.uint32(cap - 1)
    idx = jnp.where(valid, idx, cap)
    return queue.at[idx].set(rows, mode="drop")
