"""Frontier array programs: masked ring-queue append/pop, in-batch dedup.

These are the TPU-shaped replacements for the reference's per-thread
VecDeque pending queues and entry-API dedup (src/checker/bfs.rs:177-335):
ragged per-state successor sets become fixed-shape candidate batches that
are filtered by a claim-arbitrated visited-set insert and appended to a
power-of-two ring buffer that lives in device memory.

The ring is structure-of-arrays: a tuple of dense [qcap] uint32 lane
arrays. Gathers and scatters touch each lane as a flat 1-D vector — the
layout TPU tiling is fast at — and the ring index math is computed once
and shared across lanes.
"""

from __future__ import annotations

import jax.numpy as jnp


def dedup_mask(h1, h2, valid):
    """First-occurrence mask over (h1, h2) keys, restricted to `valid`.

    Sort-based: a lexsort with validity as the primary key pushes invalid
    rows to the end; equal valid neighbors are duplicates. Which duplicate
    survives is arbitrary-but-deterministic, matching the reference's
    benign insert races (bfs.rs:243-244, 302-315).

    Note: the visited-set insert no longer requires pre-deduplication (its
    claim protocol arbitrates in-batch duplicates); this remains for hosts
    of sorted-exchange schemes and tests.
    """
    invalid = (~valid).astype(jnp.uint8)
    perm = jnp.lexsort((h2, h1, invalid))  # last key is primary
    sv = valid[perm]
    s1 = h1[perm]
    s2 = h2[perm]
    dup = (s1[1:] == s1[:-1]) & (s2[1:] == s2[:-1]) & sv[1:] & sv[:-1]
    first = jnp.ones(h1.shape[0], dtype=bool).at[1:].set(~dup)
    return jnp.zeros(h1.shape[0], dtype=bool).at[perm].set(first & sv)


def ring_indices(head, n, cap):
    """[n] ring positions starting at `head` in a power-of-two ring."""
    return (head + jnp.arange(n, dtype=jnp.uint32)) & jnp.uint32(cap - 1)


def ring_gather(lanes, head, n):
    """Pop-view `n` consecutive ring rows: returns (lane tuples, indices)."""
    cap = lanes[0].shape[0]
    idx = ring_indices(head, n, cap)
    return tuple(l[idx] for l in lanes), idx


def ring_scatter(lanes, tail, cand_lanes, valid):
    """Append candidate rows where `valid`, packed at tail..tail+count.

    Valid rows land at consecutive ring positions in candidate order
    (cumsum compaction); invalid rows scatter out of bounds and drop. The
    target positions are unique, which keeps the scatters on the fast
    TPU path.
    """
    cap = lanes[0].shape[0]
    n = valid.shape[0]
    offsets = jnp.cumsum(valid.astype(jnp.uint32)) - 1
    idx = (tail + offsets) & jnp.uint32(cap - 1)
    # Dropped rows get DISTINCT out-of-bounds indices so the unique_indices
    # promise holds even for the discarded entries.
    oob = jnp.uint32(cap) + jnp.arange(n, dtype=jnp.uint32)
    idx = jnp.where(valid, idx, oob)
    return tuple(
        l.at[idx].set(c, mode="drop", unique_indices=True)
        for l, c in zip(lanes, cand_lanes)
    )
