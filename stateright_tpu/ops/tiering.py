"""Tiered frontier spill: a host-RAM budget with an npz disk tier below.

The device engines stage overflowing frontier rows on the host as a LIFO
stack of refill-sized uint32 blocks (``self._spill``). On billion-state
runs that stack itself outgrows host RAM, so this module bounds it: RAM
holds the newest blocks up to ``host_budget_bytes``; older blocks demote
to npz segment files on disk and promote back (newest segment first)
when the refill path drains the RAM tier. LIFO order is preserved across
tiers — the engines' spill/refill semantics (and therefore exploration
output) are bit-identical to the unbounded in-RAM stack.

Budget source: the ``STPU_SPILL_HOST_BUDGET_BYTES`` environment variable
(unset = unbounded, pure-RAM — the pre-tiering behavior). Tier moves are
reported through an ``on_tier`` callback so each engine can keep its
counters (``spill_tier_rows`` / ``spill_tier_refill_rows``) and the
memory ledger's ``spill_disk`` component / ``spill_tier`` events exact.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Callable, Iterable, Iterator, List, Optional

import numpy as np

__all__ = ["TieredSpillStore", "spill_host_budget_bytes"]


def spill_host_budget_bytes() -> Optional[int]:
    """Host-RAM budget for spill staging, from the environment.

    ``STPU_SPILL_HOST_BUDGET_BYTES`` unset/empty/non-positive means
    unbounded (no disk tier engaged) — mirrors the shape of
    ``obs.memory.device_memory_bytes``.
    """
    raw = os.environ.get("STPU_SPILL_HOST_BUDGET_BYTES", "").strip()
    if not raw:
        return None
    try:
        val = int(raw)
    except ValueError:
        return None
    return val if val > 0 else None


class TieredSpillStore:
    """LIFO stack of spill blocks: budgeted RAM on top, disk below.

    Stack order (oldest -> newest) is ``segments[0] .. segments[-1]``
    then ``ram[0] .. ram[-1]``: demotion moves the OLDEST RAM blocks into
    a new segment file (appended after every existing segment), so the
    relative order of all live blocks never changes. ``pop()`` always
    returns the newest block; an empty RAM tier promotes the newest
    segment wholesale first (one file read amortized over its blocks).

    The store is engine-thread-only (like the list it replaces); the
    ``on_tier(direction, rows, nbytes, disk_bytes)`` callback fires on
    every tier move with direction ``"ram_to_disk"`` or ``"disk_to_ram"``.
    """

    def __init__(
        self,
        *,
        host_budget_bytes: Optional[int] = None,
        spool_dir: Optional[str] = None,
        on_tier: Optional[Callable[[str, int, int, int], None]] = None,
        label: str = "spill",
    ):
        self._budget = (
            int(host_budget_bytes) if host_budget_bytes else None
        )
        self._ram: List[np.ndarray] = []
        # Each segment: {"path": str, "rows": [per-block row counts,
        # oldest first], "nbytes": total payload bytes}.
        self._segments: List[dict] = []
        self._spool = spool_dir
        self._own_spool = spool_dir is None
        self._label = str(label)
        self._on_tier = on_tier
        self._seq = 0

    # -- sizing accessors ---------------------------------------------------

    def __bool__(self) -> bool:
        return bool(self._ram) or bool(self._segments)

    def __len__(self) -> int:
        """Number of live blocks across both tiers."""
        return len(self._ram) + sum(len(s["rows"]) for s in self._segments)

    def rows(self) -> int:
        return sum(len(b) for b in self._ram) + sum(
            sum(s["rows"]) for s in self._segments
        )

    def host_bytes(self) -> int:
        return sum(b.nbytes for b in self._ram)

    def disk_bytes(self) -> int:
        return sum(s["nbytes"] for s in self._segments)

    def total_nbytes(self) -> int:
        return self.host_bytes() + self.disk_bytes()

    def segments(self) -> int:
        return len(self._segments)

    def peek_rows(self) -> int:
        """Row count of the newest block (the next ``pop()``) without
        promoting it — the refill loop's fit check must stay free."""
        if self._ram:
            return len(self._ram[-1])
        if self._segments:
            return int(self._segments[-1]["rows"][-1])
        raise IndexError("peek on empty spill store")

    # -- the stack API the engines drive ------------------------------------

    def append(self, block: np.ndarray) -> None:
        self._ram.append(block)
        self._maybe_demote()

    def pop(self) -> np.ndarray:
        if not self._ram:
            self._promote_newest_segment()
        return self._ram.pop()

    def iter_blocks(self) -> Iterator[np.ndarray]:
        """Every live block, oldest first (the engines' checkpoint
        serialization order). Disk segments are read transiently; the
        store itself is unchanged."""
        for seg in self._segments:
            for blk in self._load_segment(seg):
                yield blk
        for blk in self._ram:
            yield blk

    def reset(self, blocks: Iterable[np.ndarray]) -> None:
        """Replace the whole stack (checkpoint resume), re-applying the
        budget to the restored blocks oldest-first."""
        self.clear()
        for blk in blocks:
            self.append(blk)

    def clear(self) -> None:
        self._ram = []
        for seg in self._segments:
            try:
                os.unlink(seg["path"])
            except OSError:
                pass
        self._segments = []

    def close(self) -> None:
        self.clear()
        if self._own_spool and self._spool is not None:
            shutil.rmtree(self._spool, ignore_errors=True)
            self._spool = None
            self._own_spool = True

    def __del__(self):  # best-effort spool cleanup on abandoned runs
        try:
            self.close()
        except Exception:
            pass

    # -- tier moves ----------------------------------------------------------

    def _spool_dir(self) -> str:
        if self._spool is None:
            self._spool = tempfile.mkdtemp(prefix=f"stpu-{self._label}-")
        return self._spool

    def _maybe_demote(self) -> None:
        """Demote the oldest RAM blocks into ONE new segment until the
        RAM tier fits the budget; the newest block always stays in RAM
        (it is the next pop/peek)."""
        if self._budget is None or len(self._ram) <= 1:
            return
        if self.host_bytes() <= self._budget:
            return
        demote: List[np.ndarray] = []
        freed = 0
        over = self.host_bytes() - self._budget
        while len(self._ram) > 1 and freed < over:
            blk = self._ram.pop(0)
            demote.append(blk)
            freed += blk.nbytes
        if not demote:
            return
        self._seq += 1
        path = os.path.join(
            self._spool_dir(), f"seg{self._seq:06d}.npz"
        )
        with open(path, "wb") as f:
            np.savez(f, **{f"b{i}": blk for i, blk in enumerate(demote)})
        seg = {
            "path": path,
            "rows": [len(b) for b in demote],
            "nbytes": sum(b.nbytes for b in demote),
        }
        self._segments.append(seg)
        if self._on_tier is not None:
            self._on_tier(
                "ram_to_disk", sum(seg["rows"]), seg["nbytes"],
                self.disk_bytes(),
            )

    @staticmethod
    def _load_segment(seg: dict) -> List[np.ndarray]:
        with np.load(seg["path"]) as data:
            return [data[f"b{i}"] for i in range(len(seg["rows"]))]

    def _promote_newest_segment(self) -> None:
        if not self._segments:
            raise IndexError("pop on empty spill store")
        seg = self._segments.pop()
        blocks = self._load_segment(seg)
        try:
            os.unlink(seg["path"])
        except OSError:
            pass
        # RAM is empty here (pop only promotes then) — the segment's
        # blocks ARE the new RAM tier, order preserved. Transiently
        # exceeding the budget is fine: the refill loop is about to
        # consume these newest blocks, and the next append re-demotes
        # any leftovers.
        self._ram = blocks + self._ram
        if self._on_tier is not None:
            self._on_tier(
                "disk_to_ram", sum(seg["rows"]), seg["nbytes"],
                self.disk_bytes(),
            )
