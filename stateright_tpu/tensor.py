"""The tensor encoding layer: fixed-width state encodings for batched checking.

This is the keystone of the TPU-first design (no reference counterpart — the
reference explores Rust object graphs; SURVEY.md section 7 step 3). A
`TensorModel` describes the same transition system as a `Model`, but as pure
array programs in **structure-of-arrays (lanes) form**:

  - a state is `state_width` uint32 *lanes*; a batch of B states is a tuple
    of `state_width` dense `[B]` arrays (NOT a `[B, S]` matrix — on TPU a
    row-major matrix with a small minor axis wastes the 8x128 vector tiles
    on every gather/scatter, measured at >1000x slowdown in the hot loop),
  - `step_lanes(xp, lanes)` returns, for each of the `max_actions` static
    action slots, the successor's lanes plus a validity mask — ragged
    action sets become masked slots (XLA needs static shapes),
  - properties are batched predicates over lanes: `check(xp, lanes) -> [B]`.

`step_lanes` receives the array namespace `xp` (numpy or jax.numpy) so one
definition serves both the host engines (vectorized numpy, and single-row via
the `TensorModelAdapter`) and the TPU engine (jit over the frontier).
Keeping a single source of truth is what makes host/TPU discovery-output
equivalence checkable.

Fingerprints of tensor states are computed by the shared word-stream hash
(`stateright_tpu.fingerprint.hash_lanes_*`), bit-identical on host and
device and bit-identical to the row form `hash_words_*`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from .core import Expectation, Model, Property
from .fingerprint import combine64, hash_words_np


@dataclass
class TensorProperty:
    """A batched property predicate: check(xp, lanes) -> bool[B]."""

    expectation: Expectation
    name: str
    check: Callable[[Any, Any], Any]

    @staticmethod
    def always(name: str, check) -> "TensorProperty":
        return TensorProperty(Expectation.ALWAYS, name, check)

    @staticmethod
    def eventually(name: str, check) -> "TensorProperty":
        return TensorProperty(Expectation.EVENTUALLY, name, check)

    @staticmethod
    def sometimes(name: str, check) -> "TensorProperty":
        return TensorProperty(Expectation.SOMETIMES, name, check)


class TensorModel:
    """A transition system over fixed-width uint32 state lanes.

    Subclasses define `state_width`, `max_actions`, `init_states_array`,
    `step_lanes`, and `tensor_properties`; optionally
    `within_boundary_lanes`, `decode_state` / `format_action` for display.
    """

    state_width: int
    max_actions: int

    # -- required interface -------------------------------------------------

    def init_states_array(self) -> np.ndarray:
        """[N0, S] uint32 initial states (host-side; row form is fine here)."""
        raise NotImplementedError

    def step_lanes(self, xp, lanes):
        """lanes (tuple of S uint32 [B] arrays) ->
        (succs: list over A of tuples of S [B] lanes, valid: list over A of
        bool [B] masks).

        Must be a pure array program valid under jax.jit (no data-dependent
        Python control flow; elementwise/gather ops only) and equally valid
        under numpy. Invalid action slots may contain arbitrary lane data —
        they are masked out.
        """
        raise NotImplementedError

    def tensor_properties(self) -> List[TensorProperty]:
        return []

    # -- optional interface -------------------------------------------------

    def within_boundary_lanes(self, xp, lanes):
        """lanes -> bool[B]; default: everything is in bounds."""
        return xp.ones(lanes[0].shape, dtype=bool)

    # Symmetry reduction hook (reference Representative/RewritePlan,
    # src/checker/{representative,rewrite_plan}.rs; SURVEY §7 step 8):
    # lanes -> canonicalized lanes, a pure batched array program (sorting
    # networks over entity descriptors, not argsort gathers) valid under
    # both numpy and jax.numpy. `None` means the model has no symmetry
    # canonicalization; engines asked for `.symmetry()` over such a model
    # raise instead of silently ignoring the request.
    representative_lanes = None

    def decode_state(self, row: np.ndarray) -> Any:
        """Human-readable view of one state row (Explorer / error messages)."""
        return tuple(int(v) for v in row)

    def format_action(self, action_index: int) -> str:
        return f"action[{action_index}]"

    # -- derived ------------------------------------------------------------

    def config_digest(self) -> str:
        """Stable digest of this instance's constructor-derived parameters.

        Binds checkpoints to the exact model configuration: two instances of
        the same class with different parameters that happen to share
        state_width would otherwise pass resume validation and silently
        reuse the wrong visited table. Default: every scalar/tuple attribute
        in declaration-independent (sorted) order; models holding richer
        config may override.
        """
        items = sorted(
            (k, v)
            for k, v in vars(self).items()
            if isinstance(v, (bool, int, float, str, tuple))
        )
        return repr(items)

    def fingerprint_row(self, row: np.ndarray) -> int:
        h1, h2 = hash_words_np(np.asarray(row, dtype=np.uint32)[None, :])
        return combine64(h1[0], h2[0])

    def checker(self):
        """Build a checker over the host-facing adapter view of this model."""
        return TensorModelAdapter(self).checker()


def lanes_of_rows(xp, rows):
    """[B, S] row matrix -> tuple of S [B] lane arrays."""
    return tuple(rows[:, i] for i in range(rows.shape[1]))


class _AdapterProperty:
    """Bridges a TensorProperty to a host (model, state) predicate."""

    def __init__(self, tensor_prop: TensorProperty):
        self._tp = tensor_prop

    def __call__(self, model: "TensorModelAdapter", state: Tuple[int, ...]) -> bool:
        lanes = tuple(np.asarray([v], dtype=np.uint32) for v in state)
        return bool(np.asarray(self._tp.check(np, lanes))[0])


class TensorModelAdapter(Model):
    """Presents a TensorModel through the host `Model` interface.

    States are tuples of ints (one per lane); actions are action indices.
    Host BFS/DFS run the tensor model through numpy single rows, guaranteeing
    the host and TPU engines execute the *same* transition function — the
    host run is the correctness oracle for the TPU run.
    """

    def __init__(self, tensor_model: TensorModel):
        self.tm = tensor_model
        # Single-entry step memo: engines call actions(s) then next_state(s, a)
        # once per action on the same state, which would otherwise recompute
        # the full step A+1 times per expansion.
        self._memo_key: Optional[Tuple[int, ...]] = None
        self._memo_val: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- Model interface ----------------------------------------------------

    def init_states(self) -> List[Tuple[int, ...]]:
        arr = np.asarray(self.tm.init_states_array(), dtype=np.uint32)
        return [tuple(int(v) for v in row) for row in arr]

    def actions(self, state, actions: List[int]) -> None:
        _succs, mask = self._step_row(state)
        for a in range(self.tm.max_actions):
            if mask[a]:
                actions.append(a)

    def next_state(self, last_state, action: int) -> Optional[Tuple[int, ...]]:
        succs, mask = self._step_row(last_state)
        if not mask[action]:
            return None
        return tuple(int(v) for v in succs[action])

    def properties(self) -> List[Property]:
        return [
            Property(tp.expectation, tp.name, _AdapterProperty(tp))
            for tp in self.tm.tensor_properties()
        ]

    def within_boundary(self, state) -> bool:
        lanes = tuple(np.asarray([v], dtype=np.uint32) for v in state)
        return bool(np.asarray(self.tm.within_boundary_lanes(np, lanes))[0])

    def format_action(self, action: int) -> str:
        return self.tm.format_action(action)

    def fingerprint_state(self, state) -> int:
        """Shared word hash => identical fingerprints on host and device."""
        return self.tm.fingerprint_row(np.asarray(state, dtype=np.uint32))

    def representative_state(self, state) -> Tuple[int, ...]:
        """Canonical representative of a state via the model's batched
        canonicalizer (single-row numpy evaluation). Raises if the model
        defines no symmetry."""
        if self.tm.representative_lanes is None:
            raise ValueError(
                f"{type(self.tm).__name__} defines no representative_lanes"
            )
        lanes = tuple(np.asarray([v], dtype=np.uint32) for v in state)
        canon = self.tm.representative_lanes(np, lanes)
        return tuple(int(np.asarray(l)[0]) for l in canon)

    # -- helpers ------------------------------------------------------------

    def _step_row(self, state) -> Tuple[np.ndarray, np.ndarray]:
        key = tuple(state)
        if key == self._memo_key and self._memo_val is not None:
            return self._memo_val
        lanes = tuple(np.asarray([v], dtype=np.uint32) for v in state)
        succs, valid = self.tm.step_lanes(np, lanes)
        A = self.tm.max_actions
        S = self.tm.state_width
        succ_rows = np.zeros((A, S), dtype=np.uint32)
        mask = np.zeros(A, dtype=bool)
        for a in range(A):
            mask[a] = bool(np.asarray(valid[a])[0])
            for s in range(S):
                succ_rows[a, s] = np.asarray(succs[a][s], dtype=np.uint32)[0]
        val = (succ_rows, mask)
        self._memo_key, self._memo_val = key, val
        return val


class CanonicalTensorAdapter(TensorModelAdapter):
    """Adapter view living entirely in CANONICAL (representative) space.

    Used for path reconstruction of symmetry-reduced device runs: the
    engine explores rep(init) and rep(step(rep_state)), so the chain
    walker must do exactly the same — init states and successors are
    canonicalized before matching. (Walking RAW states and matching by
    canonical fingerprint is NOT sufficient: with an imperfect
    canonicalizer — the reference's own — equivalent states may map to
    different representatives, so a raw walk can diverge from the
    canonical chain; observed at 2pc-10 depth.) The reported path is a
    sequence of representative states, each one actually explored by the
    engine.
    """

    def init_states(self):
        return [
            self.representative_state(s) for s in super().init_states()
        ]

    def next_state(self, last_state, action: int):
        nxt = super().next_state(last_state, action)
        if nxt is None:
            return None
        return self.representative_state(nxt)

    def fingerprint_state(self, state) -> int:
        return self.tm.fingerprint_row(
            np.asarray(self.representative_state(state), dtype=np.uint32)
        )
