"""The tensor encoding layer: fixed-width state encodings for batched checking.

This is the keystone of the TPU-first design (no reference counterpart — the
reference explores Rust object graphs; SURVEY.md section 7 step 3). A
`TensorModel` describes the same transition system as a `Model`, but as pure
array programs over fixed-width uint32 state rows:

  - a state is a `[S]` uint32 vector (`state_width` lanes),
  - a batch of states is `[B, S]`,
  - `step_batch(xp, states)` maps `[B, S] -> ([B, A, S] successors,
    [B, A] validity mask)` where `A = max_actions` is the static fanout bound
    (ragged action sets become masked padding — XLA needs static shapes),
  - properties are batched predicates `[B, S] -> [B]` bool.

`step_batch` receives the array namespace `xp` (numpy or jax.numpy) so one
definition serves both the host engines (vectorized numpy, and single-row via
the `TensorModelAdapter`) and the TPU engine (jit + vmap over the frontier).
Keeping a single source of truth is what makes host/TPU discovery-output
equivalence checkable.

Fingerprints of tensor states are computed by the shared word-stream hash
(`stateright_tpu.fingerprint.hash_words_*`), bit-identical on host and device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from .core import Expectation, Model, Property
from .fingerprint import combine64, hash_words_np


@dataclass
class TensorProperty:
    """A batched property predicate: check(xp, states[B,S]) -> bool[B]."""

    expectation: Expectation
    name: str
    check: Callable[[Any, Any], Any]

    @staticmethod
    def always(name: str, check) -> "TensorProperty":
        return TensorProperty(Expectation.ALWAYS, name, check)

    @staticmethod
    def eventually(name: str, check) -> "TensorProperty":
        return TensorProperty(Expectation.EVENTUALLY, name, check)

    @staticmethod
    def sometimes(name: str, check) -> "TensorProperty":
        return TensorProperty(Expectation.SOMETIMES, name, check)


class TensorModel:
    """A transition system over fixed-width uint32 state rows.

    Subclasses define `state_width`, `max_actions`, `init_states_array`,
    `step_batch`, and `tensor_properties`; optionally
    `within_boundary_batch`, `decode_state` / `format_action` for display.
    """

    state_width: int
    max_actions: int

    # -- required interface -------------------------------------------------

    def init_states_array(self) -> np.ndarray:
        """[N0, S] uint32 initial states."""
        raise NotImplementedError

    def step_batch(self, xp, states):
        """states[B, S] -> (succs[B, A, S], mask[B, A] bool).

        Must be a pure array program valid under jax.jit (no data-dependent
        Python control flow; elementwise/gather ops only) and equally valid
        under numpy. Invalid action slots may contain arbitrary state data —
        they are masked out.
        """
        raise NotImplementedError

    def tensor_properties(self) -> List[TensorProperty]:
        return []

    # -- optional interface -------------------------------------------------

    def within_boundary_batch(self, xp, states):
        """states[B, S] -> bool[B]; default: everything is in bounds."""
        return xp.ones(states.shape[0], dtype=bool)

    def decode_state(self, row: np.ndarray) -> Any:
        """Human-readable view of one state row (Explorer / error messages)."""
        return tuple(int(v) for v in row)

    def format_action(self, action_index: int) -> str:
        return f"action[{action_index}]"

    # -- derived ------------------------------------------------------------

    def fingerprint_row(self, row: np.ndarray) -> int:
        h1, h2 = hash_words_np(np.asarray(row, dtype=np.uint32)[None, :])
        return combine64(h1[0], h2[0])

    def checker(self):
        """Build a checker over the host-facing adapter view of this model."""
        return TensorModelAdapter(self).checker()


class _AdapterProperty:
    """Bridges a TensorProperty to a host (model, state) predicate."""

    def __init__(self, tensor_prop: TensorProperty):
        self._tp = tensor_prop

    def __call__(self, model: "TensorModelAdapter", state: Tuple[int, ...]) -> bool:
        row = np.asarray(state, dtype=np.uint32)[None, :]
        return bool(np.asarray(self._tp.check(np, row))[0])


class TensorModelAdapter(Model):
    """Presents a TensorModel through the host `Model` interface.

    States are tuples of ints (one per lane); actions are action indices.
    Host BFS/DFS run the tensor model through numpy single rows, guaranteeing
    the host and TPU engines execute the *same* transition function — the
    host run is the correctness oracle for the TPU run.
    """

    def __init__(self, tensor_model: TensorModel):
        self.tm = tensor_model
        # Single-entry step memo: engines call actions(s) then next_state(s, a)
        # once per action on the same state, which would otherwise recompute
        # the full step_batch A+1 times per expansion.
        self._memo_key: Optional[Tuple[int, ...]] = None
        self._memo_val: Optional[Tuple[np.ndarray, np.ndarray]] = None

    # -- Model interface ----------------------------------------------------

    def init_states(self) -> List[Tuple[int, ...]]:
        arr = np.asarray(self.tm.init_states_array(), dtype=np.uint32)
        return [tuple(int(v) for v in row) for row in arr]

    def actions(self, state, actions: List[int]) -> None:
        _succs, mask = self._step_row(state)
        for a in range(self.tm.max_actions):
            if mask[a]:
                actions.append(a)

    def next_state(self, last_state, action: int) -> Optional[Tuple[int, ...]]:
        succs, mask = self._step_row(last_state)
        if not mask[action]:
            return None
        return tuple(int(v) for v in succs[action])

    def properties(self) -> List[Property]:
        return [
            Property(tp.expectation, tp.name, _AdapterProperty(tp))
            for tp in self.tm.tensor_properties()
        ]

    def within_boundary(self, state) -> bool:
        row = np.asarray(state, dtype=np.uint32)[None, :]
        return bool(np.asarray(self.tm.within_boundary_batch(np, row))[0])

    def format_action(self, action: int) -> str:
        return self.tm.format_action(action)

    def fingerprint_state(self, state) -> int:
        """Shared word hash => identical fingerprints on host and device."""
        return self.tm.fingerprint_row(np.asarray(state, dtype=np.uint32))

    # -- helpers ------------------------------------------------------------

    def _step_row(self, state) -> Tuple[np.ndarray, np.ndarray]:
        key = tuple(state)
        if key == self._memo_key and self._memo_val is not None:
            return self._memo_val
        row = np.asarray(state, dtype=np.uint32)[None, :]
        succs, mask = self.tm.step_batch(np, row)
        val = (
            np.asarray(succs, dtype=np.uint32)[0],
            np.asarray(mask, dtype=bool)[0],
        )
        self._memo_key, self._memo_val = key, val
        return val
