"""ABD linearizable register as a TPU-native TensorModel.

The device twin of `examples/linearizable_register.py` (reference:
examples/linearizable-register.rs:60-255): two ABD servers, `c` register
clients, the unordered non-duplicating network, and the linearizability
tester carried as state — all encoded on the `lanes.ActorNetModel`
toolkit, proving the toolkit generalizes beyond the paxos twin it was
extracted from.

Protocol (Attiya-Bar-Noy-Dolev): phase 1 queries a quorum for the highest
(logical-clock, server-id) sequencer; phase 2 records the chosen
value/sequencer at a quorum before replying. With s=2 servers the quorum
is both servers, which simplifies the lane program: the self-response
means ONE AckQuery reaches quorum and ONE AckRecord completes phase 2.

State identity matches the host `ActorModel` exactly (544 unique states
at 2 clients / 2 servers, linearizable-register.rs:287), including the
tester lanes (client phases, read values, real-time counters — the shared
register-client packing in stateright_tpu.lanes).

In-flight bound K = c + 2: each client has at most one client-protocol
message outstanding (Put/PutOk/Get/GetOk are strict request-response),
and each server at most one internal message per active phase (Query ->
AckQuery -> Record -> AckRecord are sequential, and with s=2 every ack is
consumed before the phase advances). Golden-validated against the actor
model.

Lane layout (S = 4 + c + K):
  lanes 0..3    server j: [2j] core (seq|val|ptag|rid|requester|wval),
                [2j+1] phase detail (P1 response map / P2 read+acks)
  lanes 4..4+c-1 client i: shared register-client tester packing
  remaining K   network: sorted envelope words, 0 = empty
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..lanes import (
    ActorNetModel,
    decode_net,
    decode_register_clients,
    env_word,
    register_client_deliver,
    register_family_properties,
    register_linearizable_lanes,
)
from ..tensor import TensorProperty

# Message types (nonzero so an envelope word is never 0).
PUT, GET, PUTOK, GETOK, QUERY, ACKQUERY, RECORD, ACKRECORD = range(1, 9)

# Server core-lane field offsets.
_SEQ = 0  # 5 bits: clock(4) << 1 | server_id(1); lex order == int order
_VAL = 5  # 3 bits: 0 = None, 1..c = client (id-2)'s value
_PTAG = 8  # 2 bits: 0 = idle, 1 = phase 1, 2 = phase 2
_RID = 10  # 4 bits
_REQ = 14  # 4 bits: requester actor id
_WVAL = 18  # 3 bits: phase-1 pending write value; 0 = read

# Phase-detail lane (overlaid; _PTAG disambiguates, idle == 0).
# P1: per-server response slot t: present(1) | seq(5) | val(3) at 9*t.
# P2: is_read(1) @0 | read code(4) @1 | acks set(2) @5.


class AbdTensor(ActorNetModel):
    """Device twin of abd_model(client_count, 2). See module docstring."""

    max_sends = 1  # s=2: every delivery sends at most one message

    def __init__(self, client_count: int, server_count: int = 2):
        if server_count != 2:
            raise ValueError("AbdTensor supports exactly 2 servers")
        if client_count > 5:
            raise ValueError(
                "AbdTensor supports at most 5 clients (4-bit request ids)"
            )
        self.c = client_count
        self.n_servers = 2
        self.K = client_count + 2
        self.n_actor_lanes = 4 + client_count

    # -- init ---------------------------------------------------------------

    def init_states_array(self) -> np.ndarray:
        # Server j starts as AbdState(seq=(0, j), val=None, phase=None):
        # seq packs to j, everything else zero. Client m (= 2 + i) sends
        # Put(request_id=m, value=i+1) to server m % 2 on start.
        servers = [0, 0, 1, 0]  # [seq lane j=0, detail, seq lane j=1, detail]
        puts = [
            (PUT << 28) | ((2 + i) << 24) | (((2 + i) % 2) << 20)
            | (2 + i) | ((i + 1) << 4)
            for i in range(self.c)
        ]
        return self.pack_init_row(servers, puts)

    # -- the batched delivery handler ---------------------------------------

    def deliver(self, xp, lanes, env):
        u = xp.uint32
        c = self.c
        occ = env != u(0)
        typ = env >> u(28)
        src = (env >> u(24)) & u(15)
        dst = (env >> u(20)) & u(15)
        pay = env & u((1 << 20) - 1)
        rid = pay & u(15)
        mseq = (pay >> u(4)) & u(31)
        mval = (pay >> u(9)) & u(7)

        new_lanes = list(lanes)
        changed = occ & False
        send = u(0) * env

        for j in range(2):
            cond = occ & (dst == u(j))
            a = lanes[2 * j]
            b = lanes[2 * j + 1]
            seq = (a >> u(_SEQ)) & u(31)
            val = (a >> u(_VAL)) & u(7)
            ptag = (a >> u(_PTAG)) & u(3)
            my_rid = (a >> u(_RID)) & u(15)
            req = (a >> u(_REQ)) & u(15)
            wval = (a >> u(_WVAL)) & u(7)
            peer = 1 - j

            # Put/Get on an idle server: open phase 1 with the self
            # response recorded, query the peer
            # (linearizable-register.rs:107-127).
            is_start = (typ == u(PUT)) | (typ == u(GET))
            b_start = cond & is_start & (ptag == u(0))
            start_wval = xp.where(typ == u(PUT), (pay >> u(4)) & u(7), u(0) * env)
            start_a = (
                (seq << u(_SEQ))
                | (val << u(_VAL))
                | (u(1) << u(_PTAG))
                | (rid << u(_RID))
                | (src << u(_REQ))
                | (start_wval << u(_WVAL))
            )
            # P1 detail: self slot j present with (seq, val).
            start_b = (u(1) | (seq << u(1)) | (val << u(6))) << u(9 * j)
            start_send = env_word(
                xp, QUERY, u(j) + (src & u(0)), u(peer) + (src & u(0)), rid
            )

            # Query: reply with our (seq, val) — unconditional, stateless
            # (linearizable-register.rs:129-131).
            b_query = cond & (typ == u(QUERY))
            query_send = env_word(
                xp, ACKQUERY, u(j) + (src & u(0)), src,
                rid | (seq << u(4)) | (val << u(9)),
            )

            # AckQuery for the open phase 1: with s=2 the peer's response
            # completes the quorum immediately (self response counts).
            # Choose max-seq (seqs are globally distinct), then move to
            # phase 2 and Record at the peer
            # (linearizable-register.rs:133-165).
            b_ackq = cond & (typ == u(ACKQUERY)) & (ptag == u(1)) & (rid == my_rid)
            self_seq = (b >> u(9 * j + 1)) & u(31)
            self_val = (b >> u(9 * j + 6)) & u(7)
            peer_better = mseq > self_seq
            best_seq = xp.where(peer_better, mseq, self_seq)
            best_val = xp.where(peer_better, mval, self_val)
            is_read = wval == u(0)
            # Write: bump the clock, tag with our id. Read: keep best.
            chosen_seq = xp.where(
                is_read, best_seq, (((best_seq >> u(1)) + u(1)) << u(1)) | u(j)
            )
            chosen_val = xp.where(is_read, best_val, wval)
            read_code = best_val + u(1)  # 0->1 (None), v -> 2+(v-1)
            # Self-record: adopt (chosen_seq, chosen_val) if greater.
            adopt = chosen_seq > seq
            ackq_a = (
                (xp.where(adopt, chosen_seq, seq) << u(_SEQ))
                | (xp.where(adopt, chosen_val, val) << u(_VAL))
                | (u(2) << u(_PTAG))
                | (my_rid << u(_RID))
                | (req << u(_REQ))
            )
            ackq_b = (
                is_read.astype(xp.uint32)
                | (xp.where(is_read, read_code, u(0) * env) << u(1))
                | (u(1 << j) << u(5))  # acks = {self}
            )
            ackq_send = env_word(
                xp, RECORD, u(j) + (src & u(0)), u(peer) + (src & u(0)),
                my_rid | (chosen_seq << u(4)) | (chosen_val << u(9)),
            )

            # Record: ack, and adopt the recorded (seq, val) if greater
            # (linearizable-register.rs:167-172).
            b_rec = cond & (typ == u(RECORD))
            rec_adopt = mseq > seq
            rec_a = (
                (xp.where(rec_adopt, mseq, seq) << u(_SEQ))
                | (xp.where(rec_adopt, mval, val) << u(_VAL))
                | (a & ~u((31 << _SEQ) | (7 << _VAL)))
            )
            rec_send = env_word(
                xp, ACKRECORD, u(j) + (src & u(0)), src, rid
            )

            # AckRecord for the open phase 2: with s=2 the peer's ack
            # completes the quorum; reply to the requester and go idle
            # (linearizable-register.rs:174-189).
            acks = (b >> u(5)) & u(3)
            src_bit = u(1) << src  # src is 0 or 1 here (a server id)
            b_ackr = (
                cond
                & (typ == u(ACKRECORD))
                & (ptag == u(2))
                & (rid == my_rid)
                & ((acks & src_bit) == u(0))
            )
            p2_is_read = (b & u(1)) == u(1)
            p2_code = (b >> u(1)) & u(15)
            ackr_a = (seq << u(_SEQ)) | (val << u(_VAL))  # idle: clears phase
            done_send = xp.where(
                p2_is_read,
                env_word(xp, GETOK, u(j) + (src & u(0)), req, my_rid | (p2_code << u(4))),
                env_word(xp, PUTOK, u(j) + (src & u(0)), req, my_rid),
            )

            na = a
            nb = b
            na = xp.where(b_start, start_a, na)
            nb = xp.where(b_start, start_b, nb)
            na = xp.where(b_ackq, ackq_a, na)
            nb = xp.where(b_ackq, ackq_b, nb)
            na = xp.where(b_rec, rec_a, na)
            na = xp.where(b_ackr, ackr_a, na)
            nb = xp.where(b_ackr, u(0) * env, nb)
            new_lanes[2 * j] = na
            new_lanes[2 * j + 1] = nb
            changed = changed | b_start | b_ackq | (b_rec & rec_adopt) | b_ackr

            s = u(0) * env
            s = xp.where(b_start, start_send, s)
            s = xp.where(b_query, query_send, s)
            s = xp.where(b_ackq, ackq_send, s)
            s = xp.where(b_rec, rec_send, s)
            s = xp.where(b_ackr, done_send, s)
            send = send | s

        # Clients: the shared RegisterClient lane program.
        client_lanes = [lanes[4 + i] for i in range(c)]
        for i in range(c):
            cid = 2 + i
            cond = occ & (dst == u(cid))
            get_send = env_word(
                xp, GET, u(cid) + (src & u(0)),
                u((cid + 1) % 2) + (src & u(0)), u(2 * cid),
            )
            ncl, csend, chg = register_client_deliver(
                xp,
                client_lanes,
                i,
                cond & (typ == u(PUTOK)),
                cond & (typ == u(GETOK)),
                (pay >> u(4)) & u(15),
                get_send,
            )
            new_lanes[4 + i] = ncl
            changed = changed | chg
            send = send | csend

        return new_lanes, [send], changed

    # -- properties ---------------------------------------------------------

    def linearizable_lanes(self, xp, lanes):
        return register_linearizable_lanes(
            xp, [lanes[4 + i] for i in range(self.c)]
        )

    def tensor_properties(self) -> List[TensorProperty]:
        return register_family_properties(self, GETOK, val_shift=4)

    # -- display ------------------------------------------------------------

    def decode_state(self, row) -> dict:
        names = dict(
            zip(
                range(1, 9),
                "Put Get PutOk GetOk Query AckQuery Record AckRecord".split(),
            )
        )
        servers = []
        for j in range(2):
            a = int(row[2 * j])
            servers.append(
                {
                    "seq": ((a >> 1) & 15, a & 1),
                    "val": (a >> _VAL) & 7,
                    "phase": (a >> _PTAG) & 3,
                    "rid": (a >> _RID) & 15,
                }
            )
        clients = decode_register_clients(row, 4, self.c)
        return {
            "servers": servers,
            "clients": clients,
            "net": decode_net(row, self.n_actor_lanes, self.K, names),
        }


class AbdOrderedTensor(AbdTensor):
    """ABD over the ORDERED network: per-flow FIFO, head-only delivery.

    Device twin of `abd_model(c, 2, Network.new_ordered())` — the
    reference's `linearizable-register check N ordered` workload
    (bench.sh:33; Ordered semantics network.rs:62-68, head-of-flow rule
    model.rs:269-275). The toolkit's ordered mode (lanes.net_step_ordered)
    supplies the flow-rank encoding; the delivery handler is inherited
    unchanged (ABD payloads fit the 16-bit ordered payload field).

    Host-oracle goldens (exhaustive actor-model runs): 620 uniques at
    c=2, 46,516 at c=3; linearizable HOLDS on both.
    """

    ordered = True
