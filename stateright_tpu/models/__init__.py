"""Example and fixture models.

Fixtures (`fixtures.py`) mirror the reference's src/test_util.rs models used
to test the engines themselves. Protocol examples (two_phase_commit,
increment, …) mirror the reference's examples/ directory and double as the
integration-test and benchmark suite, with golden unique-state counts.
"""

from .fixtures import BinaryClock, DGraph, LinearEquation, Panicker
from .two_phase_commit import TwoPhaseSys, TwoPhaseTensor
from .increment import Increment, IncrementTensor
from .increment_lock import IncrementLock, IncrementLockTensor
from .abd import AbdOrderedTensor, AbdTensor
from .paxos import PaxosTensor
from .single_copy import SingleCopyTensor

__all__ = [
    "AbdOrderedTensor",
    "AbdTensor",
    "BinaryClock",
    "DGraph",
    "Increment",
    "IncrementLock",
    "IncrementLockTensor",
    "IncrementTensor",
    "LinearEquation",
    "Panicker",
    "PaxosTensor",
    "SingleCopyTensor",
    "TwoPhaseSys",
    "TwoPhaseTensor",
]
