"""Single-copy register as a TPU-native TensorModel.

The device twin of `examples/single_copy_register.py` (reference:
examples/single-copy-register.rs): `s` independent register servers (no
consensus — a server stores whatever it was last told and answers reads
from its own copy) plus `c` toolkit register clients. With one server the
system is linearizable; with two, a client that writes to server 0 and
reads from server 1 gets None back — a completed read that cannot
linearize past the client's own completed write. The shared
`register_linearizable_lanes` program finds that counterexample ON DEVICE,
which makes this twin the toolkit's only register-family member whose
linearizability property actually FIRES on a real (un-mutated) protocol.

Server state is one lane: the stored value (0 = None, 1..c = client i's
value). In-flight bound: exactly c (every client keeps one request-
response message outstanding and servers reply in the same delivery) —
and the protocol SITS at that bound, so the ring carries one slack slot
(K = c + 1) to keep the `net_capacity_property` guard meaningful: slot 0
nonzero then really means the bound was exceeded, not merely reached.

Lane layout (S = s + c + K):
  lanes 0..s-1     server j: stored value
  lanes s..s+c-1   client i: shared register-client tester packing
  remaining K      network: sorted envelope words, 0 = empty
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..lanes import (
    ActorNetModel,
    decode_net,
    decode_register_clients,
    env_word,
    register_client_deliver,
    register_family_properties,
    register_linearizable_lanes,
)
from ..tensor import TensorProperty

PUT, GET, PUTOK, GETOK = range(1, 5)


class SingleCopyTensor(ActorNetModel):
    """Device twin of single_copy_model(client_count, server_count)."""

    max_sends = 1

    def __init__(self, client_count: int, server_count: int = 1):
        if not 1 <= server_count <= 4:
            raise ValueError("SingleCopyTensor supports 1-4 servers")
        if client_count > 5:
            raise ValueError("SingleCopyTensor supports at most 5 clients")
        self.c = client_count
        self.s = server_count
        self.K = client_count + 1
        self.n_actor_lanes = server_count + client_count

    # -- init ---------------------------------------------------------------

    def init_states_array(self) -> np.ndarray:
        s, c = self.s, self.c
        # Client m (= s + i) sends Put(request_id=m, value=i+1) to m % s.
        puts = [
            (PUT << 28) | ((s + i) << 24) | (((s + i) % s) << 20)
            | (s + i) | ((i + 1) << 4)
            for i in range(c)
        ]
        return self.pack_init_row([0] * s, puts)

    # -- the batched delivery handler ---------------------------------------

    def deliver(self, xp, lanes, env):
        u = xp.uint32
        s, c = self.s, self.c
        occ = env != u(0)
        typ = env >> u(28)
        src = (env >> u(24)) & u(15)
        dst = (env >> u(20)) & u(15)
        pay = env & u((1 << 20) - 1)
        rid = pay & u(15)

        new_lanes = list(lanes)
        changed = occ & False
        send = u(0) * env

        for j in range(s):
            cond = occ & (dst == u(j))
            val = lanes[j]
            b_put = cond & (typ == u(PUT))
            b_get = cond & (typ == u(GET))
            # Put: store, ack (single-copy-register.rs:27-33).
            new_lanes[j] = xp.where(b_put, (pay >> u(4)) & u(7), val)
            put_send = env_word(xp, PUTOK, u(j) + (src & u(0)), src, rid)
            # Get: answer from the local copy; tester code 1+val maps the
            # empty register to None (single-copy-register.rs:35-41).
            get_send = env_word(
                xp, GETOK, u(j) + (src & u(0)), src,
                rid | ((val + u(1)) << u(4)),
            )
            send = send | xp.where(b_put, put_send, u(0) * env)
            send = send | xp.where(b_get, get_send, u(0) * env)
            changed = changed | b_put

        client_lanes = [lanes[s + i] for i in range(c)]
        for i in range(c):
            cid = s + i
            cond = occ & (dst == u(cid))
            get_env = env_word(
                xp, GET, u(cid) + (src & u(0)),
                u((cid + 1) % s) + (src & u(0)), u(2 * cid),
            )
            ncl, csend, chg = register_client_deliver(
                xp,
                client_lanes,
                i,
                cond & (typ == u(PUTOK)),
                cond & (typ == u(GETOK)),
                (pay >> u(4)) & u(15),
                get_env,
            )
            new_lanes[s + i] = ncl
            changed = changed | chg
            send = send | csend

        return new_lanes, [send], changed

    # -- properties ---------------------------------------------------------

    def linearizable_lanes(self, xp, lanes):
        return register_linearizable_lanes(
            xp, [lanes[self.s + i] for i in range(self.c)]
        )

    def tensor_properties(self) -> List[TensorProperty]:
        return register_family_properties(self, GETOK, val_shift=4)

    # -- display ------------------------------------------------------------

    def decode_state(self, row) -> dict:
        names = dict(zip(range(1, 5), "Put Get PutOk GetOk".split()))
        return {
            "servers": [int(row[j]) for j in range(self.s)],
            "clients": decode_register_clients(row, self.s, self.c),
            "net": decode_net(row, self.n_actor_lanes, self.K, names),
        }
