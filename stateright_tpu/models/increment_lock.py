"""Lock-protected shared-memory counter: the race in `increment` fixed.

Reference: examples/increment_lock.rs — each thread Lock→Read→Write→Release;
the "fin" invariant now holds, and a "mutex" invariant asserts at most one
thread is inside the critical section.

`IncrementLock` is the host model; `IncrementLockTensor` the dense TPU
encoding (lane 0 = counter, lane 1 = lock bit, lanes 2+2k/3+2k = thread k's
local value and program counter; action slots 4k..4k+3 = Lock/Read/Write/
Release for thread k).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..core import Model, Property
from ..tensor import TensorModel, TensorProperty


@dataclass(frozen=True)
class IncrementLockState:
    i: int
    lock: bool
    s: Tuple[Tuple[int, int], ...]  # per-thread (t, pc)

    def representative(self) -> "IncrementLockState":
        """Sort the identical threads (examples/increment_lock.rs:35-45)."""
        return IncrementLockState(self.i, self.lock, tuple(sorted(self.s)))


class IncrementLock(Model):
    """Host model. Reference: examples/increment_lock.rs:47-107."""

    def __init__(self, thread_count: int):
        self.n = thread_count

    def init_states(self) -> List[IncrementLockState]:
        return [IncrementLockState(0, False, ((0, 0),) * self.n)]

    def actions(self, state: IncrementLockState, actions: List) -> None:
        for tid in range(self.n):
            pc = state.s[tid][1]
            if pc == 0 and not state.lock:
                actions.append(("Lock", tid))
            elif pc == 1:
                actions.append(("Read", tid))
            elif pc == 2:
                actions.append(("Write", tid))
            elif pc == 3 and state.lock:
                actions.append(("Release", tid))

    def next_state(self, state: IncrementLockState, action) -> IncrementLockState:
        kind, tid = action
        s = list(state.s)
        t, _pc = state.s[tid]
        if kind == "Lock":
            s[tid] = (t, 1)
            return IncrementLockState(state.i, True, tuple(s))
        if kind == "Read":
            s[tid] = (state.i, 2)
            return IncrementLockState(state.i, state.lock, tuple(s))
        if kind == "Write":
            s[tid] = (t, 3)
            return IncrementLockState((t + 1) % 256, state.lock, tuple(s))
        s[tid] = (t, 4)  # Release
        return IncrementLockState(state.i, False, tuple(s))

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _m, s: sum(1 for (_t, pc) in s.s if pc >= 3) % 256 == s.i,
            ),
            Property.always(
                "mutex",
                lambda _m, s: sum(1 for (_t, pc) in s.s if 1 <= pc < 4) <= 1,
            ),
        ]


class IncrementLockTensor(TensorModel):
    """Dense encoding of `IncrementLock` for the batched TPU engine."""

    def __init__(self, thread_count: int):
        self.n = thread_count
        self.state_width = 2 + 2 * thread_count
        self.max_actions = 4 * thread_count

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, self.state_width), dtype=np.uint32)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        succs = []
        masks = []
        shared = lanes[0]
        lock = lanes[1]
        for k in range(self.n):
            t = lanes[2 + 2 * k]
            pc = lanes[3 + 2 * k]

            # Lock(k): lock <- 1, pc <- 1 (enabled iff pc == 0 and !lock)
            cols = list(lanes)
            cols[1] = xp.ones_like(lock)
            cols[3 + 2 * k] = xp.full_like(pc, 1)
            succs.append(tuple(cols))
            masks.append((pc == u(0)) & (lock == u(0)))

            # Read(k): t <- shared, pc <- 2
            cols = list(lanes)
            cols[2 + 2 * k] = shared
            cols[3 + 2 * k] = xp.full_like(pc, 2)
            succs.append(tuple(cols))
            masks.append(pc == u(1))

            # Write(k): shared <- t + 1, pc <- 3
            cols = list(lanes)
            cols[0] = (t + u(1)) & u(0xFF)
            cols[3 + 2 * k] = xp.full_like(pc, 3)
            succs.append(tuple(cols))
            masks.append(pc == u(2))

            # Release(k): lock <- 0, pc <- 4
            cols = list(lanes)
            cols[1] = xp.zeros_like(lock)
            cols[3 + 2 * k] = xp.full_like(pc, 4)
            succs.append(tuple(cols))
            masks.append((pc == u(3)) & (lock == u(1)))

        return succs, masks

    def tensor_properties(self) -> List[TensorProperty]:
        n = self.n

        def fin(xp, lanes):
            count = xp.zeros(lanes[0].shape, dtype=xp.uint32)
            for k in range(n):
                count = count + (lanes[3 + 2 * k] >= xp.uint32(3)).astype(
                    xp.uint32
                )
            return (count & xp.uint32(0xFF)) == lanes[0]

        def mutex(xp, lanes):
            count = xp.zeros(lanes[0].shape, dtype=xp.uint32)
            for k in range(n):
                pc = lanes[3 + 2 * k]
                count = count + (
                    (pc >= xp.uint32(1)) & (pc < xp.uint32(4))
                ).astype(xp.uint32)
            return count <= xp.uint32(1)

        return [
            TensorProperty.always("fin", fin),
            TensorProperty.always("mutex", mutex),
        ]

    def format_action(self, a: int) -> str:
        tid, kind = divmod(a, 4)
        return f"{('Lock', 'Read', 'Write', 'Release')[kind]}({tid})"
