"""Fixture models for testing the engines themselves.

Reference: src/test_util.rs — binary_clock (2-state machine), dgraph
(arbitrary graph from paths; used for eventually-property semantics tests),
linear_equation_solver (the canonical engine test: 256x256 u8 space), and
panicker (clean shutdown when user code raises).
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..core import Model, Property


class BinaryClock(Model):
    """Cycles between 0 and 1. Reference: test_util.rs:3-47."""

    GO_LOW = "GoLow"
    GO_HIGH = "GoHigh"

    def init_states(self) -> List[int]:
        return [0, 1]

    def actions(self, state: int, actions: List[str]) -> None:
        if state == 0:
            actions.append(self.GO_HIGH)
        else:
            actions.append(self.GO_LOW)

    def next_state(self, state: int, action: str):
        return 1 if action == self.GO_HIGH else 0

    def properties(self) -> List[Property]:
        return [Property.always("in [0, 1]", lambda _m, s: 0 <= s <= 1)]


class DGraph(Model):
    """A directed graph specified via paths from initial states.

    Reference: test_util.rs:49-116. States and actions are small ints; the
    action *is* the destination state.
    """

    def __init__(self, property: Property):
        self.inits: Set[int] = set()
        self.edges: Dict[int, Set[int]] = {}
        self._property = property

    @staticmethod
    def with_property(property: Property) -> "DGraph":
        return DGraph(property)

    def with_path(self, path: List[int]) -> "DGraph":
        src = path[0]
        self.inits.add(src)
        for dst in path[1:]:
            self.edges.setdefault(src, set()).add(dst)
            src = dst
        return self

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self) -> List[int]:
        return sorted(self.inits)

    def actions(self, state: int, actions: List[int]) -> None:
        actions.extend(sorted(self.edges.get(state, ())))

    def next_state(self, _state: int, action: int) -> int:
        return action

    def properties(self) -> List[Property]:
        return [self._property]


class LinearEquation(Model):
    """Solve a*x + b*y = c in u8 by guessing increments.

    Reference: test_util.rs:139-192. Full state space is 256*256 = 65,536.
    """

    INCREASE_X = "IncreaseX"
    INCREASE_Y = "IncreaseY"

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    def init_states(self):
        return [(0, 0)]

    def actions(self, _state, actions: List[str]) -> None:
        actions.append(self.INCREASE_X)
        actions.append(self.INCREASE_Y)

    def next_state(self, state, action: str):
        x, y = state
        if action == self.INCREASE_X:
            return ((x + 1) % 256, y)
        return (x, (y + 1) % 256)

    def properties(self) -> List[Property]:
        def solvable(model: "LinearEquation", solution) -> bool:
            x, y = solution
            return (model.a * x + model.b * y) % 256 == model.c % 256

        return [Property.sometimes("solvable", solvable)]


class Panicker(Model):
    """Raises during checking once state 5 is expanded. Reference: test_util.rs:194-228."""

    def init_states(self):
        return [0]

    def actions(self, _state, actions: List[int]) -> None:
        actions.append(1)

    def next_state(self, last_state: int, action: int):
        if last_state == 5:
            raise RuntimeError("reached panic state")
        return last_state + action

    def properties(self) -> List[Property]:
        return [Property.always("true", lambda _m, _s: True)]
