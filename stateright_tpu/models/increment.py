"""Unsynchronized shared-memory counter: the classic lost-update race.

Reference: examples/increment.rs — N threads each read the shared counter
then write back the increment; interleavings break the invariant that the
counter equals the number of finished threads (13 unique states at N=2,
8 with symmetry reduction; the "fin" always-property has a counterexample).

`Increment` is the host model; `IncrementTensor` is the dense TPU encoding
(one lane for the shared counter, two lanes per thread for local value and
program counter).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..core import Model, Property
from ..tensor import TensorModel, TensorProperty


@dataclass(frozen=True)
class IncrementState:
    i: int
    s: Tuple[Tuple[int, int], ...]  # per-thread (t, pc)

    def representative(self) -> "IncrementState":
        """Sort the identical threads (examples/increment.rs:142-151)."""
        return IncrementState(self.i, tuple(sorted(self.s)))


class Increment(Model):
    """Host model. Reference: examples/increment.rs:153-197."""

    def __init__(self, thread_count: int):
        self.n = thread_count

    def init_states(self) -> List[IncrementState]:
        return [IncrementState(0, ((0, 1),) * self.n)]

    def actions(self, state: IncrementState, actions: List) -> None:
        for tid in range(self.n):
            pc = state.s[tid][1]
            if pc == 1:
                actions.append(("Read", tid))
            elif pc == 2:
                actions.append(("Write", tid))

    def next_state(self, state: IncrementState, action) -> IncrementState:
        kind, tid = action
        s = list(state.s)
        if kind == "Read":
            s[tid] = (state.i, 2)
            return IncrementState(state.i, tuple(s))
        t = state.s[tid][0]
        s[tid] = (t, 3)
        return IncrementState((t + 1) % 256, tuple(s))

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _m, s: sum(1 for (_t, pc) in s.s if pc == 3) % 256 == s.i,
            )
        ]


class IncrementTensor(TensorModel):
    """Dense encoding: lane 0 = shared counter; lanes 1+2k / 2+2k = thread k's
    local value and program counter. Actions: slot 2k = Read(k), 2k+1 = Write(k).
    """

    def __init__(self, thread_count: int):
        self.n = thread_count
        self.state_width = 1 + 2 * thread_count
        self.max_actions = 2 * thread_count

    def init_states_array(self) -> np.ndarray:
        row = np.zeros(self.state_width, dtype=np.uint32)
        for k in range(self.n):
            row[2 + 2 * k] = 1  # pc = 1
        return row[None, :]

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        succs = []
        masks = []
        shared = lanes[0]
        for k in range(self.n):
            t = lanes[1 + 2 * k]
            pc = lanes[2 + 2 * k]

            # Read(k): t <- shared, pc <- 2
            cols = list(lanes)
            cols[1 + 2 * k] = shared
            cols[2 + 2 * k] = xp.full_like(pc, 2)
            succs.append(tuple(cols))
            masks.append(pc == u(1))

            # Write(k): shared <- t + 1, pc <- 3
            cols = list(lanes)
            cols[0] = (t + u(1)) & u(0xFF)
            cols[2 + 2 * k] = xp.full_like(pc, 3)
            succs.append(tuple(cols))
            masks.append(pc == u(2))

        return succs, masks

    def tensor_properties(self) -> List[TensorProperty]:
        n = self.n

        def fin(xp, lanes):
            finished = lanes[2] == xp.uint32(3)
            count = finished.astype(xp.uint32)
            for k in range(1, n):
                count = count + (lanes[2 + 2 * k] == xp.uint32(3)).astype(
                    xp.uint32
                )
            return (count & xp.uint32(0xFF)) == lanes[0]

        return [TensorProperty.always("fin", fin)]

    def format_action(self, a: int) -> str:
        tid, kind = divmod(a, 2)
        return f"{'Read' if kind == 0 else 'Write'}({tid})"
