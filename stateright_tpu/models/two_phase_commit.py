"""Two-phase commit, after Gray & Lamport's "Consensus on Transaction Commit".

Reference: examples/2pc.rs — an abstract TLA+-style model (no actors). Golden
unique-state counts: 288 at 3 RMs, 8,832 at 5 RMs, 665 at 5 RMs with symmetry
reduction (examples/2pc.rs:149-170).

Two implementations of the same system:

  - `TwoPhaseSys`: a host `Model` over rich Python states, action order
    matching the reference for golden parity.
  - `TwoPhaseTensor`: the TPU-native `TensorModel` — the whole system state
    packs into 3 uint32 lanes (TM state, TM-prepared bitmask + RM states at
    2 bits each, message-set bitmask), and all 2+5N actions are evaluated as
    one masked batch. This dense encoding is what the batched frontier engine
    explores at full speed.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import FrozenSet, List, Tuple

import numpy as np

from ..core import Model, Property
from ..tensor import TensorModel, TensorProperty

# RM states
WORKING, PREPARED, COMMITTED, ABORTED = 0, 1, 2, 3
# TM states
TM_INIT, TM_COMMITTED, TM_ABORTED = 0, 1, 2

# Messages are encoded as ints: Prepared{rm} = rm, Commit = -1, Abort = -2.
MSG_COMMIT = -1
MSG_ABORT = -2


@dataclass(frozen=True)
class TwoPhaseState:
    rm_state: Tuple[int, ...]
    tm_state: int
    tm_prepared: Tuple[bool, ...]
    msgs: FrozenSet[int]

    def representative(self) -> "TwoPhaseState":
        """Canonicalize under RM-identity permutation (examples/2pc.rs:203-229).

        Sort RMs by their local state, reindexing tm_prepared and Prepared
        messages with the same permutation.
        """
        n = len(self.rm_state)
        order = sorted(range(n), key=lambda i: self.rm_state[i])
        inverse = [0] * n
        for new_i, old_i in enumerate(order):
            inverse[old_i] = new_i
        return TwoPhaseState(
            rm_state=tuple(self.rm_state[i] for i in order),
            tm_state=self.tm_state,
            tm_prepared=tuple(self.tm_prepared[i] for i in order),
            msgs=frozenset(
                m if m < 0 else inverse[m] for m in self.msgs
            ),
        )


class TwoPhaseSys(Model):
    """Host model. Reference: examples/2pc.rs:59-147."""

    def __init__(self, rm_count: int):
        self.rm_count = rm_count

    def init_states(self) -> List[TwoPhaseState]:
        n = self.rm_count
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * n,
                tm_state=TM_INIT,
                tm_prepared=(False,) * n,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState, actions: List) -> None:
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            actions.append(("TmCommit",))
        if state.tm_state == TM_INIT:
            actions.append(("TmAbort",))
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and rm in state.msgs:
                actions.append(("TmRcvPrepared", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("RmPrepare", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("RmChooseToAbort", rm))
            if MSG_COMMIT in state.msgs:
                actions.append(("RmRcvCommitMsg", rm))
            if MSG_ABORT in state.msgs:
                actions.append(("RmRcvAbortMsg", rm))

    def next_state(self, s: TwoPhaseState, action) -> TwoPhaseState:
        kind = action[0]
        if kind == "TmRcvPrepared":
            rm = action[1]
            prepared = list(s.tm_prepared)
            prepared[rm] = True
            return replace(s, tm_prepared=tuple(prepared))
        if kind == "TmCommit":
            return replace(s, tm_state=TM_COMMITTED, msgs=s.msgs | {MSG_COMMIT})
        if kind == "TmAbort":
            return replace(s, tm_state=TM_ABORTED, msgs=s.msgs | {MSG_ABORT})
        rm = action[1]
        rm_state = list(s.rm_state)
        if kind == "RmPrepare":
            rm_state[rm] = PREPARED
            return replace(s, rm_state=tuple(rm_state), msgs=s.msgs | {rm})
        if kind == "RmChooseToAbort":
            rm_state[rm] = ABORTED
        elif kind == "RmRcvCommitMsg":
            rm_state[rm] = COMMITTED
        elif kind == "RmRcvAbortMsg":
            rm_state[rm] = ABORTED
        return replace(s, rm_state=tuple(rm_state))

    def properties(self) -> List[Property]:
        return [
            Property.sometimes(
                "abort agreement",
                lambda _m, s: all(r == ABORTED for r in s.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda _m, s: all(r == COMMITTED for r in s.rm_state),
            ),
            Property.always(
                "consistent",
                lambda _m, s: not (
                    ABORTED in s.rm_state and COMMITTED in s.rm_state
                ),
            ),
        ]


class TwoPhaseTensor(TensorModel):
    """TPU-native dense encoding of two-phase commit.

    State layout (3 uint32 lanes, N RMs <= 16):
      lane 0: tm_state (2 bits)
      lane 1: bits [2i, 2i+1] = rm_state[i]; bits 16+i not used
      lane 2: bit i = Prepared{i} in msgs; bit 29 = tm_prepared bitmask is
              folded into lane 0 bits [2+i]; bit 30 = Commit, bit 31 = Abort

    Concretely: lane0 = tm_state | (tm_prepared_mask << 2);
                lane1 = packed 2-bit rm states;
                lane2 = prepared_msgs_mask | commit_bit<<30 | abort_bit<<31.

    Actions (A = 2 + 5N): slot 0 TmCommit, slot 1 TmAbort, then for each rm:
    TmRcvPrepared, RmPrepare, RmChooseToAbort, RmRcvCommitMsg, RmRcvAbortMsg.
    """

    state_width = 3

    def __init__(self, rm_count: int):
        if rm_count > 16:
            raise ValueError("TwoPhaseTensor supports up to 16 RMs")
        self.n = rm_count
        self.max_actions = 2 + 5 * rm_count

    def init_states_array(self) -> np.ndarray:
        return np.zeros((1, 3), dtype=np.uint32)

    # -- lane helpers (work under numpy and jax.numpy) ----------------------

    @staticmethod
    def _tm_state(xp, lane0):
        return lane0 & xp.uint32(3)

    def _prepared_mask(self, xp, lane0):
        return (lane0 >> xp.uint32(2)) & xp.uint32((1 << self.n) - 1)

    @staticmethod
    def _rm_state(xp, lane1, rm: int):
        return (lane1 >> xp.uint32(2 * rm)) & xp.uint32(3)

    def step_lanes(self, xp, lanes):
        n = self.n
        u = xp.uint32
        lane0, lane1, lane2 = lanes
        tm = self._tm_state(xp, lane0)
        prep_mask = self._prepared_mask(xp, lane0)
        all_prepared = prep_mask == u((1 << n) - 1)
        tm_init = tm == u(TM_INIT)
        has_commit = (lane2 >> u(30)) & u(1)
        has_abort = (lane2 >> u(31)) & u(1)

        succs = []
        masks = []

        # slot 0: TmCommit
        succs.append(
            (
                (lane0 & ~u(3)) | u(TM_COMMITTED),
                lane1,
                lane2 | (u(1) << u(30)),
            )
        )
        masks.append(tm_init & all_prepared)

        # slot 1: TmAbort
        succs.append(
            (
                (lane0 & ~u(3)) | u(TM_ABORTED),
                lane1,
                lane2 | (u(1) << u(31)),
            )
        )
        masks.append(tm_init)

        for rm in range(n):
            rm_working = self._rm_state(xp, lane1, rm) == u(WORKING)
            prepared_msg = ((lane2 >> u(rm)) & u(1)) == u(1)
            rm_shift = u(2 * rm)
            rm_clear = ~(u(3) << rm_shift)

            # TmRcvPrepared(rm)
            succs.append((lane0 | (u(1) << u(2 + rm)), lane1, lane2))
            masks.append(tm_init & prepared_msg)

            # RmPrepare(rm)
            succs.append(
                (
                    lane0,
                    (lane1 & rm_clear) | (u(PREPARED) << rm_shift),
                    lane2 | (u(1) << u(rm)),
                )
            )
            masks.append(rm_working)

            # RmChooseToAbort(rm)
            succs.append(
                (
                    lane0,
                    (lane1 & rm_clear) | (u(ABORTED) << rm_shift),
                    lane2,
                )
            )
            masks.append(rm_working)

            # RmRcvCommitMsg(rm)
            succs.append(
                (
                    lane0,
                    (lane1 & rm_clear) | (u(COMMITTED) << rm_shift),
                    lane2,
                )
            )
            masks.append(has_commit == u(1))

            # RmRcvAbortMsg(rm)
            succs.append(
                (
                    lane0,
                    (lane1 & rm_clear) | (u(ABORTED) << rm_shift),
                    lane2,
                )
            )
            masks.append(has_abort == u(1))

        return succs, masks

    def representative_lanes(self, xp, lanes):
        """Batched RM-permutation canonicalization (examples/2pc.rs:203-229;
        device analogue of TwoPhaseState.representative).

        Each RM i is one descriptor word rm_state(2b) | i(4b) | prep(1b) |
        msg(1b); an odd-even transposition network sorts the N descriptors
        per state. The original index sits directly below the sort key, so
        ties between equal rm_states preserve original order — exactly the
        host's stable sort — and the carried prep/msg bits never influence
        the order. All elementwise min/max: no gathers, no argsort.

        Count semantics (measured, 2pc-5): this canonicalizer is IMPERFECT
        (the reference's own rule — ties between equal rm_states are not
        canonicalized over prep/msg), so the symmetry-reduced unique count
        is traversal-defined: reference DFS = 665 (expand-original,
        dedup-by-rep, DFS order; examples/2pc.rs:168, matched by our host
        DFS), an expand-original BFS = 508, and the device engine's
        canonical CLOSURE (expand representatives — the only
        order-independent definition a batched BFS admits) = 1,092.
        Every variant soundly covers the same equivalence classes and
        yields identical property verdicts.
        """
        n = self.n
        u = xp.uint32
        lane0, lane1, lane2 = lanes
        descs = []
        for i in range(n):
            rm = (lane1 >> u(2 * i)) & u(3)
            prep = (lane0 >> u(2 + i)) & u(1)
            msg = (lane2 >> u(i)) & u(1)
            descs.append((rm << u(6)) | u(i << 2) | (prep << u(1)) | msg)
        for p in range(n):
            for m in range(p & 1, n - 1, 2):
                lo = xp.minimum(descs[m], descs[m + 1])
                hi = xp.maximum(descs[m], descs[m + 1])
                descs[m] = lo
                descs[m + 1] = hi
        new0 = lane0 & u(3)  # tm_state
        new1 = lane1 & ~u((1 << (2 * n)) - 1)
        new2 = lane2 & ~u((1 << n) - 1)  # keep Commit/Abort bits
        for j, d in enumerate(descs):
            rm = (d >> u(6)) & u(3)
            prep = (d >> u(1)) & u(1)
            msg = d & u(1)
            new0 = new0 | (prep << u(2 + j))
            new1 = new1 | (rm << u(2 * j))
            new2 = new2 | (msg << u(j))
        return (new0, new1, new2)

    def tensor_properties(self) -> List[TensorProperty]:
        n = self.n

        def rm_states(xp, lanes):
            lane1 = lanes[1]
            return [
                (lane1 >> xp.uint32(2 * rm)) & xp.uint32(3) for rm in range(n)
            ]

        def abort_agreement(xp, lanes):
            rs = rm_states(xp, lanes)
            acc = rs[0] == xp.uint32(ABORTED)
            for r in rs[1:]:
                acc = acc & (r == xp.uint32(ABORTED))
            return acc

        def commit_agreement(xp, lanes):
            rs = rm_states(xp, lanes)
            acc = rs[0] == xp.uint32(COMMITTED)
            for r in rs[1:]:
                acc = acc & (r == xp.uint32(COMMITTED))
            return acc

        def consistent(xp, lanes):
            rs = rm_states(xp, lanes)
            any_abort = rs[0] == xp.uint32(ABORTED)
            any_commit = rs[0] == xp.uint32(COMMITTED)
            for r in rs[1:]:
                any_abort = any_abort | (r == xp.uint32(ABORTED))
                any_commit = any_commit | (r == xp.uint32(COMMITTED))
            return ~(any_abort & any_commit)

        return [
            TensorProperty.sometimes("abort agreement", abort_agreement),
            TensorProperty.sometimes("commit agreement", commit_agreement),
            TensorProperty.always("consistent", consistent),
        ]

    def format_action(self, a: int) -> str:
        if a == 0:
            return "TmCommit"
        if a == 1:
            return "TmAbort"
        rm, kind = divmod(a - 2, 5)
        return [
            f"TmRcvPrepared({rm})",
            f"RmPrepare({rm})",
            f"RmChooseToAbort({rm})",
            f"RmRcvCommitMsg({rm})",
            f"RmRcvAbortMsg({rm})",
        ][kind]

    def decode_state(self, row) -> dict:
        lane0, lane1, lane2 = (int(v) for v in row)
        names = {0: "Working", 1: "Prepared", 2: "Committed", 3: "Aborted"}
        return {
            "tm_state": {0: "Init", 1: "Committed", 2: "Aborted"}[lane0 & 3],
            "tm_prepared": [(lane0 >> (2 + i)) & 1 == 1 for i in range(self.n)],
            "rm_state": [names[(lane1 >> (2 * i)) & 3] for i in range(self.n)],
            "msgs": sorted(
                [f"Prepared({i})" for i in range(self.n) if (lane2 >> i) & 1]
                + (["Commit"] if (lane2 >> 30) & 1 else [])
                + (["Abort"] if (lane2 >> 31) & 1 else [])
            ),
        }
