"""Single Decree Paxos as a TPU-native TensorModel.

The device twin of `examples/paxos.py` (reference: examples/paxos.rs): the
whole actor system — three Paxos servers, `c` register clients, the
unordered non-duplicating network, AND the linearizability tester carried
as the model's history variable — is encoded into fixed uint32 lanes, and
one `step_lanes` evaluates every Deliver action as pure elementwise lane
arithmetic (no reductions, no gathers: quorum counts are 3-bit popcounts,
ballot comparison is integer comparison on a (round<<2|proposer) packing,
and the sorted network multiset is maintained with shift/insert passes).

State identity matches the host `ActorModel` exactly — including the
tester: each client's thread history is determined by its phase
(write-in-flight / read-in-flight / done), the value its read returned,
and the per-peer completed-op counts snapshotted when its read was
invoked (the tester's real-time edges, linearizability.rs:55-66). All of
those are lanes here, so unique-state counts agree with the host model
(16,668 at 2 clients / 3 servers, examples/paxos.rs:327).

BOTH properties run on device: "value chosen" (sometimes) scans the net
for a value-carrying GetOk, and "linearizable" (always) evaluates the
register-linearizability verdict per state as a closed-form lane program
(write-precedence digraph acyclicity — see `linearizable_lanes`), matching
the host model's backtracking-tester verdict (examples/paxos.rs:282-284
parity; oracle-validated in tests/test_paxos_linearizable.py).

Lane layout (S = 6 + c + K lanes, K = 7*c network slots):
  lanes 0..5   server j: [2j] packed core, [2j+1] prepares map
  lanes 6..6+c-1 client i: phase | read value | real-time counters
  remaining K  network: sorted envelope words, 0 = empty (zeros first)
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..lanes import (
    ActorNetModel,
    decode_register_clients,
    register_client_deliver,
    register_linearizable_lanes,
)
from ..tensor import TensorProperty

# Message types (nonzero so an envelope word is never 0).
PUT, GET, PUTOK, GETOK, PREPARE, PREPARED, ACCEPT, ACCEPTED, DECIDED = range(1, 10)

_PAY_MASK = (1 << 20) - 1

# 4-bit actor ids support 3 servers + up to 7 clients (the round-3 3-bit
# packing capped clients at 5, below the reference bench's `paxos check 6`
# workload — bench.sh:31). The widest payload is Prepared's 14 bits,
# comfortably inside the shared 20-bit field (lanes.env_word layout).
from ..lanes import env_word as _env


def _pop3(xp, bits):
    u = xp.uint32
    return (bits & u(1)) + ((bits >> u(1)) & u(1)) + ((bits >> u(2)) & u(1))


class PaxosTensor(ActorNetModel):
    """Device twin of paxos_model(client_count, 3). See module docstring."""

    def __init__(self, client_count: int, server_count: int = 3):
        if server_count != 3:
            raise ValueError("PaxosTensor supports exactly 3 servers")
        if client_count > 7:
            # 4-bit actor ids and 3-bit term rounds both cap out at 7
            # clients — enough for the reference bench's `paxos check 6`.
            raise ValueError("PaxosTensor supports at most 7 clients")
        self.c = client_count
        self.n_servers = 3
        # Bound on simultaneously in-flight messages. Each client keeps at
        # most ONE client-protocol message outstanding (Put/PutOk/Get/GetOk
        # are strict request-response), and term-protocol messages proceed
        # in rounds with at most two broadcast copies plus superseded-term
        # stragglers in flight. Measured maxima over the FULL reachable
        # space: 5 at c=1, 10 at c=2 (5 per client); K = 7c adds a 40%
        # margin, and the "network within capacity" always-property turns
        # any violation into a loud counterexample (rounds 1-3 used 14c:
        # ~1.7x the state width and 4x the net-update arithmetic for
        # nothing).
        self.K = 7 * client_count
        self.n_actor_lanes = 6 + client_count
        self._net_base = self.n_actor_lanes

    # -- init ---------------------------------------------------------------

    def init_states_array(self) -> np.ndarray:
        # on_start: client 3+i sends Put to server (3+i) % 3; the tester's
        # write invocations all carry empty completed-maps (nothing has
        # completed yet), so they need no lanes.
        return self.pack_init_row(
            [],
            [
                (PUT << 28) | ((3 + i) << 24) | ((i % 3) << 20)
                for i in range(self.c)
            ],
        )

    # -- the batched deliver step -------------------------------------------
    #
    # step_lanes is inherited from ActorNetModel: one [K*B]-wide delivery
    # handler + batched sorted-multiset network update (the O(K) XLA
    # program that makes paxos-3 compilable).

    def deliver(self, xp, actor_lanes, env):
        new_lanes, m1, m2, m3, changed = self._deliver(xp, actor_lanes, env)
        return new_lanes, [m1, m2, m3], changed

    def _deliver(self, xp, lanes, env):
        """One batched delivery: `lanes` are the NA actor lanes (any width),
        `env` the envelope words. Returns (new actor lanes, send1..3,
        changed)."""
        u = xp.uint32
        c = self.c
        occ = env != u(0)
        typ = env >> u(28)
        src = (env >> u(24)) & u(15)
        dst = (env >> u(20)) & u(15)
        pay = env & u(_PAY_MASK)

        new_lanes = list(lanes)
        changed = occ & False
        sends = []  # per handler: up to 3 envelope words (0 = no send)

        # --- server handlers -------------------------------------
        for j in range(3):
            cond = occ & (dst == u(j))
            a = lanes[2 * j]
            pl = lanes[2 * j + 1]
            ballot = a & u(31)
            prop = (a >> u(5)) & u(7)
            accepts = (a >> u(8)) & u(7)
            acc_pres = (a >> u(11)) & u(1)
            acc_ballot = (a >> u(12)) & u(31)
            acc_prop = (a >> u(17)) & u(7)
            decided = ((a >> u(20)) & u(1)) == u(1)
            mb = pay & u(31)
            peers = [s for s in range(3) if s != j]

            # Get on a decided server: reply with the accepted value
            # (paxos.rs:146-151). No state change.
            b_dget = cond & decided & (typ == u(GET))
            dget_send = _env(
                xp, GETOK, u(j) + (src & u(0)), src, u(1) + acc_prop
            )

            live = cond & ~decided

            # Put on a proposal-less server: start a term
            # (paxos.rs:160-174).
            b_put = live & (typ == u(PUT)) & (prop == u(0))
            nb_ballot = (((ballot >> u(2)) + u(1)) << u(2)) | u(j)
            put_a = (
                nb_ballot
                | ((u(1) + src - u(3)) << u(5))  # proposal = client code
                | (acc_pres << u(11))
                | (acc_ballot << u(12))
                | (acc_prop << u(17))
            )
            # prepares := {(self, accepted)}: only slot j populated.
            put_pl = (
                u(1) | (acc_pres << u(1)) | (acc_ballot << u(2))
                | (acc_prop << u(7))
            ) << u(10 * j)
            put_sends = [
                _env(xp, PREPARE, u(j) + (src & u(0)), u(p) + (src & u(0)), nb_ballot)
                for p in peers
            ]

            # Prepare with a higher ballot: adopt + reply Prepared
            # (paxos.rs:141-145).
            b_prep = live & (typ == u(PREPARE)) & (ballot < mb)
            prep_a = (a & ~u(31)) | mb
            prep_pay = (
                mb | (acc_pres << u(5)) | (acc_ballot << u(6))
                | (acc_prop << u(11))
            )
            prep_send = _env(xp, PREPARED, u(j) + (src & u(0)), src, prep_pay)

            # Prepared for the current ballot: record; on quorum pick the
            # best accepted proposal and broadcast Accept
            # (paxos.rs:147-166).
            b_prd = live & (typ == u(PREPARED)) & (mb == ballot)
            la_pres = (pay >> u(5)) & u(1)
            la_ballot = (pay >> u(6)) & u(31)
            la_prop = (pay >> u(11)) & u(7)
            entry = (
                u(1) | (la_pres << u(1)) | (la_ballot << u(2))
                | (la_prop << u(7))
            )
            # Insert into the src slot of the prepares map.
            npl = pl
            for s in range(3):
                sl = u(10 * s)
                npl = xp.where(
                    b_prd & (src == u(s)),
                    (npl & ~(u(0x3FF) << sl)) | (entry << sl),
                    npl,
                )
            inmap = (
                ((npl >> u(0)) & u(1))
                + ((npl >> u(10)) & u(1))
                + ((npl >> u(20)) & u(1))
            )
            quorum_p = inmap == u(2)  # majority(3) = 2
            # Best accepted entry across in-map slots: key packs
            # (value-present, ballot, proposal) so integer max ==
            # the host's lexicographic max (None sorts lowest).
            best = u(0) * a
            for s in range(3):
                sl = u(10 * s)
                s_in = (npl >> sl) & u(1)
                s_vp = (npl >> (sl + u(1))) & u(1)
                s_b = (npl >> (sl + u(2))) & u(31)
                s_pr = (npl >> (sl + u(7))) & u(7)
                key = xp.where(
                    s_in == u(1),
                    u(1) + ((s_vp << u(8)) | (s_b << u(3)) | s_pr),
                    u(0) * a,
                )
                best = xp.where(key > best, key, best)
            best_vp = ((best - u(1)) >> u(8)) & u(1)
            best_prop = (best - u(1)) & u(7)
            q_prop = xp.where(best_vp == u(1), best_prop, prop)
            prd_a_quorum = (
                ballot
                | (q_prop << u(5))
                | (u(1 << j) << u(8))  # accepts = {self}
                | (u(1) << u(11))  # accepted = (ballot, q_prop)
                | (ballot << u(12))
                | (q_prop << u(17))
            )
            prd_a = xp.where(b_prd & quorum_p, prd_a_quorum, a)
            acc_pay = ballot | (q_prop << u(5))
            prd_sends = [
                _env(
                    xp, ACCEPT, u(j) + (src & u(0)), u(p) + (src & u(0)),
                    acc_pay,
                )
                for p in peers
            ]

            # Accept with ballot >= ours: adopt + reply Accepted
            # (paxos.rs:168-174).
            b_acc = live & (typ == u(ACCEPT)) & (ballot <= mb)
            acc_prop_in = (pay >> u(5)) & u(7)
            acc_a = (
                mb
                | (prop << u(5))
                | (accepts << u(8))
                | (u(1) << u(11))
                | (mb << u(12))
                | (acc_prop_in << u(17))
            )
            acc_send = _env(xp, ACCEPTED, u(j) + (src & u(0)), src, mb)

            # Accepted for the current ballot: count; on quorum decide,
            # broadcast Decided, and ack the requester
            # (paxos.rs:176-187).
            b_acd = live & (typ == u(ACCEPTED)) & (mb == ballot)
            nacc = accepts | (u(1) << src)
            quorum_a = _pop3(xp, nacc) == u(2)
            acd_a = xp.where(
                b_acd & quorum_a,
                (a & ~(u(7) << u(8))) | (nacc << u(8)) | (u(1) << u(20)),
                (a & ~(u(7) << u(8))) | (nacc << u(8)),
            )
            dec_pay = ballot | (prop << u(5))
            requester = u(3) + prop - u(1)
            acd_sends = [
                _env(
                    xp, DECIDED, u(j) + (src & u(0)), u(p) + (src & u(0)),
                    dec_pay,
                )
                for p in peers
            ] + [_env(xp, PUTOK, u(j) + (src & u(0)), requester, u(0) * a)]

            # Decided: adopt unconditionally (paxos.rs:189-195).
            b_dec = live & (typ == u(DECIDED))
            dec_prop_in = (pay >> u(5)) & u(7)
            dec_a = (
                mb
                | (prop << u(5))
                | (accepts << u(8))
                | (u(1) << u(11))
                | (mb << u(12))
                | (dec_prop_in << u(17))
                | (u(1) << u(20))
            )

            # Merge this server's branches into the successor lanes.
            na = a
            na = xp.where(b_put, put_a, na)
            na = xp.where(b_prep, prep_a, na)
            na = xp.where(b_prd, prd_a, na)
            na = xp.where(b_acc, acc_a, na)
            na = xp.where(b_acd, acd_a, na)
            na = xp.where(b_dec, dec_a, na)
            npl_out = xp.where(b_put, put_pl, xp.where(b_prd, npl, pl))
            new_lanes[2 * j] = na
            new_lanes[2 * j + 1] = npl_out
            chg = b_put | b_prep | b_prd | b_acc | b_acd | b_dec
            changed = changed | chg

            zero = u(0) * a
            s1 = zero
            s2 = zero
            s3 = zero
            s1 = xp.where(b_dget, dget_send, s1)
            s1 = xp.where(b_put, put_sends[0], s1)
            s2 = xp.where(b_put, put_sends[1], s2)
            s1 = xp.where(b_prep, prep_send, s1)
            s1 = xp.where(b_prd & quorum_p, prd_sends[0], s1)
            s2 = xp.where(b_prd & quorum_p, prd_sends[1], s2)
            s1 = xp.where(b_acc, acc_send, s1)
            s1 = xp.where(b_acd & quorum_a, acd_sends[0], s1)
            s2 = xp.where(b_acd & quorum_a, acd_sends[1], s2)
            s3 = xp.where(b_acd & quorum_a, acd_sends[2], s3)
            sends.append((s1, s2, s3))

        # --- client handlers (toolkit RegisterClient lane program) ----
        client_lanes = [lanes[6 + j] for j in range(c)]
        for i in range(c):
            cid = 3 + i
            cond = occ & (dst == u(cid))
            get_send = _env(
                xp, GET, u(cid) + (src & u(0)),
                u((cid + 1) % 3) + (src & u(0)), u(0) * env,
            )
            ncl, send, chg = register_client_deliver(
                xp,
                client_lanes,
                i,
                cond & (typ == u(PUTOK)),
                cond & (typ == u(GETOK)),
                pay,
                get_send,
            )
            new_lanes[6 + i] = ncl
            changed = changed | chg
            zero = u(0) * env
            sends.append((send, zero, zero))

        # Exactly one handler fires per delivery (dst is unique), so the
        # per-handler send words OR together.
        m1 = sends[0][0]
        m2 = sends[0][1]
        m3 = sends[0][2]
        for s1, s2, s3 in sends[1:]:
            m1 = m1 | s1
            m2 = m2 | s2
            m3 = m3 | s3
        return new_lanes, m1, m2, m3, changed

    # -- properties ---------------------------------------------------------

    def linearizable_lanes(self, xp, lanes):
        """Register-linearizability verdict — the shared closed-form lane
        program (see lanes.register_linearizable_lanes for the reduction
        and its oracle validation)."""
        return register_linearizable_lanes(
            xp, [lanes[6 + i] for i in range(self.c)]
        )

    def tensor_properties(self) -> List[TensorProperty]:
        NB = self._net_base
        K = self.K

        def value_chosen(xp, lanes):
            u = xp.uint32
            acc = lanes[NB] != lanes[NB]  # all-false, varying
            for m in range(K):
                env = lanes[NB + m]
                is_gok = (env >> u(28)) == u(GETOK)
                val = env & u(15)  # GetOk payload: 1 = None, 2+k = value k
                acc = acc | (is_gok & (val != u(1)))
            return acc

        def ballot_rounds_in_range(xp, lanes):
            # The 3-bit term-round packing caps rounds at 7; a server
            # incrementing past that would silently wrap and MERGE
            # distinct states. Like the net-capacity guard, this turns an
            # encoding-bound violation into a loud counterexample instead
            # of a silently wrong unique count (relevant from c=4 up,
            # where deeper election races could push rounds higher).
            u = xp.uint32
            acc = lanes[0] == lanes[0]  # all-true, varying
            for j in range(3):
                a = lanes[2 * j]
                acc = acc & (((a & u(31)) >> u(2)) < u(7))
                acc = acc & ((((a >> u(12)) & u(31)) >> u(2)) < u(7))
            return acc

        return [
            TensorProperty.always("linearizable", self.linearizable_lanes),
            TensorProperty.sometimes("value chosen", value_chosen),
            self.net_capacity_property(),
            TensorProperty.always(
                "ballot rounds within range", ballot_rounds_in_range
            ),
        ]

    # -- display ------------------------------------------------------------

    def decode_state(self, row) -> dict:
        names = dict(
            zip(
                range(1, 10),
                "Put Get PutOk GetOk Prepare Prepared Accept Accepted Decided".split(),
            )
        )
        net = []
        for m in range(self.K):
            env = int(row[self._net_base + m])
            if env:
                net.append(
                    f"{names[env >> 28]}({(env >> 24) & 15}->{(env >> 20) & 15},"
                    f" pay={env & _PAY_MASK:#x})"
                )
        servers = []
        for j in range(3):
            a = int(row[2 * j])
            servers.append(
                {
                    "ballot": (a & 31) >> 2,
                    "proposer": a & 3,
                    "proposal": (a >> 5) & 7,
                    "accepts": (a >> 8) & 7,
                    "accepted": ((a >> 12) & 31, (a >> 17) & 7)
                    if (a >> 11) & 1
                    else None,
                    "decided": bool((a >> 20) & 1),
                }
            )
        clients = decode_register_clients(row, 6, self.c)
        return {"servers": servers, "clients": clients, "net": net}


class PaxosTensorExhaustive(PaxosTensor):
    """Compatibility alias from rounds 1-3.

    Historically PaxosTensor lacked the "linearizable" always-property on
    device, so exhaustive runs needed an extra never-satisfied blocker
    here. Now that "linearizable" is evaluated on device (never violated,
    so the default finish_when=ALL explores to exhaustion exactly like the
    host model), the base class already has the right behavior.
    """
