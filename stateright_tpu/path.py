"""Counterexample/example traces through a model's state space.

Reference: `Path` at src/checker/path.rs. A path is a sequence
`state --action--> state ... --action--> state`. Engines store only
fingerprints; `Path.from_fingerprints` re-executes the model along the
fingerprint chain to recover states and actions (the TLC technique cited at
src/checker/bfs.rs:389-393).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple


class PathReconstructionError(RuntimeError):
    pass


_NONDETERMINISM_HINT = (
    "This usually happens when the model varies across calls given identical "
    "inputs — e.g. it reads untracked external state or iterates a container "
    "with nondeterministic order."
)


def _verdict(value) -> str:
    if value is None:
        return "?"
    return "true" if value else "FALSE"


def _state_fields(model, state) -> dict:
    """Named-field view of a state for diffing (values repr'd, so records
    stay JSON-serializable for the Explorer).

    Tensor-backed states decode through the model's `decode_state` (the
    human view the Explorer already uses); rich states decompose via
    dataclass/namedtuple/dict/sequence structure; anything else reports
    as one opaque field.
    """
    import dataclasses

    tm = getattr(model, "tm", None)
    if tm is not None and hasattr(tm, "decode_state"):
        try:
            import numpy as np

            state = tm.decode_state(np.asarray(state, dtype=np.uint32))
        except Exception:
            pass  # fall through to the generic decomposition
    if dataclasses.is_dataclass(state) and not isinstance(state, type):
        return {k: repr(v) for k, v in vars(state).items()}
    if hasattr(state, "_asdict"):  # namedtuple
        return {k: repr(v) for k, v in state._asdict().items()}
    if isinstance(state, dict):
        return {str(k): repr(v) for k, v in state.items()}
    if isinstance(state, (tuple, list)):
        return {f"[{i}]": repr(v) for i, v in enumerate(state)}
    return {"state": repr(state)}


def _diff_fields(old: dict, new: dict) -> dict:
    """Field -> [old, new] for every field whose value changed."""
    out = {}
    for key in list(old) + [k for k in new if k not in old]:
        a = old.get(key)
        b = new.get(key)
        if a != b:
            out[key] = [a, b]
    return out


class Path:
    """A list of (state, Optional[action]) pairs; the final pair has action None."""

    def __init__(self, pairs: List[Tuple[Any, Optional[Any]]]):
        if not pairs:
            raise ValueError("empty path is invalid")
        self._pairs = pairs

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_fingerprints(model, fingerprints: Sequence[int]) -> "Path":
        """Re-execute `model` along a fingerprint chain. Reference: path.rs:20-97."""
        fps = list(fingerprints)
        if not fps:
            raise PathReconstructionError("empty path is invalid")
        init_print = fps[0]
        last_state = None
        for s in model.init_states():
            if model.fingerprint_state(s) == init_print:
                last_state = s
                break
        if last_state is None:
            avail = [model.fingerprint_state(s) for s in model.init_states()]
            raise PathReconstructionError(
                f"No init state has the expected fingerprint ({init_print}). "
                f"{_NONDETERMINISM_HINT} Available init fingerprints: {avail}"
            )
        pairs: List[Tuple[Any, Optional[Any]]] = []
        for next_fp in fps[1:]:
            found = None
            for action, next_state in model.next_steps(last_state):
                if model.fingerprint_state(next_state) == next_fp:
                    found = (action, next_state)
                    break
            if found is None:
                avail = [
                    model.fingerprint_state(s) for s in model.next_states(last_state)
                ]
                raise PathReconstructionError(
                    f"{1 + len(pairs)} previous state(s) reconstructed, but no "
                    f"successor has the next fingerprint ({next_fp}). "
                    f"{_NONDETERMINISM_HINT} Available next fingerprints: {avail}"
                )
            action, next_state = found
            pairs.append((last_state, action))
            last_state = next_state
        pairs.append((last_state, None))
        return Path(pairs)

    @staticmethod
    def from_actions(model, init_state, actions) -> Optional["Path"]:
        """Build a path from an init state and an action sequence.

        Returns None if unreachable. Reference: path.rs:101-131.
        """
        if not any(s == init_state for s in model.init_states()):
            return None
        pairs: List[Tuple[Any, Optional[Any]]] = []
        prev_state = init_state
        for action in actions:
            found = None
            for a, next_state in model.next_steps(prev_state):
                if a == action:
                    found = (a, next_state)
                    break
            if found is None:
                return None
            pairs.append((prev_state, found[0]))
            prev_state = found[1]
        pairs.append((prev_state, None))
        return Path(pairs)

    @staticmethod
    def final_state(model, fingerprints: Sequence[int]) -> Optional[Any]:
        """Final state of a fingerprint path, or None. Reference: path.rs:134-165."""
        fps = list(fingerprints)
        if not fps:
            return None
        state = None
        for s in model.init_states():
            if model.fingerprint_state(s) == fps[0]:
                state = s
                break
        if state is None:
            return None
        for next_fp in fps[1:]:
            nxt = None
            for s in model.next_states(state):
                if model.fingerprint_state(s) == next_fp:
                    nxt = s
                    break
            if nxt is None:
                return None
            state = nxt
        return state

    # -- forensics -----------------------------------------------------------

    def explain_steps(self, model) -> List[dict]:
        """Per-step forensic records for this path (the data behind
        `explain()` and the Explorer's path-detail view).

        Each record describes one transition: the action taken, the
        FIELD-LEVEL state diff (only what changed), and which property
        predicates flipped across the step — so an "EVENTUALLY violated,
        14-step path" reads as a narrative instead of a state dump. The
        leading record (step 0) is the initial state with every property's
        starting verdict. Property evaluation is best-effort: a predicate
        that raises on some state reports as "?" rather than killing the
        report.
        """
        props = list(model.properties())
        pairs = self._pairs

        def prop_vals(state):
            vals = {}
            for p in props:
                try:
                    vals[p.name] = bool(p.condition(model, state))
                except Exception:
                    vals[p.name] = None
            return vals

        prev_vals = prop_vals(pairs[0][0])
        out: List[dict] = [
            {
                "step": 0,
                "action": None,
                "state": _state_fields(model, pairs[0][0]),
                "changes": {},
                "properties": dict(prev_vals),
                "property_flips": {},
            }
        ]
        for i in range(1, len(pairs)):
            prev_state, action = pairs[i - 1]
            state = pairs[i][0]
            vals = prop_vals(state)
            flips = {
                name: [prev_vals[name], vals[name]]
                for name in vals
                if vals[name] != prev_vals[name]
            }
            out.append(
                {
                    "step": i,
                    "action": model.format_action(action),
                    "state": _state_fields(model, state),
                    "changes": _diff_fields(
                        _state_fields(model, prev_state),
                        _state_fields(model, state),
                    ),
                    "properties": dict(vals),
                    "property_flips": flips,
                }
            )
            prev_vals = vals
        return out

    def explain(self, model) -> str:
        """Human-readable per-step narrative of this path: action taken,
        field-level state diff, and property-predicate flips. Used by
        `WriteReporter` when printing discoveries and by the Explorer's
        path-detail view."""
        steps = self.explain_steps(model)
        lines = [f"Path[{len(self)}] explained:"]
        first = steps[0]
        init_desc = ", ".join(f"{k}={v}" for k, v in first["state"].items())
        lines.append(f"  init: {init_desc}")
        start = ", ".join(
            f"{name}={_verdict(v)}" for name, v in first["properties"].items()
        )
        if start:
            lines.append(f"  properties: {start}")
        for rec in steps[1:]:
            lines.append(f"  {rec['step']}. {rec['action']}")
            for field, (old, new) in rec["changes"].items():
                lines.append(f"       {field}: {old} -> {new}")
            if not rec["changes"]:
                lines.append("       (no field-level change)")
            for name, (old, new) in rec["property_flips"].items():
                lines.append(
                    f"       ~ property {name!r}: "
                    f"{_verdict(old)} -> {_verdict(new)}"
                )
        return "\n".join(lines) + "\n"

    # -- accessors ----------------------------------------------------------

    def last_state(self) -> Any:
        return self._pairs[-1][0]

    def into_states(self) -> List[Any]:
        return [s for s, _a in self._pairs]

    def into_actions(self) -> List[Any]:
        return [a for _s, a in self._pairs if a is not None]

    def into_vec(self) -> List[Tuple[Any, Optional[Any]]]:
        return list(self._pairs)

    def encode(self, model) -> str:
        """Fingerprint-path string "fp/fp/fp". Reference: path.rs:189-198."""
        return "/".join(str(model.fingerprint_state(s)) for s, _a in self._pairs)

    # -- dunder -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._pairs) - 1  # number of steps, like Path[n] display

    def _key(self) -> tuple:
        # Canonical bytes keep __eq__/__hash__ consistent even for states
        # whose == is structural but whose repr varies (e.g. dict insertion
        # order); falls back to repr for states our encoder can't handle.
        from .fingerprint import canonical_bytes

        def enc(v):
            try:
                return canonical_bytes(v)
            except TypeError:
                return repr(v)

        return tuple((enc(s), enc(a)) for s, a in self._pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self) -> str:
        return f"Path(steps={len(self)}, last_state={self._pairs[-1][0]!r})"

    def __str__(self) -> str:
        """Reference display format: path.rs:207-221."""
        lines = [f"Path[{len(self)}]:"]
        for _state, action in self._pairs:
            if action is not None:
                lines.append(f"- {action!r}")
        return "\n".join(lines) + "\n"
