"""TraceRecorder: the `spawn(..., record=path)` hook, engine-agnostic.

Both spawn engines call the same three methods:

  `attach(actors, engine)`      once, before any handler runs — learns the
                                deployment roster, builds the id->index
                                map, writes the ``meta`` line
  `record_handler(...)`         after every handler (on_start / on_msg /
                                on_timeout / on_random), with the
                                post-handler state and the handler's `Out`
  `record_fault(...)`           from the fault injector, at decision time

Writing is the obs/trace.py discipline: thread-safe, one flushed JSONL
line per event, writes after `close()` silently dropped. A handler event
and its command children are written under one lock acquisition, so they
are adjacent in the file and the trace is causally ordered: an actor's
``send`` line precedes the wire datagram, which precedes the receiver's
``deliver`` line.

Sequence numbers are per-actor and monotonic from 0; command events
consume sequence numbers too and name their parent via ``cause``.

Schema v2 (v1 traces still load — the stamps below are additive):

  - every handler/command event carries a per-actor Lamport clock ``lc``
    (commands tick the clock; a deliver takes ``max(local, send lc) + 1``);
  - a matched ``deliver`` names its send as ``sent_by: [actor, seq]``
    (duplicated datagrams re-match the consumed send, ``redelivery``);
  - handler events carry ``dur`` (handler execution seconds) when the
    engine measured it;
  - the meta line carries the deployment's ``faults`` plan (seed +
    probabilities) when an injector was attached, so a fault schedule is
    replayable from the trace alone.

The send/deliver matching here is the same FIFO-per-(src, dst, msg-key)
discipline `obs.netobs.assign_lamport` replays offline; the recorder
additionally feeds delivery latency and per-actor in-flight depth into
the deployment's `NetObs` when one is attached.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .events import command_views, jsonable

TRACE_VERSION = 2


class TraceRecorder:
    """Records one deployment's events as JSONL (see conformance/README.md)."""

    def __init__(self, path: str, netobs=None):
        self.path = os.fspath(path)
        self.netobs = netobs  # obs.netobs.NetObs or None
        self._lock = threading.Lock()
        self._f = open(self.path, "w", encoding="utf-8")
        self._seqs: List[int] = []
        self._clocks: List[int] = []
        self._id_map: Dict[int, int] = {}
        self._attached = False
        # FIFO of recorded-but-undelivered sends per (src, dst, msg) key,
        # the consumed entry kept for duplicate re-matching, and per-actor
        # in-flight depth (sends addressed to it, not yet delivered).
        self._pending: Dict[tuple, deque] = {}
        self._consumed: Dict[tuple, dict] = {}
        self._outstanding: Dict[int, int] = {}

    # -- engine hooks --------------------------------------------------------

    def attach(self, actors, engine: str, plan=None) -> None:
        """Register the deployment roster: `actors` is the spawn-resolved
        list of (Id, Actor) pairs, in model-index order. `plan` is the
        deployment's `FaultPlan`, recorded in the meta line when given."""
        roster = []
        for index, (id, actor) in enumerate(actors):
            self._id_map[int(id)] = index
            ip = int(id) >> 16
            addr = ".".join(str((ip >> s) & 0xFF) for s in (24, 16, 8, 0))
            roster.append(
                {
                    "index": index,
                    "id": int(id),
                    "addr": f"{addr}:{int(id) & 0xFFFF}",
                    "actor": type(actor).__name__,
                }
            )
        self._seqs = [0] * len(roster)
        self._clocks = [0] * len(roster)
        self._attached = True
        meta: Dict[str, Any] = {
            "kind": "meta",
            "v": TRACE_VERSION,
            "engine": engine,
            "ts": time.time(),
            "actors": roster,
        }
        if plan is not None:
            meta["faults"] = dataclasses.asdict(plan)
        self._write(meta)

    def record_handler(
        self,
        index: int,
        kind: str,
        state: Any,
        out,
        *,
        src: Optional[int] = None,
        msg: Any = None,
        timer: Any = None,
        value: Any = None,
        duration: Optional[float] = None,
    ) -> None:
        """One handler execution: `kind` is init/deliver/timeout/random,
        `state` the post-handler actor state, `out` the handler's Out."""
        now = time.time()
        main: Dict[str, Any] = {
            "kind": kind,
            "actor": index,
            "ts": now,
            "state": jsonable(state, self._id_map),
        }
        if kind == "deliver":
            main["src"] = self._map_id(src)
            main["msg"] = jsonable(msg, self._id_map)
        elif kind == "timeout":
            main["timer"] = jsonable(timer, self._id_map)
        elif kind == "random":
            main["value"] = jsonable(value, self._id_map)
        if duration is not None:
            main["dur"] = round(float(duration), 6)
        children = command_views(out.commands, self._id_map) if out else []
        latency: Optional[float] = None
        outstanding: Optional[Dict[int, int]] = None
        with self._lock:
            if self._f.closed:
                return
            seq = self._next_seq(index)
            main["seq"] = seq
            entry = None
            if kind == "deliver":
                key = (main["src"], index, json.dumps(main["msg"], sort_keys=True))
                queue = self._pending.get(key)
                if queue:
                    entry = queue.popleft()
                    self._consumed[key] = entry
                    self._outstanding[index] = self._outstanding.get(index, 0) - 1
                    latency = now - entry["ts"]
                else:
                    entry = self._consumed.get(key)
                    if entry is not None:
                        main["redelivery"] = True
            if entry is not None:
                lc = max(self._clock(index), entry["lc"]) + 1
                main["sent_by"] = [entry["actor"], entry["seq"]]
            else:
                lc = self._clock(index) + 1
            self._clocks[index] = lc
            main["lc"] = lc
            self._write_locked(main)
            for view in children:
                lc = self._clock(index) + 1
                self._clocks[index] = lc
                child: Dict[str, Any] = {
                    "kind": view[0],
                    "actor": index,
                    "seq": self._next_seq(index),
                    "cause": seq,
                    "ts": now,
                    "lc": lc,
                }
                if view[0] == "send":
                    child["dst"] = view[1]
                    child["msg"] = view[2]
                    key = (index, view[1], json.dumps(view[2], sort_keys=True))
                    self._pending.setdefault(key, deque()).append(
                        {"actor": index, "seq": child["seq"], "lc": lc, "ts": now}
                    )
                    if isinstance(view[1], int):
                        self._outstanding[view[1]] = (
                            self._outstanding.get(view[1], 0) + 1
                        )
                elif view[0] in ("timer_set", "timer_cancel"):
                    child["timer"] = view[1]
                elif view[0] == "choose":
                    child["key"] = view[1]
                    child["choices"] = view[2]
                self._write_locked(child)
            self._f.flush()
            if self.netobs is not None:
                outstanding = {
                    k: v for k, v in self._outstanding.items() if v > 0
                }
        if self.netobs is not None:
            if latency is not None:
                self.netobs.latency(latency)
            if outstanding is not None:
                self.netobs.mailbox(outstanding)

    def record_fault(
        self,
        index: int,
        fault: str,
        dst: int,
        link_seq: int,
        delay: Optional[float] = None,
        seed_key: Optional[str] = None,
    ) -> None:
        """One fault-injector decision on the `index` actor's outgoing link
        to `dst` (the link's `link_seq`-th datagram). `seed_key` is the
        injector's per-(src, dst, n) RNG key, recorded so the schedule is
        replayable from the trace alone."""
        record: Dict[str, Any] = {
            "kind": "fault",
            "actor": index,
            "fault": fault,
            "dst": self._map_id(dst),
            "link_seq": int(link_seq),
            "ts": time.time(),
        }
        if delay is not None:
            record["delay"] = round(float(delay), 6)
        if seed_key is not None:
            record["seed_key"] = seed_key
        self._write(record)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    # -- internals -----------------------------------------------------------

    def _map_id(self, raw) -> int:
        iv = int(raw)
        return self._id_map.get(iv, iv)

    def _next_seq(self, index: int) -> int:
        while index >= len(self._seqs):  # defensive vs. late attach
            self._seqs.append(0)
        seq = self._seqs[index]
        self._seqs[index] = seq + 1
        return seq

    def _clock(self, index: int) -> int:
        while index >= len(self._clocks):  # defensive vs. late attach
            self._clocks.append(0)
        return self._clocks[index]

    def _write(self, record: dict) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._write_locked(record)
            self._f.flush()

    def _write_locked(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")


def as_recorder(record) -> Optional[TraceRecorder]:
    """Normalize `spawn`'s ``record=`` argument: None, a path, or an
    already-built TraceRecorder."""
    if record is None or isinstance(record, TraceRecorder):
        return record
    return TraceRecorder(record)
