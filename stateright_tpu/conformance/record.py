"""TraceRecorder: the `spawn(..., record=path)` hook, engine-agnostic.

Both spawn engines call the same three methods:

  `attach(actors, engine)`      once, before any handler runs — learns the
                                deployment roster, builds the id->index
                                map, writes the ``meta`` line
  `record_handler(...)`         after every handler (on_start / on_msg /
                                on_timeout / on_random), with the
                                post-handler state and the handler's `Out`
  `record_fault(...)`           from the fault injector, at decision time

Writing is the obs/trace.py discipline: thread-safe, one flushed JSONL
line per event, writes after `close()` silently dropped. A handler event
and its command children are written under one lock acquisition, so they
are adjacent in the file and the trace is causally ordered: an actor's
``send`` line precedes the wire datagram, which precedes the receiver's
``deliver`` line.

Sequence numbers are per-actor and monotonic from 0; command events
consume sequence numbers too and name their parent via ``cause``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .events import command_views, jsonable


class TraceRecorder:
    """Records one deployment's events as JSONL (see conformance/README.md)."""

    def __init__(self, path: str):
        self.path = os.fspath(path)
        self._lock = threading.Lock()
        self._f = open(self.path, "w", encoding="utf-8")
        self._seqs: List[int] = []
        self._id_map: Dict[int, int] = {}
        self._attached = False

    # -- engine hooks --------------------------------------------------------

    def attach(self, actors, engine: str) -> None:
        """Register the deployment roster: `actors` is the spawn-resolved
        list of (Id, Actor) pairs, in model-index order."""
        roster = []
        for index, (id, actor) in enumerate(actors):
            self._id_map[int(id)] = index
            ip = int(id) >> 16
            addr = ".".join(str((ip >> s) & 0xFF for s in (24, 16, 8, 0)))
            roster.append(
                {
                    "index": index,
                    "id": int(id),
                    "addr": f"{addr}:{int(id) & 0xFFFF}",
                    "actor": type(actor).__name__,
                }
            )
        self._seqs = [0] * len(roster)
        self._attached = True
        self._write(
            {
                "kind": "meta",
                "v": 1,
                "engine": engine,
                "ts": time.time(),
                "actors": roster,
            }
        )

    def record_handler(
        self,
        index: int,
        kind: str,
        state: Any,
        out,
        *,
        src: Optional[int] = None,
        msg: Any = None,
        timer: Any = None,
        value: Any = None,
    ) -> None:
        """One handler execution: `kind` is init/deliver/timeout/random,
        `state` the post-handler actor state, `out` the handler's Out."""
        now = time.time()
        main: Dict[str, Any] = {
            "kind": kind,
            "actor": index,
            "ts": now,
            "state": jsonable(state, self._id_map),
        }
        if kind == "deliver":
            main["src"] = self._map_id(src)
            main["msg"] = jsonable(msg, self._id_map)
        elif kind == "timeout":
            main["timer"] = jsonable(timer, self._id_map)
        elif kind == "random":
            main["value"] = jsonable(value, self._id_map)
        children = command_views(out.commands, self._id_map) if out else []
        with self._lock:
            if self._f.closed:
                return
            seq = self._next_seq(index)
            main["seq"] = seq
            self._write_locked(main)
            for view in children:
                child: Dict[str, Any] = {
                    "kind": view[0],
                    "actor": index,
                    "seq": self._next_seq(index),
                    "cause": seq,
                    "ts": now,
                }
                if view[0] == "send":
                    child["dst"] = view[1]
                    child["msg"] = view[2]
                elif view[0] in ("timer_set", "timer_cancel"):
                    child["timer"] = view[1]
                elif view[0] == "choose":
                    child["key"] = view[1]
                    child["choices"] = view[2]
                self._write_locked(child)
            self._f.flush()

    def record_fault(
        self,
        index: int,
        fault: str,
        dst: int,
        link_seq: int,
        delay: Optional[float] = None,
    ) -> None:
        """One fault-injector decision on the `index` actor's outgoing link
        to `dst` (the link's `link_seq`-th datagram)."""
        record: Dict[str, Any] = {
            "kind": "fault",
            "actor": index,
            "fault": fault,
            "dst": self._map_id(dst),
            "link_seq": int(link_seq),
            "ts": time.time(),
        }
        if delay is not None:
            record["delay"] = round(float(delay), 6)
        self._write(record)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    # -- internals -----------------------------------------------------------

    def _map_id(self, raw) -> int:
        iv = int(raw)
        return self._id_map.get(iv, iv)

    def _next_seq(self, index: int) -> int:
        while index >= len(self._seqs):  # defensive vs. late attach
            self._seqs.append(0)
        seq = self._seqs[index]
        self._seqs[index] = seq + 1
        return seq

    def _write(self, record: dict) -> None:
        with self._lock:
            if self._f.closed:
                return
            self._write_locked(record)
            self._f.flush()

    def _write_locked(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")


def as_recorder(record) -> Optional[TraceRecorder]:
    """Normalize `spawn`'s ``record=`` argument: None, a path, or an
    already-built TraceRecorder."""
    if record is None or isinstance(record, TraceRecorder):
        return record
    return TraceRecorder(record)
