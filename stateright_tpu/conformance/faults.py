"""Deterministic fault injection for the spawn socket layer.

A `FaultPlan` is a seeded, *pure* policy: the fate of the n-th datagram
on the (src, dst) link is a function of (seed, src, dst, n) alone —
independent of wall-clock timing or thread interleaving — so the same
plan replays the same drop/duplicate/delay/reorder schedule run after
run (locked by tests/test_conformance.py). The fault kinds mirror what
`actor/network.py` lets the model claim to tolerate:

  drop       the datagram never reaches the socket (lossy network)
  duplicate  sent twice back-to-back (duplicating network)
  delay      sent after a seeded pause (unordered network)
  reorder    held until the link's next datagram has been sent
             (unordered network; a 0.2s failsafe flush bounds the hold
             when the link goes quiet)

`FaultInjector` wraps an engine's raw send callable. Both engines route
every outgoing datagram through `transmit(src, dst, payload, send)`;
the injector applies the plan's decision and records it as a ``fault``
TraceEvent. Delayed/held sends fire from a single scheduler thread —
safe because both engines' send paths are thread-safe (`socket.sendto`,
and `srn_send` which no-ops after `srn_stop`).
"""

from __future__ import annotations

import dataclasses
import heapq
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

FAULT_KINDS = ("drop", "duplicate", "delay", "reorder", "deliver")

_REORDER_FLUSH_SECS = 0.2


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    """What the plan decided for one datagram."""

    kind: str  # one of FAULT_KINDS
    delay: float = 0.0  # seconds; only meaningful for kind == "delay"


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seeded per-datagram fault policy. Probabilities are independent
    slices of one uniform draw (so they must sum to <= 1); whatever is
    left delivers cleanly."""

    seed: int = 0
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    reorder: float = 0.0
    delay_range: Tuple[float, float] = (0.005, 0.05)

    def __post_init__(self):
        total = self.drop + self.duplicate + self.delay + self.reorder
        if total > 1.0 + 1e-9:
            raise ValueError(f"fault probabilities sum to {total} > 1")

    def decide(self, src: int, dst: int, n: int) -> FaultDecision:
        """The fate of the n-th datagram on the src->dst link. Pure."""
        rng = random.Random(f"{self.seed}|{int(src)}|{int(dst)}|{int(n)}")
        r = rng.random()
        edge = self.drop
        if r < edge:
            return FaultDecision("drop")
        edge += self.duplicate
        if r < edge:
            return FaultDecision("duplicate")
        edge += self.delay
        if r < edge:
            lo, hi = self.delay_range
            return FaultDecision("delay", delay=rng.uniform(lo, hi))
        edge += self.reorder
        if r < edge:
            return FaultDecision("reorder")
        return FaultDecision("deliver")

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the CLI's ``--faults SEED[,drop[,dup[,delay[,reorder]]]]``
        (e.g. ``--faults 7,0.05,0.1``). Omitted probabilities are 0."""
        parts = [p.strip() for p in str(spec).split(",")]
        try:
            seed = int(parts[0])
            probs = [float(p) for p in parts[1:5]]
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"bad fault spec {spec!r}; want SEED[,drop[,dup[,delay[,reorder]]]]"
            ) from e
        probs += [0.0] * (4 - len(probs))
        return cls(
            seed=seed,
            drop=probs[0],
            duplicate=probs[1],
            delay=probs[2],
            reorder=probs[3],
        )

    @classmethod
    def from_meta(cls, meta: dict) -> "FaultPlan":
        """Rebuild the plan a recorded trace ran under, from the schema-v2
        meta line's ``faults`` object — together with the fault lines'
        ``seed_key``s this makes a schedule replayable from the trace
        alone. Raises `ValueError` when the trace recorded no plan."""
        spec = meta.get("faults")
        if not isinstance(spec, dict):
            raise ValueError("trace meta carries no fault plan")
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in spec.items() if k in fields}
        if "delay_range" in kwargs:
            kwargs["delay_range"] = tuple(kwargs["delay_range"])
        return cls(**kwargs)


class FaultInjector:
    """Applies a `FaultPlan` to a deployment's outgoing datagrams."""

    def __init__(self, plan: FaultPlan, netobs=None):
        self.plan = plan
        self.netobs = netobs  # obs.netobs.NetObs or None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._counters: Dict[Tuple[int, int], int] = {}
        self._held: Dict[Tuple[int, int], List[tuple]] = {}
        self._heap: List[tuple] = []  # (due, tick, fire)
        self._tick = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- engine hook ---------------------------------------------------------

    def transmit(
        self,
        src: int,
        dst: int,
        payload: bytes,
        send: Callable[[bytes], None],
        recorder=None,
        actor_index: Optional[int] = None,
    ) -> None:
        """Route one outgoing datagram through the plan. `send` performs
        the actual wire send of a payload (engine-specific closure)."""
        link = (int(src), int(dst))
        with self._lock:
            if self._closed:
                return
            n = self._counters.get(link, 0)
            self._counters[link] = n + 1
        decision = self.plan.decide(link[0], link[1], n)
        if decision.kind != "deliver":
            # Counted and recorded at *injection* time, not check time: the
            # live fault_injected{kind=...} series and the trace's fault
            # line exist the moment the injector acts.
            if self.netobs is not None:
                self.netobs.fault(decision.kind)
            if recorder is not None and actor_index is not None:
                recorder.record_fault(
                    actor_index,
                    decision.kind,
                    dst,
                    n,
                    delay=decision.delay if decision.kind == "delay" else None,
                    seed_key=f"{self.plan.seed}|{link[0]}|{link[1]}|{n}",
                )
        if decision.kind == "reorder":
            with self._cond:
                if self._closed:
                    _safe_send(send, payload)
                    return
                self._held.setdefault(link, []).append((send, payload))
                self._push_locked(
                    time.monotonic() + _REORDER_FLUSH_SECS,
                    lambda: self._flush_held(link),
                )
            self._ensure_thread()
            return
        held = self._pop_held(link)
        if decision.kind == "drop":
            pass
        elif decision.kind == "duplicate":
            _safe_send(send, payload)
            _safe_send(send, payload)
        elif decision.kind == "delay":
            with self._cond:
                if self._closed:
                    _safe_send(send, payload)
                else:
                    self._push_locked(
                        time.monotonic() + decision.delay,
                        lambda: _safe_send(send, payload),
                    )
            self._ensure_thread()
        else:
            _safe_send(send, payload)
        # A held (reordered) datagram goes out AFTER its link's successor.
        for s, p in held:
            _safe_send(s, p)

    def close(self) -> None:
        """Flush everything still pending and stop the scheduler. Engines
        call this at shutdown before closing the recorder."""
        with self._cond:
            self._closed = True
            heap, self._heap = self._heap, []
            held, self._held = self._held, {}
            self._cond.notify_all()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=1.0)
        for entries in held.values():
            for s, p in entries:
                _safe_send(s, p)
        for _due, _tick, fire in sorted(heap):
            try:
                fire()
            except Exception:
                pass

    # -- internals -----------------------------------------------------------

    def _pop_held(self, link) -> List[tuple]:
        with self._lock:
            return self._held.pop(link, [])

    def _flush_held(self, link) -> None:
        for s, p in self._pop_held(link):
            _safe_send(s, p)

    def _push_locked(self, due: float, fire: Callable[[], None]) -> None:
        heapq.heappush(self._heap, (due, self._tick, fire))
        self._tick += 1
        self._cond.notify_all()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None and not self._closed:
                self._thread = threading.Thread(
                    target=self._scheduler, name="fault-injector", daemon=True
                )
                self._thread.start()

    def _scheduler(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                if not self._heap:
                    self._cond.wait(0.5)
                    continue
                due = self._heap[0][0]
                now = time.monotonic()
                if due > now:
                    self._cond.wait(min(due - now, 0.5))
                    continue
                _due, _tick, fire = heapq.heappop(self._heap)
            try:
                fire()
            except Exception:
                pass


def _safe_send(send: Callable[[bytes], None], payload: bytes) -> None:
    try:
        send(payload)
    except Exception:
        pass  # sockets may already be closing at shutdown


def as_injector(faults) -> Optional[FaultInjector]:
    """Normalize `spawn`'s ``faults=`` argument: None, a FaultPlan, a
    spec string, or an already-built FaultInjector."""
    if faults is None or isinstance(faults, FaultInjector):
        return faults
    if isinstance(faults, FaultPlan):
        return FaultInjector(faults)
    if isinstance(faults, str):
        return FaultInjector(FaultPlan.from_spec(faults))
    raise TypeError(f"faults must be a FaultPlan, spec string, or FaultInjector; got {faults!r}")
