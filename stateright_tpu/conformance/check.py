"""The conformance checker: replay a recorded trace against the model.

`check_trace(model, trace)` walks the trace's handler events in wire
order and matches each against the `ActorModel` transition relation:

  - a ``deliver`` must correspond to an enabled `Deliver` action — a
    deliverable envelope with the same src/dst/payload; a ``timeout`` to
    an armed model timer; a ``random`` to a pending `SelectRandom`;
  - the commands the deployment emitted (the event's ``send`` /
    ``timer_set`` / ... children) must equal the commands the model's
    handler emits for that step;
  - the recorded post-handler actor state must equal the model's.

Some real-world events are legitimate *stutters* — steps the model
prunes from its graph but that its semantics explain: a no-op delivery
the model collapses (`is_no_op`, e.g. a duplicated datagram hitting an
idempotent handler), or a timeout whose only effect is re-arming itself
(`is_no_op_with_timer`). These count as ``stutters``, not divergences.

Everything else is a `Divergence`:

  ``unexplained-deliver``   delivered message matches no deliverable model
                            envelope, and replaying it is not a no-op
  ``unexplained-timeout``   fired timer is not armed in the model state
  ``unexplained-random``    resolved value matches no pending choice
  ``command-mismatch``      deployment sent/armed something the model's
                            handler would not (or vice versa)
  ``state-mismatch``        post-handler state differs from the model's —
                            reported with a field-level diff and a
                            `Path.explain` narrative of the steps leading
                            up to it
  ``decode-error``          a recorded payload no decoder recognizes

Divergence-free means: the deployment's observed behavior is a path
through (a stuttering extension of) the model's state graph.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..actor.base import Out, is_no_op
from ..actor.ids import Id
from ..actor.model import Deliver, SelectRandom, Timeout
from ..obs.metrics import MetricsRegistry
from ..obs.netobs import assign_lamport, causal_past, format_event
from ..path import Path
from .events import TraceError, command_views, jsonable, load_trace


@dataclasses.dataclass
class Divergence:
    """One point where the deployment left the model's behavior."""

    kind: str
    actor: int
    seq: int
    message: str
    diff: Dict[str, list] = dataclasses.field(default_factory=dict)
    narrative: str = ""
    causal_past: List[str] = dataclasses.field(default_factory=list)

    def format(self) -> str:
        lines = [f"[{self.kind}] actor={self.actor} seq={self.seq}: {self.message}"]
        for field, pair in self.diff.items():
            lines.append(f"    {field}: model={pair[0]!r} trace={pair[1]!r}")
        if self.causal_past:
            lines.append("    causal past (events that happened-before this one):")
            for ln in self.causal_past:
                lines.append(f"      {ln}")
        if self.narrative:
            lines.append("    model-side steps leading here:")
            for ln in self.narrative.rstrip("\n").splitlines():
                lines.append(f"      {ln}")
        return "\n".join(lines)


@dataclasses.dataclass
class ConformanceReport:
    """The verdict of one `check_trace` run."""

    events: int = 0
    steps: int = 0
    stutters: int = 0
    faults: int = 0
    boundary_exits: int = 0
    divergences: List[Divergence] = dataclasses.field(default_factory=list)
    truncated: bool = False
    history: Any = None
    meta: dict = dataclasses.field(default_factory=dict)
    final_state: Any = None

    @property
    def ok(self) -> bool:
        return not self.divergences

    def format(self) -> str:
        verdict = "OK" if self.ok else f"DIVERGED ({len(self.divergences)})"
        lines = [
            f"conformance: {verdict} — {self.events} events, "
            f"{self.steps} model steps, {self.stutters} stutters, "
            f"{self.faults} injected faults, "
            f"{self.boundary_exits} boundary exits"
        ]
        for d in self.divergences:
            lines.append("  " + d.format().replace("\n", "\n  "))
        if self.truncated:
            lines.append("  ... divergence list truncated")
        return "\n".join(lines) + "\n"

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "events": self.events,
            "steps": self.steps,
            "stutters": self.stutters,
            "faults": self.faults,
            "boundary_exits": self.boundary_exits,
            "truncated": self.truncated,
            "divergences": [dataclasses.asdict(d) for d in self.divergences],
        }


def check_trace(
    model,
    trace,
    decode=None,
    metrics: Optional[MetricsRegistry] = None,
    max_divergences: int = 25,
    keep_steps: int = 8,
) -> ConformanceReport:
    """Replay `trace` (a path, or a `load_trace` result) against `model`.

    `decode` (from `make_decoder`) lets the checker re-execute handlers on
    recorded payloads that match no in-flight model envelope, to tell a
    harmless redelivery stutter from a genuinely unexplained message.
    `metrics` (created if None) receives the ``conformance_*`` counters.
    """
    if isinstance(trace, (str, bytes)) or hasattr(trace, "__fspath__"):
        meta, events = load_trace(trace)
    else:
        meta, events = trace
    if metrics is None:
        metrics = MetricsRegistry()
    roster = meta.get("actors", [])
    if len(roster) != len(model.actors):
        raise TraceError(
            f"trace has {len(roster)} actors but the model has "
            f"{len(model.actors)} — not the same system"
        )

    report = ConformanceReport(meta=meta)
    cur = model.init_states()[0]
    recent: deque = deque(maxlen=keep_steps)
    children: Dict[Tuple[int, int], List[dict]] = {}
    for ev in events:
        if "cause" in ev:
            children.setdefault((ev["actor"], ev["cause"]), []).append(ev)
    # Deterministic Lamport stamping (netobs recomputes even on v2 traces,
    # so a hand-edited or v1 trace still gets a causal past).
    stamped = assign_lamport(events)

    def diverge(kind, ev, message, diff=None, narrative=""):
        if len(report.divergences) >= max_divergences:
            report.truncated = True
            return
        past: List[str] = []
        if "actor" in ev and "seq" in ev:
            try:
                past = [
                    format_event(p)
                    for p in causal_past(
                        stamped, ev["actor"], ev["seq"], k=keep_steps
                    )
                ]
            except Exception:
                past = []
        report.divergences.append(
            Divergence(
                kind=kind,
                actor=ev.get("actor", -1),
                seq=ev.get("seq", -1),
                message=message,
                diff=diff or {},
                narrative=narrative,
                causal_past=past,
            )
        )

    def narrate() -> str:
        try:
            return Path(list(recent) + [(cur, None)]).explain(model)
        except Exception:
            return ""

    def check_children(ev, out) -> None:
        expected = command_views(out.commands)
        actual = [
            _child_view(c) for c in children.get((ev["actor"], ev["seq"]), [])
        ]
        if expected != actual:
            diverge(
                "command-mismatch",
                ev,
                f"{ev['kind']} handler commands differ",
                diff={"commands": [expected, actual]},
                narrative=narrate(),
            )

    def check_state(ev) -> None:
        index = ev["actor"]
        model_enc = jsonable(cur.actor_states[index])
        if model_enc != ev["state"]:
            diff = _json_diff(cur.actor_states[index], model_enc, ev["state"])
            diverge(
                "state-mismatch",
                ev,
                f"actor {index} post-{ev['kind']} state differs from the model",
                diff=diff,
                narrative=narrate(),
            )

    for ev in events:
        kind = ev["kind"]
        if kind == "fault":
            report.faults += 1
            metrics.inc_labeled("conformance_fault_kinds", ev.get("fault", "?"))
            continue
        if "cause" in ev:  # command child; handled with its parent
            continue
        report.events += 1
        index = ev["actor"]

        if kind == "init":
            out = Out()
            try:
                model.actors[index].on_start(Id(index), out)
            except Exception as e:
                diverge("unexplained-deliver", ev, f"on_start replay raised: {e!r}")
                continue
            check_children(ev, out)
            check_state(ev)
            continue

        if kind == "deliver":
            env = None
            for cand in cur.network.iter_deliverable():
                if (
                    int(cand.dst) == index
                    and int(cand.src) == ev.get("src")
                    and jsonable(cand.msg) == ev["msg"]
                ):
                    env = cand
                    break
            if env is not None:
                action = Deliver(env.src, env.dst, env.msg)
                out = Out()
                try:
                    model.actors[index].on_msg(
                        env.dst, cur.actor_states[index], env.src, env.msg, out
                    )
                    nxt = model.next_state(cur, action)
                except Exception as e:
                    diverge("unexplained-deliver", ev, f"on_msg replay raised: {e!r}")
                    continue
                check_children(ev, out)
                if nxt is None:
                    report.stutters += 1  # model prunes this no-op delivery
                else:
                    recent.append((cur, action))
                    cur = nxt
                    report.steps += 1
                check_state(ev)
                continue
            # No matching in-flight envelope. Replay the payload: a no-op
            # redelivery (duplicate/late datagram) is a stutter; anything
            # with an effect is a message the model cannot explain.
            replayed = False
            if decode is not None:
                try:
                    msg = decode(ev["msg"])
                except Exception as e:
                    diverge("decode-error", ev, f"cannot decode payload: {e!r}")
                    continue
                out = Out()
                try:
                    returned = model.actors[index].on_msg(
                        Id(index),
                        cur.actor_states[index],
                        Id(ev.get("src", 0)),
                        msg,
                        out,
                    )
                    replayed = True
                except Exception:
                    replayed = False
                if replayed and is_no_op(returned, out):
                    report.stutters += 1
                    check_children(ev, out)
                    check_state(ev)
                    continue
            in_flight = [
                f"{int(e.src)}->{int(e.dst)}: {jsonable(e.msg)}"
                for e in cur.network.iter_deliverable()
            ]
            diverge(
                "unexplained-deliver",
                ev,
                f"delivered message {ev['msg']!r} from {ev.get('src')} matches "
                f"no deliverable model envelope (and is not a no-op "
                f"redelivery); deliverable now: {in_flight or 'none'}",
                narrative=narrate(),
            )
            continue

        if kind == "timeout":
            timer = None
            for cand in cur.timers_set[index]:
                if jsonable(cand) == ev["timer"]:
                    timer = cand
                    break
            if timer is None:
                diverge(
                    "unexplained-timeout",
                    ev,
                    f"timer {ev['timer']!r} fired but is not armed in the "
                    f"model (armed: {[jsonable(t) for t in cur.timers_set[index]]})",
                    narrative=narrate(),
                )
                continue
            action = Timeout(Id(index), timer)
            out = Out()
            try:
                model.actors[index].on_timeout(
                    Id(index), cur.actor_states[index], timer, out
                )
                nxt = model.next_state(cur, action)
            except Exception as e:
                diverge("unexplained-timeout", ev, f"on_timeout replay raised: {e!r}")
                continue
            check_children(ev, out)
            if nxt is None:
                report.stutters += 1  # pure re-arm, pruned by the model
            else:
                recent.append((cur, action))
                cur = nxt
                report.steps += 1
            check_state(ev)
            continue

        if kind == "random":
            action = None
            for key in sorted(cur.random_choices[index].map):
                for choice in cur.random_choices[index].map[key]:
                    if jsonable(choice) == ev["value"]:
                        action = SelectRandom(Id(index), key, choice)
                        break
                if action is not None:
                    break
            if action is None:
                diverge(
                    "unexplained-random",
                    ev,
                    f"random value {ev['value']!r} matches no pending choice",
                    narrative=narrate(),
                )
                continue
            out = Out()
            try:
                model.actors[index].on_random(
                    Id(index), cur.actor_states[index], action.random, out
                )
                nxt = model.next_state(cur, action)
            except Exception as e:
                diverge("unexplained-random", ev, f"on_random replay raised: {e!r}")
                continue
            check_children(ev, out)
            if nxt is not None:
                recent.append((cur, action))
                cur = nxt
                report.steps += 1
            check_state(ev)
            continue

        diverge("decode-error", ev, f"unknown TraceEvent kind {kind!r}")

    if not model.within_boundary(cur):
        report.boundary_exits += 1
    report.history = cur.history
    report.final_state = cur

    metrics.inc("conformance_events", report.events)
    metrics.inc("conformance_steps", report.steps)
    metrics.inc("conformance_stutters", report.stutters)
    metrics.inc("conformance_faults", report.faults)
    metrics.inc("conformance_divergences", len(report.divergences))
    try:
        metrics.set_gauge("conformance_history_ops", len(report.history))
    except TypeError:
        pass
    return report


# -- internals ----------------------------------------------------------------


def _child_view(ev: dict) -> list:
    kind = ev["kind"]
    if kind == "send":
        return ["send", ev.get("dst"), ev.get("msg")]
    if kind in ("timer_set", "timer_cancel"):
        return [kind, ev.get("timer")]
    if kind == "choose":
        return ["choose", ev.get("key"), ev.get("choices")]
    return [kind]


def _json_diff(obj: Any, a: Any, b: Any, prefix: str = "") -> Dict[str, list]:
    """Field-level diff of two canonical encodings of the same state.

    `obj` is the model-side value whose structure names the paths: a
    dataclass contributes ``TypeName.field`` segments, sequences ``[i]``.
    Returns path -> [model_encoding, trace_encoding] leaves.
    """
    if a == b:
        return {}
    if (
        dataclasses.is_dataclass(obj)
        and not isinstance(obj, type)
        and isinstance(a, list)
        and isinstance(b, list)
        and len(a) == len(b)
        and a[:1] == b[:1]
        and len(a) == 1 + len(dataclasses.fields(obj))
    ):
        out: Dict[str, list] = {}
        name = type(obj).__name__
        for i, f in enumerate(dataclasses.fields(obj), start=1):
            seg = f"{prefix}.{name}.{f.name}" if prefix else f"{name}.{f.name}"
            out.update(_json_diff(getattr(obj, f.name), a[i], b[i], seg))
        return out
    if (
        isinstance(obj, (list, tuple))
        and isinstance(a, list)
        and isinstance(b, list)
        and len(a) == len(b)
        and len(obj) == len(a)
    ):
        out = {}
        for i, sub in enumerate(obj):
            out.update(_json_diff(sub, a[i], b[i], f"{prefix}[{i}]"))
        return out
    return {prefix or "state": [a, b]}
