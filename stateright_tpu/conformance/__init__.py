"""Trace conformance: record real-network runs and check them against the model.

The dual-execution story's missing half. `spawn(..., record=path)` makes
both engines emit a JSONL `TraceEvent` stream (events.py / record.py);
`spawn(..., faults=FaultPlan(...))` fuzzes the deployment's links with a
seeded deterministic drop/duplicate/delay/reorder schedule (faults.py);
`check_trace(model, path)` replays the recording against the
`ActorModel` transition relation and reports divergences with
field-level forensics (check.py); `register_history` / `extract_history`
feed the recorded client operations through the semantics/ testers
(history.py). See conformance/README.md for the schema and the
divergence-kind catalog, and `examples/_cli.py` for the CLI surface
(``spawn --record/--faults`` and ``conform``).
"""

from .check import ConformanceReport, Divergence, check_trace
from .events import TraceError, jsonable, load_trace, make_decoder
from .faults import FaultDecision, FaultInjector, FaultPlan, as_injector
from .history import extract_history, register_history
from .record import TRACE_VERSION, TraceRecorder, as_recorder

__all__ = [
    "ConformanceReport",
    "Divergence",
    "FaultDecision",
    "FaultInjector",
    "FaultPlan",
    "TRACE_VERSION",
    "TraceError",
    "TraceRecorder",
    "as_injector",
    "as_recorder",
    "check_trace",
    "extract_history",
    "jsonable",
    "load_trace",
    "make_decoder",
    "register_history",
]
