"""TraceEvent schema: canonical JSON encoding of real-network runs.

A recorded trace is JSONL — one standalone JSON object per line, flushed
as written (the obs/trace.py discipline), so a killed deployment still
leaves a parseable prefix. Line one is the ``meta`` record naming the
deployment (engine, actor roster); every following line is one
`TraceEvent` (see conformance/README.md for the full catalog):

  handler events (carry ``actor``, per-actor monotonic ``seq``, ``ts``,
  and the actor's post-handler ``state``):

    ``init``     on_start ran
    ``deliver``  a datagram was deserialized and handled (``src``, ``msg``)
    ``timeout``  a timer fired (``timer``)
    ``random``   a pending random choice resolved (``value``)

  command events (children of the handler event named by ``cause``):

    ``send`` / ``timer_set`` / ``timer_cancel`` / ``choose``

  fault events (from conformance/faults.py): ``fault`` with the decision
  kind, link, and per-link sequence number.

Values are encoded with `jsonable`, an extension of the spawn wire
encoding (`actor/spawn.py:_to_jsonable`) that additionally handles
sets/frozensets and dicts (actor *states* contain them even though wire
messages may not) and — crucially — remaps deployment `Id`s back to
dense model indices. A deployment id packs (ip << 16) | port, so every
real id is >= 2**16; remapping by value therefore never collides with
legitimate small integers in the payload, and messages that embed actor
ids (ABD's ``seq=(clock, id)`` sequencers, requester ids, ...) compare
equal to their model-world counterparts.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..actor.base import CancelTimer, ChooseRandom, Send, SetTimer
from ..actor.spawn import _from_jsonable


class TraceError(Exception):
    """A trace file that cannot be parsed (not a divergence — a broken file)."""


def jsonable(value: Any, id_map: Optional[Dict[int, int]] = None):
    """Canonical JSON view of a message/state value.

    `id_map` maps deployment ids (as ints) to dense model indices; every
    int found in the map is remapped, wherever it is nested. Encoding
    rules beyond `_to_jsonable`: set/frozenset -> ``{"set": [...]}`` with
    deterministically sorted elements, dict -> ``{"map": [[k, v], ...]}``
    sorted by key, unknown objects -> ``{"repr": "..."}``. JSON objects
    never arise from the wire encoding, so these wrappers are unambiguous.
    """
    if isinstance(value, bool) or value is None or isinstance(value, (float, str)):
        return value
    if isinstance(value, int):
        iv = int(value)  # normalizes Id subclasses
        if id_map and iv in id_map:
            return id_map[iv]
        return iv
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return [type(value).__name__] + [
            jsonable(getattr(value, f.name), id_map)
            for f in dataclasses.fields(value)
        ]
    if isinstance(value, (list, tuple)):
        return [jsonable(v, id_map) for v in value]
    if isinstance(value, (set, frozenset)):
        encoded = [jsonable(v, id_map) for v in value]
        encoded.sort(key=lambda e: json.dumps(e, sort_keys=True))
        return {"set": encoded}
    if isinstance(value, dict):
        pairs = [
            [jsonable(k, id_map), jsonable(v, id_map)] for k, v in value.items()
        ]
        pairs.sort(key=lambda kv: json.dumps(kv[0], sort_keys=True))
        return {"map": pairs}
    return {"repr": repr(value)}


def command_views(commands, id_map: Optional[Dict[int, int]] = None) -> List[list]:
    """The comparable view of an `Out`'s commands — the exact shape the
    recorder emits as command events, so trace children and model replay
    compare with ``==``. Timer durations are deliberately excluded: they
    are real-world scheduling detail the model abstracts away."""
    views: List[list] = []
    for cmd in commands:
        if isinstance(cmd, Send):
            dst = int(cmd.dst)
            if id_map and dst in id_map:
                dst = id_map[dst]
            views.append(["send", dst, jsonable(cmd.msg, id_map)])
        elif isinstance(cmd, SetTimer):
            views.append(["timer_set", jsonable(cmd.timer, id_map)])
        elif isinstance(cmd, CancelTimer):
            views.append(["timer_cancel", jsonable(cmd.timer, id_map)])
        elif isinstance(cmd, ChooseRandom):
            views.append(
                ["choose", cmd.key, [jsonable(c, id_map) for c in cmd.choices]]
            )
    return views


def make_decoder(*message_types) -> Callable[[Any], Any]:
    """Jsonable -> model-domain message, recognizing ["TypeName", ...] for
    the given dataclass types (the conformance-side twin of
    `make_json_deserializer`; JSON lists decode to tuples for the same
    reason)."""
    by_name = {t.__name__: t for t in message_types}

    def decode(value: Any) -> Any:
        return _from_jsonable(value, by_name)

    return decode


HANDLER_KINDS = ("init", "deliver", "timeout", "random")
COMMAND_KINDS = ("send", "timer_set", "timer_cancel", "choose")


def load_trace(path: str) -> Tuple[dict, List[dict]]:
    """Parse a recorded JSONL trace into ``(meta, events)``.

    Raises `TraceError` on malformed JSON or a missing/invalid meta line.
    A trailing partial line (killed deployment) is tolerated.
    """
    meta: Optional[dict] = None
    events: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise TraceError(f"cannot read trace {path!r}: {e}") from e
    for lineno, line in enumerate(lines, start=1):
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError as e:
            if lineno == len(lines):
                break  # torn final line: the deployment was killed mid-write
            raise TraceError(f"{path}:{lineno}: malformed JSON: {e}") from e
        if not isinstance(record, dict) or "kind" not in record:
            raise TraceError(f"{path}:{lineno}: not a TraceEvent object")
        if record["kind"] == "meta":
            if meta is not None:
                raise TraceError(f"{path}:{lineno}: duplicate meta record")
            meta = record
        else:
            events.append(record)
    if meta is None:
        raise TraceError(f"{path}: missing meta record (is this a trace file?)")
    return meta, events
