"""Operation histories from recorded traces -> semantics/ testers.

A recorded trace sees operations from the *client's* perspective: an
operation is invoked when the client actor first puts its request on the
wire (a ``send`` event) and returns when the matching response reaches it
(a ``deliver`` event). That framing is what makes histories valid under
injected faults and retries:

  - a *retransmission* of an in-flight request is not a second invoke
    (the tester would poison the history on a double in-flight op);
  - a *duplicated* or *stale* response is not a second return (only the
    response matching the currently in-flight request id counts).

`extract_history` is the generic driver; `register_history` instantiates
it for the Put/Get register protocol (actor/register.py clients over
any server — ABD, single-copy, ...) against the `semantics/` `Register`
sequential spec, yielding the same verdict machinery model checking uses
(`LinearizabilityTester.serialized_history()`), now for a real run.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..semantics.linearizability import LinearizabilityTester
from ..semantics.register import READ, WRITE_OK, ReadOk, Register, Write

# (request_id, operation) or None — "does this sent/delivered payload
# invoke/return a client operation?"
Matcher = Callable[[int, Any], Optional[Tuple[Any, Any]]]


def extract_history(
    events: List[dict],
    tester,
    invoke_of: Matcher,
    return_of: Matcher,
):
    """Feed a trace's client operations through a semantics/ tester.

    `invoke_of(actor, msg_jsonable)` maps a ``send`` payload to
    ``(request_id, op)`` when it invokes an operation; `return_of` maps a
    ``deliver`` payload to ``(request_id, ret)`` when it completes one.
    Thread id is the client actor's index. Returns the tester.
    """
    in_flight: Dict[int, Any] = {}  # actor index -> pending request id
    for ev in events:
        actor = ev.get("actor")
        if ev.get("kind") == "send":
            hit = invoke_of(actor, ev.get("msg"))
            if hit is None:
                continue
            rid, op = hit
            if actor in in_flight:
                continue  # retransmission of the in-flight op
            in_flight[actor] = rid
            tester.on_invoke(actor, op)
        elif ev.get("kind") == "deliver":
            hit = return_of(actor, ev.get("msg"))
            if hit is None:
                continue
            rid, ret = hit
            if in_flight.get(actor) != rid:
                continue  # duplicate or stale response
            del in_flight[actor]
            tester.on_return(actor, ret)
    return tester


def register_history(
    events: List[dict], tester=None
) -> "LinearizabilityTester":
    """History extraction for the Put/Get register protocol: client
    ``Put``/``Get`` sends invoke ``Write``/``Read``; ``PutOk``/``GetOk``
    deliveries return ``WriteOk``/``ReadOk``. Defaults to a fresh
    `LinearizabilityTester(Register(None))`; pass a
    `SequentialConsistencyTester` for the weaker verdict."""
    if tester is None:
        tester = LinearizabilityTester(Register(None))

    def invoke_of(actor, msg):
        if isinstance(msg, list) and len(msg) == 3 and msg[0] == "Put":
            return (msg[1], Write(msg[2]))
        if isinstance(msg, list) and len(msg) == 2 and msg[0] == "Get":
            return (msg[1], READ)
        return None

    def return_of(actor, msg):
        if isinstance(msg, list) and len(msg) == 2 and msg[0] == "PutOk":
            return (msg[1], WRITE_OK)
        if isinstance(msg, list) and len(msg) == 3 and msg[0] == "GetOk":
            return (msg[1], ReadOk(msg[2]))
        return None

    return extract_history(events, tester, invoke_of, return_of)
