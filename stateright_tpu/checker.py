"""CheckerBuilder / Checker: configure, launch, and query checking runs.

Reference: src/checker.rs:65-578. The builder carries the model plus options
(threads, symmetry, target_state_count, target_max_depth, finish_when,
timeout, visitor) and spawns one of the engines:

  - `spawn_bfs()`        host breadth-first search (engines/bfs.py)
  - `spawn_dfs()`        host depth-first search (engines/dfs.py)
  - `spawn_on_demand()`  lazy BFS for the Explorer (engines/on_demand.py)
  - `spawn_simulation()` seeded random walks (engines/simulation.py)
  - `spawn_tpu_bfs()`    the TPU-native batched frontier engine
                         (engines/tpu_bfs.py) — new in this framework
  - `serve()`            Explorer web service over an on-demand checker

`Checker` exposes state_count / unique_state_count / max_depth / discoveries
and assertion helpers, matching checker.rs:294-578.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .core import Expectation, Model
from .has_discoveries import HasDiscoveries
from .path import Path
from .report import ReportData, ReportDiscovery, Reporter
from .visitor import CheckerVisitor, as_visitor


class DiscoveryClassification:
    """Reference: checker.rs:39-53."""

    EXAMPLE = "example"
    COUNTEREXAMPLE = "counterexample"


class CheckerBuilder:
    """Fluent options builder. Reference: checker.rs:65-288."""

    def __init__(self, model: Model):
        self.model = model
        self.symmetry_fn_: Optional[Any] = None
        self.target_state_count_: Optional[int] = None
        self.target_max_depth_: Optional[int] = None
        self.thread_count_: int = 1
        self.visitor_: Optional[CheckerVisitor] = None
        self.finish_when_: HasDiscoveries = HasDiscoveries.ALL
        self.timeout_: Optional[float] = None
        self.trace_path_: Optional[str] = None
        self.trace_format_: str = "jsonl"
        self.profile_dir_: Optional[str] = None
        self.coverage_: bool = True
        self.stage_profile_: bool = False
        self.stage_profile_iters_: int = 32
        self.strict_: bool = False
        self.strict_samples_: int = 128
        self.lint_report_: Optional[Any] = None
        self.multiplex_lane_: bool = False
        self.span_recorder_: Optional[Any] = None
        self.span_trace_id_: Optional[str] = None
        self.span_parent_id_: Optional[str] = None
        self.flight_: bool = True
        self.flight_capacity_: int = 4096
        self.flight_path_: Optional[str] = None
        self.flight_format_: str = "jsonl"
        self.memory_: bool = True
        self.pipeline_: bool = True
        self.pipeline_depth_: Optional[int] = None  # None = auto (2)
        self.fuse_eras_: Optional[int] = None  # None/1 = no multi-era fusion
        self.sample_: bool = True
        self.sample_k_: int = 64  # obs/sample.py DEFAULT_SAMPLE_K

    # -- options ------------------------------------------------------------

    def symmetry(self) -> "CheckerBuilder":
        """Enable symmetry reduction via the state's `representative()` method.

        Reference: checker.rs:219-227.
        """
        return self.symmetry_fn(lambda state: state.representative())

    def symmetry_fn(self, representative) -> "CheckerBuilder":
        self.symmetry_fn_ = representative
        return self

    def finish_when(self, has_discoveries: HasDiscoveries) -> "CheckerBuilder":
        self.finish_when_ = has_discoveries
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        self.target_state_count_ = count if count > 0 else None
        return self

    def target_max_depth(self, depth: int) -> "CheckerBuilder":
        self.target_max_depth_ = depth if depth > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        """Worker count for engines that support parallel checking.

        With thread_count > 1, `spawn_bfs()` on a tensor-backed model runs
        the vectorized threaded host engine (engines/vbfs.py: numpy lane
        batches + the native concurrent visited set, reference
        job_market.rs role); rich host models raise there. The other host
        Python engines stay single-threaded and raise NotImplementedError
        rather than silently ignoring the setting. The device engine
        accepts any value (its parallelism is the data-parallel chunk, not
        worker threads).
        """
        self.thread_count_ = thread_count
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        self.visitor_ = as_visitor(visitor)
        return self

    def timeout(self, seconds: float) -> "CheckerBuilder":
        self.timeout_ = seconds
        return self

    def trace(self, path: str, format: str = "jsonl") -> "CheckerBuilder":
        """Stream one event per era/wave/round to `path` (obs/trace.py
        documents the event schema). Works with every engine.
        `format="jsonl"` (default) writes standalone JSON lines;
        `format="chrome"` writes Chrome trace-event JSON loadable in
        Perfetto / `chrome://tracing` (phase timers as duration events,
        eras/waves as instant events)."""
        from .obs.trace import TRACE_FORMATS

        if format not in TRACE_FORMATS:
            raise ValueError(
                f"unknown trace format {format!r}; available: {TRACE_FORMATS}"
            )
        self.trace_path_ = path
        self.trace_format_ = format
        return self

    def coverage(self, enable: bool = True) -> "CheckerBuilder":
        """Toggle coverage accounting (obs/coverage.py): per-action fire
        counts, the per-depth unique-state histogram, per-property
        evaluation/hit counts, and dead-action detection, surfaced via
        `Checker.coverage()`. On by default; device engines fold the
        histograms into their era loops, so disabling buys back only a
        few percent of throughput (bench.py records both numbers)."""
        self.coverage_ = enable
        return self

    def flight(
        self,
        enable: bool = True,
        capacity: int = 4096,
        path: Optional[str] = None,
        format: str = "jsonl",
    ) -> "CheckerBuilder":
        """Configure the era-granularity flight recorder (obs/flight.py):
        a bounded ring of per-era records — wall time split into
        ``device_era`` vs ``host_gap`` (the dispatch gap), states/frontier/
        table counters — populated from the packed-params readback the
        device engines already do once per era (zero extra round-trips;
        <2% overhead, asserted by bench.py). On by default with a
        `capacity`-record ring; `Checker.flight()` returns the records
        and ``telemetry()["flight"]`` the summary. `path` additionally
        exports the recording at run end — JSONL (`format="jsonl"`) or a
        standalone Chrome counter-track trace (`format="chrome"`); a run
        traced with ``.trace(p, format="chrome")`` also gets the counter
        tracks embedded into that trace automatically. Host engines
        ignore the recorder (they have no era dispatch gap to measure)."""
        if format not in ("jsonl", "chrome"):
            raise ValueError(
                f"unknown flight format {format!r}; available: jsonl, chrome"
            )
        self.flight_ = enable
        self.flight_capacity_ = max(1, int(capacity))
        self.flight_path_ = path
        self.flight_format_ = format
        return self

    def memory(self, enable: bool = True) -> "CheckerBuilder":
        """Toggle the device-memory ledger (obs/memory.py): exact
        per-component accounting of every device allocation (visited
        table, frontier queue, packed params, coverage slab, spill
        staging) plus the per-era growth forecaster that projects
        eras-to-grow / eras-to-exhaustion and fires a one-shot pressure
        warning. On by default; the records ride the flight recorder's
        existing once-per-era readback (zero extra device round-trips,
        <1% overhead asserted by bench.py). Surfaced via
        ``telemetry()["memory"]``, ``memory_bytes{component=...}``
        Prometheus gauges, and the Explorer's ``GET /memory``."""
        self.memory_ = enable
        return self

    def sample(self, enable: bool = True, k: int = 64) -> "CheckerBuilder":
        """Configure the space profiler (obs/sample.py): deterministic
        bottom-k fingerprint sampling of the explored state space. A
        state is sampled iff its 64-bit fingerprint is among the `k`
        smallest seen, so the sample set is a pure function of the
        explored set — identical across engines (host bfs == tpu_bfs ==
        sharded mesh, locked by tests), visitation orders, shard
        layouts, and pipelining. On by default at small k (<2% overhead
        on the device engines, asserted by bench.py; candidates ride
        the existing once-per-era packed-params readback). Surfaced via
        `Checker.space_profile()` (field-distribution sketches, depth/
        action exemplars, packing-saturation warnings),
        ``telemetry()["space"]``, flat ``space_*`` gauges, and the
        Explorer's ``GET /space`` panel."""
        self.sample_ = bool(enable)
        self.sample_k_ = max(1, int(k))
        return self

    def multiplex_lane(self, enable: bool = True) -> "CheckerBuilder":
        """Mark this run as one lane of a multiplexed batch
        (engines/multiplex.py / the serve/ run service). Lanes share one
        compiled executable and one fused device era with their whole
        batch, so the device engines' small-workload hint — which warns
        about exactly the per-run overheads multiplexing amortizes away —
        is suppressed for them."""
        self.multiplex_lane_ = enable
        return self

    def profile(self, log_dir: str) -> "CheckerBuilder":
        """Bracket the run with `jax.profiler` start/stop_trace into
        `log_dir`. A no-op when the profiler is unavailable."""
        self.profile_dir_ = log_dir
        return self

    def spans(
        self,
        recorder: Any,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
    ) -> "CheckerBuilder":
        """Record this run into a `SpanRecorder` (obs/spans.py): one
        ``run`` span for the whole check plus one ``phase:<name>`` child
        per phase timer at run end — the run ledger's engine tier.
        `trace_id` / `parent_id` link the run into an enclosing trace
        (the serve layer passes the job's ids so engine time nests under
        the job's ``execute`` span); omitted, the run starts its own
        trace. With `trace(path, format="chrome")` also set, the
        recorder's spans are embedded into the Chrome trace at close, so
        one Perfetto file shows phases and request spans on aligned
        clocks."""
        self.span_recorder_ = recorder
        self.span_trace_id_ = trace_id
        self.span_parent_id_ = parent_id
        return self

    def stage_profile(self, enable: bool = True, iters: int = 32) -> "CheckerBuilder":
        """Attribute the device engines' era wall time across the stages
        of one BFS/simulation step (expand / hash / probe / claim /
        compact / ring / canon — obs/stageprof.py). After the run, the
        engine microbenches each stage in isolation at the run's exact
        compiled shapes (`iters` repetitions per dispatch) and scales the
        measured `device_era` time by the resulting shares, surfacing
        `stage_*` phase timers through `Checker.telemetry()`, the JSONL
        and Chrome traces, and Prometheus. Costs a few extra dispatches
        plus one compile per stage at run end; ignored by the host
        engines (their phases are timed directly)."""
        self.stage_profile_ = enable
        self.stage_profile_iters_ = max(1, int(iters))
        return self

    def pipeline(
        self,
        enable: bool = True,
        depth: Optional[int] = None,
        fuse: Optional[int] = None,
    ) -> "CheckerBuilder":
        """Speculative era pipelining on the device engines (default ON).

        While era N's packed-params readback is still in flight, the
        driver chains further eras directly off the still-on-device
        table/queue/params — the device loop's entry gate makes a
        chained dispatch an exact no-op whenever an earlier era actually
        needed host intervention (spill, grow, discovery finish, probe
        error), so results are bit-identical to the serial driver; only
        the dispatch gap between eras disappears. Disable to force the
        serial dispatch -> readback -> dispatch driver (useful when
        bisecting timing-sensitive telemetry).

        ``depth`` bounds the speculative in-flight chain: up to that many
        era dispatches are kept queued beyond the one being consumed,
        each with a non-blocking readback queued behind it (``None`` =
        auto, currently 2; ``1`` reproduces the original depth-1
        speculation). The host consumes readbacks strictly in order and
        peeks ``P_STEPS`` to tell consumed work from wasted speculation.

        ``fuse`` rolls that many eras into ONE compiled device program
        (an inner loop around the era body that continues only on pure
        budget exits), so one dispatch+readback can retire up to ``fuse``
        eras. ``None``/``1`` = no fusion. The packed params grow
        per-inner-era flight-record lanes, and the driver auto-degrades
        a dispatch to one era whenever per-era host work is pending
        (spill backlog, checkpoint cadence nearly due, state-count
        targets, timeouts)."""
        self.pipeline_ = bool(enable)
        if depth is not None:
            depth = int(depth)
            if depth < 1:
                raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.pipeline_depth_ = depth
        if fuse is not None:
            fuse = int(fuse)
            if fuse < 1:
                raise ValueError(f"pipeline fuse must be >= 1, got {fuse}")
        self.fuse_eras_ = fuse
        return self

    # -- static analysis (speclint; stateright_tpu.analysis) -----------------

    def lint(self, samples: int = 256, program_cost: bool = False) -> Any:
        """Run the speclint pre-flight over this builder's model and
        symmetry options WITHOUT launching an engine.

        Tensor-backed models additionally get the STR6xx program family:
        the compiled era loop is lowered (never executed) and scanned
        for host transfers, dropped donation, dtype drift, and op-budget
        regressions; ``program_cost=True`` widens that to every device
        program plus the STR606 cost-model roofline (seconds — the CLI's
        ``--program``).

        Returns an `analysis.AnalysisReport`; its diagnostic counts are
        also exported through `Checker.telemetry()` (as ``lint_<code>``
        counters) by any engine subsequently spawned from this builder.
        """
        from . import tensor as _tensor
        from .analysis import analyze

        # Tensor-backed models canonicalize via representative_lanes (the
        # thing the device engines actually run); the host-level
        # symmetry lambda only applies to rich host states.
        tensorish = isinstance(
            self.model, (_tensor.TensorModel, _tensor.TensorModelAdapter)
        )
        self.lint_report_ = analyze(
            self.model,
            samples=samples,
            symmetry_fn=None if tensorish else self.symmetry_fn_,
            program_cost=program_cost,
        )
        return self.lint_report_

    def strict(self, enable: bool = True, samples: int = 128) -> "CheckerBuilder":
        """Refuse to launch ANY engine while speclint finds error-severity
        diagnostics: every spawn_* first runs `lint()` (reusing an
        explicit earlier `lint()` result) and raises `SpecLintError` when
        the model's determinism, device encoding, properties, or symmetry
        are broken — engines checking a broken spec are worse than
        useless. `samples` bounds the pre-flight state sample."""
        self.strict_ = enable
        self.strict_samples_ = samples
        return self

    # -- engines ------------------------------------------------------------

    def spawn_bfs(self) -> "Checker":
        # .threads(n > 1) routes tensor-backed models to the vectorized
        # threaded engine (reference parity: multithreaded spawn_bfs,
        # bfs.rs:90-164). Rich host models get the multiprocessing
        # ownership-sharded engine (engines/pbfs.py) — true parallelism
        # for ANY picklable model, the job market's role re-designed for
        # CPython (round 5; closes SURVEY component #7).
        if self.thread_count_ > 1:
            from .tensor import TensorModel, TensorModelAdapter

            if isinstance(self.model, (TensorModel, TensorModelAdapter)):
                from .engines.vbfs import VectorizedBfsChecker

                return VectorizedBfsChecker(self)
            from .engines.pbfs import ParallelBfsChecker

            return ParallelBfsChecker(self)
        from .engines.bfs import BfsChecker

        return BfsChecker(self)

    def spawn_parallel_bfs(self) -> "Checker":
        """The multiprocessing ownership-sharded BFS for rich models."""
        from .engines.pbfs import ParallelBfsChecker

        return ParallelBfsChecker(self)

    def spawn_vbfs(self, **kw) -> "Checker":
        """The vectorized threaded host engine over a TensorModel."""
        from .engines.vbfs import VectorizedBfsChecker

        return VectorizedBfsChecker(self, **kw)

    def spawn_dfs(self) -> "Checker":
        from .engines.dfs import DfsChecker

        return DfsChecker(self)

    def spawn_on_demand(self) -> "Checker":
        from .engines.on_demand import OnDemandChecker

        return OnDemandChecker(self)

    def spawn_simulation(self, seed: int, chooser=None) -> "Checker":
        from .engines.simulation import SimulationChecker, UniformChooser

        return SimulationChecker(self, seed, chooser or UniformChooser())

    def spawn_tpu_bfs(self, **kw) -> "Checker":
        """The TPU-native batched BFS engine over a TensorModel."""
        from .engines.tpu_bfs import TpuBfsChecker

        return TpuBfsChecker(self, **kw)

    def spawn_tpu_simulation(self, seed: int, **kw) -> "Checker":
        """Batched device simulation over a TensorModel: B independent
        seeded random walks advance one transition per device step
        (engines/tpu_simulation.py; the data-parallel twin of the
        reference's per-thread walks, simulation.rs:138-201)."""
        from .engines.tpu_simulation import TpuSimulationChecker

        return TpuSimulationChecker(self, seed, **kw)

    def spawn_sharded_bfs(self, **kw) -> "Checker":
        """The multi-device sharded BFS engine over a TensorModel.

        Tables and frontiers shard by fingerprint ownership across a
        `jax.sharding.Mesh`; candidates cross the ICI once, to their owner,
        via all_to_all (parallel/mesh.py).
        """
        from .parallel.mesh import ShardedBfsChecker

        return ShardedBfsChecker(self, **kw)

    def serve(self, address: str, trace=None, deployment=None):
        """Start the Explorer web service. Reference: checker.rs:144-151.

        `trace` attaches a recorded conformance trace (a JSONL path from
        `spawn(..., record=...)`), served at ``GET /trace``; `deployment`
        attaches a live spawn handle whose netobs telemetry feeds
        ``GET /deployment``."""
        from .explorer.server import serve

        return serve(self, address, trace=trace, deployment=deployment)


class Checker:
    """Query interface over a (possibly still-running) checking run.

    Reference: the `Checker` trait, checker.rs:294-578.
    """

    # Engines must set: _model, and implement the count/discovery accessors.

    def model(self) -> Model:
        return self._model  # type: ignore[attr-defined]

    # -- to be implemented by engines ---------------------------------------

    def state_count(self) -> int:
        raise NotImplementedError

    def unique_state_count(self) -> int:
        raise NotImplementedError

    def max_depth(self) -> int:
        raise NotImplementedError

    def discoveries(self) -> Dict[str, Path]:
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def join(self) -> "Checker":
        return self

    def telemetry(self) -> Dict[str, Any]:
        """The engine's metrics-registry snapshot: counters, gauges, and
        cumulative per-phase wall millis (obs/metrics.py documents the
        names). Every engine populates one registry through the common
        API, so an occupancy or throughput regression is visible here
        without STPU_DEBUG."""
        return {}

    def coverage(self) -> Dict[str, Any]:
        """The engine's coverage snapshot (obs/coverage.py): per-action
        fire counts (`actions`), registered-but-never-fired actions
        (`dead_actions`), the per-depth unique-state histogram
        (`depths`), and per-property evaluation/hit counts
        (`properties`). Engines without coverage support return {}."""
        return {}

    def flight(self) -> List[Dict[str, Any]]:
        """The engine's flight recording (obs/flight.py): the retained
        per-era records, oldest first — each splitting the era's wall
        time into ``device_era_secs`` + ``host_gap_secs`` beside the
        frontier/table/spill counters read from that era's packed-params
        readback. The run-level summary rides ``telemetry()["flight"]``.
        Engines without an era loop return []."""
        return []

    def space_profile(self) -> Dict[str, Any]:
        """The run's space profile (obs/sample.py): the deterministic
        bottom-k sample of the explored state space rendered into
        per-field distribution sketches, per-depth exemplar states,
        per-action exemplar transitions, and packing-saturation
        warnings. Engines without sampling support return {}."""
        return {}

    # -- on-demand engine hooks (no-ops elsewhere; checker.rs:298-306) ------

    def check_fingerprint(self, fingerprint: int) -> None:
        pass

    def run_to_completion(self) -> None:
        pass

    # -- derived helpers ----------------------------------------------------

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def discovery_classification(self, name: str) -> str:
        """Reference: checker.rs:455-464."""
        prop = self.model().property(name)
        if prop.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY):
            return DiscoveryClassification.COUNTEREXAMPLE
        return DiscoveryClassification.EXAMPLE

    def report(self, reporter: Reporter) -> "Checker":
        """Poll progress until done, then emit a discovery summary.

        Reference: checker.rs:412-452.
        """
        start = time.monotonic()
        target = getattr(self, "_target_state_count", None)
        snap = getattr(self, "_initial_snapshot", None)
        if snap is not None:
            reporter.report_checking(
                ReportData(
                    total_states=snap[0],
                    unique_states=snap[1],
                    max_depth=snap[2],
                    duration_secs=0.0,
                    done=False,
                    target_states=target,
                )
            )
        while not self.is_done():
            reporter.report_checking(
                ReportData(
                    total_states=self.state_count(),
                    unique_states=self.unique_state_count(),
                    max_depth=self.max_depth(),
                    duration_secs=time.monotonic() - start,
                    done=False,
                    target_states=target,
                )
            )
            time.sleep(reporter.delay())
        self.join()
        reporter.report_checking(
            ReportData(
                total_states=self.state_count(),
                unique_states=self.unique_state_count(),
                max_depth=self.max_depth(),
                duration_secs=time.monotonic() - start,
                done=True,
                telemetry=self.telemetry(),
                coverage=self.coverage(),
                space=self.space_profile(),
            )
        )
        discoveries = {
            name: ReportDiscovery(path, self.discovery_classification(name))
            for name, path in self.discoveries().items()
        }
        reporter.report_discoveries(self.model(), discoveries)
        return self

    def join_and_report(self, reporter: Reporter) -> "Checker":
        return self.report(reporter)

    # -- assertion helpers (checker.rs:466-577) -----------------------------

    def assert_properties(self) -> None:
        for p in self.model().properties():
            if p.expectation in (Expectation.ALWAYS, Expectation.EVENTUALLY):
                self.assert_no_discovery(p.name)
            else:
                self.assert_any_discovery(p.name)

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found}Last state: {found.last_state()!r}\n"
            )
        if not self.is_done():
            raise AssertionError(
                f'Discovery for "{name}" not found, but model checking is incomplete.'
            )

    def assert_discovery(self, name: str, actions: List[Any]) -> None:
        """Assert `actions` forms a valid discovery for property `name`.

        Reference: checker.rs:519-577.
        """
        additional_info: List[str] = []
        found = self.assert_any_discovery(name)
        model = self.model()
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            prop = model.property(name)
            if prop.expectation == Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation == Expectation.EVENTUALLY:
                states = path.into_states()
                is_liveness_satisfied = any(
                    prop.condition(model, s) for s in states
                )
                last_actions: List[Any] = []
                model.actions(states[-1], last_actions)
                is_path_terminal = not last_actions
                if not is_liveness_satisfied and is_path_terminal:
                    return
                if is_liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property"
                    )
                if not is_path_terminal:
                    additional_info.append("incorrect counterexample is nonterminal")
            else:  # SOMETIMES
                if prop.condition(model, path.last_state()):
                    return
        extra = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{extra}, but a valid one was found. '
            f"found={found.into_actions()!r}"
        )
