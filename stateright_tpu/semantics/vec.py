"""Stack (Vec) operational semantics. Reference: src/semantics/vec.rs."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from .spec import SequentialSpec


@dataclass(frozen=True)
class Push:
    value: Any


@dataclass(frozen=True)
class Pop:
    pass


@dataclass(frozen=True)
class Len:
    pass


@dataclass(frozen=True)
class PushOk:
    pass


@dataclass(frozen=True)
class PopOk:
    value: Any  # None when the stack was empty


@dataclass(frozen=True)
class LenOk:
    length: int


POP = Pop()
LEN = Len()
PUSH_OK = PushOk()


class VecSpec(SequentialSpec):
    """A stack, the Python analogue of the reference's `impl SequentialSpec
    for Vec<T>` (vec.rs:22-50)."""

    __slots__ = ("items",)

    def __init__(self, items=()):
        self.items = list(items)

    def copy(self) -> "VecSpec":
        return VecSpec(self.items)

    def invoke(self, op: Any) -> Any:
        if isinstance(op, Push):
            self.items.append(op.value)
            return PUSH_OK
        if isinstance(op, Pop):
            return PopOk(self.items.pop() if self.items else None)
        if isinstance(op, Len):
            return LenOk(len(self.items))
        raise TypeError(f"not a vec op: {op!r}")

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        if isinstance(op, Push) and isinstance(ret, PushOk):
            self.items.append(op.value)
            return True
        if isinstance(op, Pop) and isinstance(ret, PopOk):
            popped = self.items.pop() if self.items else None
            return popped == ret.value
        if isinstance(op, Len) and isinstance(ret, LenOk):
            return len(self.items) == ret.length
        return False

    def __eq__(self, other) -> bool:
        return isinstance(other, VecSpec) and self.items == other.items

    def __repr__(self) -> str:
        return f"VecSpec({self.items!r})"

    def __hash__(self) -> int:
        from ..fingerprint import fingerprint

        return fingerprint(self)

    def fingerprint_key(self):
        return tuple(self.items)
