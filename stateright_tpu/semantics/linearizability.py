"""LinearizabilityTester: real-time-respecting serialization search.

Reference: src/semantics/linearizability.rs. On each invocation the tester
records, for every *other* thread, the index of that thread's last completed
operation. During serialization an operation may only be placed once every
peer has consumed its history up to that recorded index — this is what
enforces the happens-before ("real time") order that distinguishes
linearizability from sequential consistency.

The serialization itself is an exponential backtracking interleaving search
(linearizability.rs:193-280): keep histories tiny (the reference's register
examples default to one put per client for exactly this reason).

The tester is a hashable value object so it can serve as an `ActorModel`
history variable; recording hooks must call `.copy()` first (histories are
shared between system states).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .consistency_tester import ConsistencyTester
from .spec import SequentialSpec

# Per-thread history entry: (last-completed-index-by-peer, op, ret).
# In-flight entry: (last-completed-index-by-peer, op).


class LinearizabilityTester(ConsistencyTester):
    __slots__ = (
        "init_ref_obj",
        "history_by_thread",
        "in_flight_by_thread",
        "is_valid_history",
        "last_error",
    )

    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        self.history_by_thread: Dict[Any, List[Tuple[dict, Any, Any]]] = {}
        self.in_flight_by_thread: Dict[Any, Tuple[dict, Any]] = {}
        self.is_valid_history = True
        self.last_error: Optional[str] = None

    def copy(self) -> "LinearizabilityTester":
        new = LinearizabilityTester.__new__(LinearizabilityTester)
        new.init_ref_obj = self.init_ref_obj.copy()
        new.history_by_thread = {t: list(h) for t, h in self.history_by_thread.items()}
        new.in_flight_by_thread = dict(self.in_flight_by_thread)
        new.is_valid_history = self.is_valid_history
        new.last_error = self.last_error
        return new

    def __len__(self) -> int:
        """Operations completed or in flight, across all threads."""
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    def _poison(self, message: str) -> "LinearizabilityTester":
        self.is_valid_history = False
        self.last_error = message
        return self

    # -- recording (linearizability.rs:100-166) -----------------------------

    def on_invoke(self, thread_id: Any, op: Any) -> "LinearizabilityTester":
        if not self.is_valid_history:
            return self
        if thread_id in self.in_flight_by_thread:
            _, pending = self.in_flight_by_thread[thread_id]
            return self._poison(
                f"Thread already has an operation in flight. "
                f"thread_id={thread_id!r}, op={pending!r}"
            )
        last_completed = {
            t: len(h) - 1
            for t, h in self.history_by_thread.items()
            if t != thread_id and h
        }
        self.in_flight_by_thread[thread_id] = (last_completed, op)
        self.history_by_thread.setdefault(thread_id, [])
        return self

    def on_return(self, thread_id: Any, ret: Any) -> "LinearizabilityTester":
        if not self.is_valid_history:
            return self
        entry = self.in_flight_by_thread.pop(thread_id, None)
        if entry is None:
            return self._poison(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}"
            )
        completed, op = entry
        self.history_by_thread.setdefault(thread_id, []).append((completed, op, ret))
        return self

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None

    # -- serialization (linearizability.rs:175-280) -------------------------

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        """A valid total order of the recorded history, or None."""
        if not self.is_valid_history:
            return None
        remaining = {
            t: tuple(enumerate(h)) for t, h in self.history_by_thread.items()
        }
        return _serialize(
            [], self.init_ref_obj, remaining, dict(self.in_flight_by_thread)
        )

    # -- value-object protocol ----------------------------------------------

    def __hash__(self) -> int:
        from ..fingerprint import fingerprint

        return fingerprint(self)

    def fingerprint_key(self):
        return (
            self.init_ref_obj,
            {
                t: tuple((tuple(sorted(c.items())), op, ret) for c, op, ret in h)
                for t, h in self.history_by_thread.items()
            },
            {
                t: (tuple(sorted(c.items())), op)
                for t, (c, op) in self.in_flight_by_thread.items()
            },
            self.is_valid_history,
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, LinearizabilityTester)
            and self.init_ref_obj == other.init_ref_obj
            and self.history_by_thread == other.history_by_thread
            and self.in_flight_by_thread == other.in_flight_by_thread
            and self.is_valid_history == other.is_valid_history
        )

    def __repr__(self) -> str:
        return (
            f"LinearizabilityTester(init={self.init_ref_obj!r}, "
            f"history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, "
            f"valid={self.is_valid_history})"
        )


def _violates_real_time(completed: dict, remaining: dict) -> bool:
    """An op invoked after peer ops completed cannot precede them.

    `completed[peer] = i` means peer's ops 0..=i finished before this op
    began; if peer still has entry i (or earlier) unconsumed, placing this
    op now would reorder real time (linearizability.rs:224-237).
    """
    for peer_id, min_peer_time in completed.items():
        peer_ops = remaining.get(peer_id)
        if peer_ops and peer_ops[0][0] <= min_peer_time:
            return True
    return False


def _serialize(
    valid_history: list,
    ref_obj: SequentialSpec,
    remaining: Dict[Any, tuple],
    in_flight: Dict[Any, Tuple[dict, Any]],
) -> Optional[List[Tuple[Any, Any]]]:
    if all(not h for h in remaining.values()):
        return valid_history

    for thread_id in sorted(remaining):
        history = remaining[thread_id]
        if not history:
            # Case 1: nothing completed left; maybe an in-flight op can be
            # placed here (its return never arrived, but it may have taken
            # effect).
            entry = in_flight.get(thread_id)
            if entry is None:
                continue
            completed, op = entry
            if _violates_real_time(completed, remaining):
                continue
            obj = ref_obj.copy()
            ret = obj.invoke(op)
            next_valid = valid_history + [(op, ret)]
            next_remaining = remaining
            next_in_flight = {t: e for t, e in in_flight.items() if t != thread_id}
        else:
            # Case 2: try this thread's next completed op.
            _, (completed, op, ret) = history[0]
            next_remaining = dict(remaining)
            next_remaining[thread_id] = history[1:]
            if _violates_real_time(completed, next_remaining):
                continue
            obj = ref_obj.copy()
            if not obj.is_valid_step(op, ret):
                continue
            next_valid = valid_history + [(op, ret)]
            next_in_flight = in_flight
        result = _serialize(next_valid, obj, next_remaining, next_in_flight)
        if result is not None:
            return result
    return None
