"""SequentialConsistencyTester: per-thread-order-only serialization search.

Reference: src/semantics/sequential_consistency.rs. Identical in shape to
the linearizability tester minus the real-time precedence bookkeeping:
any interleaving preserving each thread's own order is acceptable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .consistency_tester import ConsistencyTester
from .spec import SequentialSpec


class SequentialConsistencyTester(ConsistencyTester):
    __slots__ = (
        "init_ref_obj",
        "history_by_thread",
        "in_flight_by_thread",
        "is_valid_history",
        "last_error",
    )

    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        self.history_by_thread: Dict[Any, List[Tuple[Any, Any]]] = {}
        self.in_flight_by_thread: Dict[Any, Any] = {}
        self.is_valid_history = True
        self.last_error: Optional[str] = None

    def copy(self) -> "SequentialConsistencyTester":
        new = SequentialConsistencyTester.__new__(SequentialConsistencyTester)
        new.init_ref_obj = self.init_ref_obj.copy()
        new.history_by_thread = {t: list(h) for t, h in self.history_by_thread.items()}
        new.in_flight_by_thread = dict(self.in_flight_by_thread)
        new.is_valid_history = self.is_valid_history
        new.last_error = self.last_error
        return new

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    def _poison(self, message: str) -> "SequentialConsistencyTester":
        self.is_valid_history = False
        self.last_error = message
        return self

    # -- recording (sequential_consistency.rs:95-143) -----------------------

    def on_invoke(self, thread_id: Any, op: Any) -> "SequentialConsistencyTester":
        if not self.is_valid_history:
            return self
        if thread_id in self.in_flight_by_thread:
            return self._poison(
                f"Thread already has an operation in flight. "
                f"thread_id={thread_id!r}, op={self.in_flight_by_thread[thread_id]!r}"
            )
        self.in_flight_by_thread[thread_id] = op
        self.history_by_thread.setdefault(thread_id, [])
        return self

    def on_return(self, thread_id: Any, ret: Any) -> "SequentialConsistencyTester":
        if not self.is_valid_history:
            return self
        if thread_id not in self.in_flight_by_thread:
            return self._poison(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}"
            )
        op = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread.setdefault(thread_id, []).append((op, ret))
        return self

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None

    # -- serialization (sequential_consistency.rs:148-~260) ------------------

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        if not self.is_valid_history:
            return None
        remaining = {t: tuple(h) for t, h in self.history_by_thread.items()}
        return _serialize(
            [], self.init_ref_obj, remaining, dict(self.in_flight_by_thread)
        )

    # -- value-object protocol ----------------------------------------------

    def __hash__(self) -> int:
        from ..fingerprint import fingerprint

        return fingerprint(self)

    def fingerprint_key(self):
        return (
            self.init_ref_obj,
            {t: tuple(h) for t, h in self.history_by_thread.items()},
            self.in_flight_by_thread,
            self.is_valid_history,
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, SequentialConsistencyTester)
            and self.init_ref_obj == other.init_ref_obj
            and self.history_by_thread == other.history_by_thread
            and self.in_flight_by_thread == other.in_flight_by_thread
            and self.is_valid_history == other.is_valid_history
        )

    def __repr__(self) -> str:
        return (
            f"SequentialConsistencyTester(init={self.init_ref_obj!r}, "
            f"history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, "
            f"valid={self.is_valid_history})"
        )


def _serialize(
    valid_history: list,
    ref_obj: SequentialSpec,
    remaining: Dict[Any, tuple],
    in_flight: Dict[Any, Any],
) -> Optional[List[Tuple[Any, Any]]]:
    if all(not h for h in remaining.values()):
        return valid_history

    for thread_id in sorted(remaining):
        history = remaining[thread_id]
        if not history:
            # Membership check (not a None sentinel): an in-flight op that is
            # literally None must still serialize, mirroring the reference's
            # contains_key and the linearizability tester.
            if thread_id not in in_flight:
                continue
            op = in_flight[thread_id]
            obj = ref_obj.copy()
            ret = obj.invoke(op)
            next_valid = valid_history + [(op, ret)]
            next_remaining = remaining
            next_in_flight = {t: o for t, o in in_flight.items() if t != thread_id}
        else:
            op, ret = history[0]
            obj = ref_obj.copy()
            if not obj.is_valid_step(op, ret):
                continue
            next_valid = valid_history + [(op, ret)]
            next_remaining = dict(remaining)
            next_remaining[thread_id] = history[1:]
            next_in_flight = in_flight
        result = _serialize(next_valid, obj, next_remaining, next_in_flight)
        if result is not None:
            return result
    return None
