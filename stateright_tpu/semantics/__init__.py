"""Consistency semantics: correctness via sequential reference objects.

Reference parity: src/semantics.rs. `SequentialSpec` defines correctness by
a reference implementation ("this system should behave like a register");
`ConsistencyTester` implementations record potentially-concurrent operation
histories and decide whether they admit a valid serialization:

  - `LinearizabilityTester`   — total order must respect real-time
    (happens-before) precedence across threads;
  - `SequentialConsistencyTester` — per-thread order only.

A tester is typically carried as an `ActorModel` history variable and
interrogated from an `always` property; it is a hashable value object so
it participates in state fingerprints.
"""

from .consistency_tester import ConsistencyTester
from .linearizability import LinearizabilityTester
from .sequential_consistency import SequentialConsistencyTester
from .spec import SequentialSpec
from . import register, vec, write_once_register

__all__ = [
    "ConsistencyTester",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
    "SequentialSpec",
    "register",
    "vec",
    "write_once_register",
]
