"""Register operational semantics. Reference: src/semantics/register.rs."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from .spec import SequentialSpec


@dataclass(frozen=True)
class Write:
    value: Any


@dataclass(frozen=True)
class Read:
    pass


@dataclass(frozen=True)
class WriteOk:
    pass


@dataclass(frozen=True)
class ReadOk:
    value: Any


READ = Read()
WRITE_OK = WriteOk()


class Register(SequentialSpec):
    """A read/write register. Reference: register.rs:8-49."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def copy(self) -> "Register":
        return Register(self.value)

    def invoke(self, op: Any) -> Any:
        if isinstance(op, Write):
            self.value = op.value
            return WRITE_OK
        if isinstance(op, Read):
            return ReadOk(self.value)
        raise TypeError(f"not a register op: {op!r}")

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        if isinstance(op, Write) and isinstance(ret, WriteOk):
            self.value = op.value
            return True
        if isinstance(op, Read) and isinstance(ret, ReadOk):
            return self.value == ret.value
        return False

    def __eq__(self, other) -> bool:
        return isinstance(other, Register) and self.value == other.value

    def __repr__(self) -> str:
        return f"Register({self.value!r})"

    def __hash__(self) -> int:
        from ..fingerprint import fingerprint

        return fingerprint(self)

    def fingerprint_key(self):
        return self.value
