"""Write-once register operational semantics.

Reference: src/semantics/write_once_register.rs. A write succeeds while the
register is unset (or when re-writing the identical value); later differing
writes fail; reads return the current optional value.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from .spec import SequentialSpec


@dataclass(frozen=True)
class Write:
    value: Any


@dataclass(frozen=True)
class Read:
    pass


@dataclass(frozen=True)
class WriteOk:
    pass


@dataclass(frozen=True)
class WriteFail:
    pass


@dataclass(frozen=True)
class ReadOk:
    value: Any  # None when the register is unset


READ = Read()
WRITE_OK = WriteOk()
WRITE_FAIL = WriteFail()


class WORegister(SequentialSpec):
    """Reference: write_once_register.rs:8-58."""

    __slots__ = ("value",)

    def __init__(self, value: Optional[Any] = None):
        self.value = value

    def copy(self) -> "WORegister":
        return WORegister(self.value)

    def invoke(self, op: Any) -> Any:
        if isinstance(op, Write):
            if self.value is None or self.value == op.value:
                self.value = op.value
                return WRITE_OK
            return WRITE_FAIL
        if isinstance(op, Read):
            return ReadOk(self.value)
        raise TypeError(f"not a write-once register op: {op!r}")

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        if isinstance(op, Write):
            if isinstance(ret, WriteOk):
                if self.value is None:
                    self.value = op.value
                    return True
                return self.value == op.value
            if isinstance(ret, WriteFail):
                return self.value is not None and self.value != op.value
            return False
        if isinstance(op, Read) and isinstance(ret, ReadOk):
            return self.value == ret.value
        return False

    def __eq__(self, other) -> bool:
        return isinstance(other, WORegister) and self.value == other.value

    def __repr__(self) -> str:
        return f"WORegister({self.value!r})"

    def __hash__(self) -> int:
        from ..fingerprint import fingerprint

        return fingerprint(self)

    def fingerprint_key(self):
        return self.value
