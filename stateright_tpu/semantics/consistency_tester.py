"""ConsistencyTester: the history-recording interface.

Reference: src/semantics/consistency_tester.rs. Recording methods return
`self` for chaining. A recording error (double-invoke, return without
invoke) *poisons* the tester — the history becomes permanently invalid
(`is_consistent()` is False) and `last_error` holds the diagnostic —
mirroring the reference's `Err(...)` + `is_valid_history = false` behavior.
"""

from __future__ import annotations

from typing import Any


class ConsistencyTester:
    def on_invoke(self, thread_id: Any, op: Any) -> "ConsistencyTester":
        """Record that `thread_id` invoked `op`."""
        raise NotImplementedError

    def on_return(self, thread_id: Any, ret: Any) -> "ConsistencyTester":
        """Record that `thread_id`'s earlier invocation returned `ret`."""
        raise NotImplementedError

    def is_consistent(self) -> bool:
        """Whether the recorded history admits a valid serialization."""
        raise NotImplementedError

    def on_invret(self, thread_id: Any, op: Any, ret: Any) -> "ConsistencyTester":
        """Record an operation and its return together (consistency_tester.rs:32-43)."""
        return self.on_invoke(thread_id, op).on_return(thread_id, ret)
