"""SequentialSpec: a sequential "reference object" defining correctness.

Reference: the `SequentialSpec` trait (src/semantics.rs:73-98). Implement
`invoke` (mutating the object, returning the op's return value) and `copy`;
`is_valid_step` / `is_valid_history` have default implementations.
"""

from __future__ import annotations

from typing import Any, Iterable, Tuple


class SequentialSpec:
    def invoke(self, op: Any) -> Any:
        """Apply `op` to this object, returning the operation's value."""
        raise NotImplementedError

    def copy(self) -> "SequentialSpec":
        """An independent copy (testers branch the object during search)."""
        raise NotImplementedError

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        """Whether invoking `op` may return `ret` (mutates on success path).

        Reference: semantics.rs:85-90.
        """
        return self.invoke(op) == ret

    def is_valid_history(self, ops: Iterable[Tuple[Any, Any]]) -> bool:
        """Whether a sequential (op, ret) history is valid for this object."""
        return all(self.is_valid_step(op, ret) for op, ret in ops)
