"""Progress reporting during checking runs.

Reference: src/report.rs. `WriteReporter` prints the same line formats the
reference's bench harness greps ("Done. states=… unique=… depth=… sec=…",
report.rs:66-74).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, TextIO


@dataclass
class ReportData:
    """Reference: report.rs:10-21 (+ engine telemetry, this framework)."""

    total_states: int
    unique_states: int
    max_depth: int
    duration_secs: float
    done: bool
    # Engine-specific gauges (device engines: load factor, take_cap,
    # steps/era, spill volume — reference report.rs has no equivalent;
    # empty for engines without telemetry).
    telemetry: Dict[str, Any] = None


@dataclass
class ReportDiscovery:
    """Reference: report.rs:24-32."""

    path: Any  # Path
    classification: Any  # DiscoveryClassification


class Reporter:
    """Reference: report.rs:35-48."""

    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def delay(self) -> float:
        """Seconds between progress samples (reference default 1s, report.rs:46-47)."""
        return 1.0


class WriteReporter(Reporter):
    """Writes progress lines to a file-like object. Reference: report.rs:50-98."""

    def __init__(self, writer: TextIO):
        self.writer = writer

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self.writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration_secs)}\n"
            )
            if data.telemetry:
                pairs = ", ".join(
                    f"{k}={v}" for k, v in sorted(data.telemetry.items())
                )
                self.writer.write(f"Telemetry. {pairs}\n")
        else:
            self.writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        for name in sorted(discoveries):
            d = discoveries[name]
            self.writer.write(f'Discovered "{name}" {d.classification} {d.path}')
            self.writer.write(f"Fingerprint path: {d.path.encode(model)}\n")
