"""Progress reporting during checking runs.

Reference: src/report.rs. `WriteReporter` prints the same line formats the
reference's bench harness greps ("Done. states=… unique=… depth=… sec=…",
report.rs:66-74), augmented with registry-derived rate information this
framework adds: each progress line past the first carries the instantaneous
throughput (states/sec over the last sample interval), a moving-average
rate over the recent sample window, and — when the run has a
target_state_count — an ETA extrapolated from the moving average. The
reference-compatible "Done." and "Checking." prefixes are unchanged, so
anything grepping them keeps working.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional, TextIO


@dataclass
class ReportData:
    """Reference: report.rs:10-21 (+ engine telemetry, this framework)."""

    total_states: int
    unique_states: int
    max_depth: int
    duration_secs: float
    done: bool
    # Engine metrics-registry snapshot (counters, gauges, phase_ms — see
    # obs/metrics.py; reference report.rs has no equivalent). Populated on
    # the final sample.
    telemetry: Dict[str, Any] = None
    # The run's target_state_count, when set — lets reporters compute ETA.
    target_states: Optional[int] = None
    # Engine coverage snapshot (obs/coverage.py: per-action fire counts,
    # dead actions, depth histogram, property eval/hit counts). Populated
    # on the final sample; drives the dead-action warning block.
    coverage: Dict[str, Any] = None
    # Engine space profile (obs/sample.py: bottom-k sample, field
    # sketches, saturation warnings). Populated on the final sample;
    # drives the one-line `Space.` recap + saturated-field warning.
    space: Dict[str, Any] = None


@dataclass
class ReportDiscovery:
    """Reference: report.rs:24-32."""

    path: Any  # Path
    classification: Any  # DiscoveryClassification


class Reporter:
    """Reference: report.rs:35-48."""

    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def delay(self) -> float:
        """Seconds between progress samples (reference default 1s, report.rs:46-47)."""
        return 1.0


def _fmt_rate(rate: float) -> str:
    if rate >= 1e6:
        return f"{rate / 1e6:.2f}M/s"
    if rate >= 1e3:
        return f"{rate / 1e3:.1f}k/s"
    return f"{rate:.0f}/s"


class WriteReporter(Reporter):
    """Writes progress lines to a file-like object. Reference: report.rs:50-98.

    Rate math: progress samples (duration, states) accumulate in a bounded
    window; `rate` is the throughput over the latest sample interval,
    `avg` the moving average across the whole window, and `eta` the
    moving-average extrapolation to the run's target_state_count.
    """

    # Moving-average window: at the default 1s sample delay this averages
    # over the last ~30s of progress.
    WINDOW = 30
    # Instantaneous-rate damping: the trailing samples must span at least
    # this many seconds. When eras shrink near the end of a run, polls can
    # land a few milliseconds apart; a one-interval rate over such a
    # sliver jitters wildly (and dragged the ETA with it), so `rate`
    # reaches back over as many samples as needed to cover a real span.
    MIN_RATE_SPAN = 0.25

    def __init__(self, writer: TextIO):
        self.writer = writer
        self._samples: deque = deque(maxlen=self.WINDOW)  # (secs, states)

    def _rate_suffix(self, data: ReportData) -> str:
        self._samples.append((data.duration_secs, data.total_states))
        if len(self._samples) < 2:
            return ""
        (t0, s0) = self._samples[0]
        (tn, sn) = self._samples[-1]
        # Sub-50ms windows (e.g. the first poll landing right after the
        # initial snapshot) extrapolate absurd rates; wait for real data.
        if tn - t0 < 0.05:
            return ""
        # Walk back until the trailing span is long enough to damp jitter
        # (stops at the window start for slow-polling callers).
        k = len(self._samples) - 2
        while k > 0 and tn - self._samples[k][0] < self.MIN_RATE_SPAN:
            k -= 1
        (tp, sp) = self._samples[k]
        avg = (sn - s0) / (tn - t0)
        inst = max(0.0, (sn - sp) / (tn - tp)) if tn > tp else avg
        suffix = f", rate={_fmt_rate(inst)}, avg={_fmt_rate(avg)}"
        if data.target_states and avg > 0 and data.target_states > sn:
            suffix += f", eta={max(0, int((data.target_states - sn) / avg))}s"
        return suffix

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self.writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration_secs)}\n"
            )
            if data.duration_secs > 0:
                self.writer.write(
                    "Rate. states_per_sec="
                    f"{data.total_states / data.duration_secs:.1f}\n"
                )
            if data.telemetry:
                telemetry = dict(data.telemetry)
                # The memory, space, and program snapshots are nested
                # documents; they get their own compact lines instead of
                # bloating the pairs line.
                memory = telemetry.pop("memory", None)
                telemetry.pop("space", None)
                program = telemetry.pop("program", None)
                pairs = ", ".join(
                    f"{k}={v}" for k, v in sorted(telemetry.items())
                )
                self.writer.write(f"Telemetry. {pairs}\n")
                self._report_memory(memory)
                self._report_program(program)
            self._report_coverage(data.coverage)
            self._report_space(data.space)
        else:
            self.writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}"
                f"{self._rate_suffix(data)}\n"
            )

    def _report_memory(self, memory) -> None:
        """One compact device-residency line from the memory ledger
        (obs/memory.py), plus the forecaster's early warning when one
        fired during the run. The full per-component snapshot stays in
        ``telemetry()["memory"]``."""
        if not memory or memory.get("total_bytes") is None:
            return
        parts = [
            f"resident_bytes={memory['total_bytes']}",
            f"peak_bytes={memory.get('peak_bytes', memory['total_bytes'])}",
        ]
        if memory.get("host_bytes"):
            parts.append(f"host_bytes={memory['host_bytes']}")
        if memory.get("headroom_bytes") is not None:
            parts.append(f"headroom_bytes={memory['headroom_bytes']}")
        forecast = memory.get("forecast") or {}
        if forecast.get("eras_to_exhaustion") is not None:
            parts.append(
                f"eta_exhaustion_eras={forecast['eras_to_exhaustion']}"
            )
        self.writer.write(f"Memory. {', '.join(parts)}\n")
        if memory.get("warning"):
            self.writer.write(f"Warning. {memory['warning']}\n")

    def _report_program(self, program) -> None:
        """The STR606 predicted-vs-achieved roofline recap: the static
        cost model's predicted st/s next to the measured rate, with
        their ratio. attribution≈1 means the memory-bound roofline
        explains the run; attribution<<1 points at the dispatch gap or
        host stalls (see analysis/README.md, "Reading the roofline").
        Printed only when a program-lint pass ran for this model."""
        if not program or not program.get("predicted_states_per_sec"):
            return
        parts = [
            f"predicted={_fmt_rate(program['predicted_states_per_sec'])}",
        ]
        if program.get("measured_states_per_sec"):
            parts.append(
                f"measured={_fmt_rate(program['measured_states_per_sec'])}"
            )
        if program.get("attribution_ratio") is not None:
            parts.append(f"attribution={program['attribution_ratio']:.2f}")
        if program.get("era_ops"):
            parts.append(f"era_ops={program['era_ops']}")
        self.writer.write(f"Program. {', '.join(parts)}\n")

    def _report_coverage(self, coverage) -> None:
        """The final coverage summary + dead-action warning block.

        A dead action is a green run's silent lie: the search verified a
        SMALLER system than the one modeled (a guard is mis-modeled or
        the transition is genuinely unreachable). TLC prints per-action
        coverage for exactly this reason; speclint STR306
        (analysis/README.md) is the pre-flight twin of this check.
        """
        if not coverage or not coverage.get("enabled"):
            return
        actions = coverage.get("actions") or {}
        if actions:
            fired = sum(1 for v in actions.values() if v)
            self.writer.write(
                f"Coverage. actions_fired={fired}/{len(actions)}, "
                f"max_depth={coverage.get('max_depth', 0)}\n"
            )
        dead = coverage.get("dead_actions") or []
        if dead:
            self.writer.write(
                f"Warning. {len(dead)} action(s) never fired — dead "
                "transitions or mis-modeled guards (speclint STR306):\n"
            )
            for label in dead:
                self.writer.write(f"  - {label}\n")

    def _report_space(self, space) -> None:
        """One compact space-profile line (obs/sample.py): sample size,
        estimated space size, and the top-cardinality decoded fields —
        the content twin of the `Coverage.` count line — plus a warning
        when any sampled lane saturates its packed range (the runtime
        twin of speclint STR209). The full profile stays in
        ``Checker.space_profile()`` / the Explorer's ``GET /space``."""
        if not space or not space.get("samples"):
            return
        parts = [
            f"samples={space['samples']}/{space.get('k', space['samples'])}",
            f"est_states={space.get('est_states', 0)}",
        ]
        fields = space.get("fields") or {}
        if fields:
            top = sorted(
                fields.items(),
                key=lambda kv: (-kv[1].get("distinct", 0), kv[0]),
            )[:3]
            parts.append(
                "top_fields="
                + ",".join(
                    f"{name}({sk.get('distinct', 0)})" for name, sk in top
                )
            )
        self.writer.write(f"Space. {', '.join(parts)}\n")
        saturated = space.get("saturated") or []
        if saturated:
            names = ", ".join(
                ent.get("field", f"lane[{ent['lane']}]")
                + f"={ent['max']} ({ent['bits']}-bit edge)"
                for ent in saturated
            )
            self.writer.write(
                f"Warning. {len(saturated)} field(s) saturate their packed "
                f"range — one step from wrapping (speclint STR209): {names}\n"
            )

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        for name in sorted(discoveries):
            d = discoveries[name]
            self.writer.write(f'Discovered "{name}" {d.classification} {d.path}')
            self.writer.write(f"Fingerprint path: {d.path.encode(model)}\n")
            try:
                # Counterexample forensics (path.py): per-step action,
                # field-level diff, and property flips — best-effort, a
                # model whose re-execution fails still gets the raw path.
                self.writer.write(d.path.explain(model))
            except Exception:
                pass
