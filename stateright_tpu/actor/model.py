"""ActorModel: compiles an actor system into a checkable `Model`.

Reference parity: src/actor/model.rs. The model's action space per state is:

  1. `Deliver` — one per deliverable envelope (head-of-flow only for
     `Ordered` networks, model.rs:269-275);
  2. `Drop` — one per deliverable envelope, iff the network is lossy;
  3. `Timeout` — one per pending (actor, timer);
  4. `Crash` — one per live actor, while fewer than `max_crashes` crashed;
  5. `SelectRandom` — one per (actor, key, choice) pending random branch.

Transitions preserve the reference's pruning semantics exactly:
a `Deliver` whose handler is a no-op is pruned unless the network is
`Ordered` (model.rs:345-347); a `Timeout` that only renews its own timer is
pruned (model.rs:377-381); a crashed actor receives nothing (model.rs:335).

Per-actor states are shared structurally between system states (the
reference's `Arc<State>` copy-on-write, model.rs:340, 371-373): a transition
copies the state-pointer list and replaces only the changed entry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

from ..core import Expectation, Model, Property
from .base import (
    Actor,
    CancelTimer,
    ChooseRandom,
    Out,
    Send,
    SetTimer,
    is_no_op,
    is_no_op_with_timer,
)
from .ids import Id
from .model_state import ActorModelState, RandomChoices
from .network import Envelope, Network, Ordered
from .timers import Timers


# ---------------------------------------------------------------------------
# Actions (model.rs:42-63)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Deliver:
    src: Id
    dst: Id
    msg: Any


@dataclass(frozen=True)
class Drop:
    envelope: Envelope


@dataclass(frozen=True)
class Timeout:
    id: Id
    timer: Any


@dataclass(frozen=True)
class Crash:
    id: Id


@dataclass(frozen=True)
class SelectRandom:
    actor: Id
    key: str
    random: Any


def model_timeout() -> Tuple[float, float]:
    """Arbitrary timeout range for checking (value irrelevant; model.rs:73-78)."""
    return (0.0, 0.0)


class ActorModel(Model):
    """A system of actors communicating over a modeled network.

    `cfg` is arbitrary read-only configuration available to properties and
    history hooks; `init_history` seeds the auxiliary history variable `H`
    (see "Auxiliary Variables in TLA"; model.rs:18-40).
    """

    def __init__(self, cfg: Any = None, init_history: Any = ()):
        self.actors: List[Actor] = []
        self.cfg = cfg
        self.init_history = init_history
        self.init_network: Network = Network.new_unordered_duplicating()
        self.lossy_network: bool = False
        self.max_crashes: int = 0
        self._properties: List[Property] = []
        self.record_msg_in: Callable[[Any, Any, Envelope], Optional[Any]] = (
            lambda cfg, history, env: None
        )
        self.record_msg_out: Callable[[Any, Any, Envelope], Optional[Any]] = (
            lambda cfg, history, env: None
        )
        self._within_boundary: Callable[[Any, ActorModelState], bool] = (
            lambda cfg, state: True
        )

    # -- builder (model.rs:89-226) ------------------------------------------

    def actor(self, actor: Actor) -> "ActorModel":
        self.actors.append(actor)
        return self

    def add_actors(self, actors) -> "ActorModel":
        for actor in actors:
            self.actors.append(actor)
        return self

    def with_init_network(self, network: Network) -> "ActorModel":
        self.init_network = network
        return self

    def with_lossy_network(self, lossy: bool) -> "ActorModel":
        self.lossy_network = lossy
        return self

    def with_max_crashes(self, max_crashes: int) -> "ActorModel":
        self.max_crashes = max_crashes
        return self

    def property(
        self, expectation, name: Optional[str] = None, condition=None
    ):
        """With three arguments: add a property (builder, model.rs:143-157).
        With one string argument: look it up (the `Model.property` accessor)."""
        if name is None and condition is None:
            if not isinstance(expectation, str):
                raise TypeError(
                    "ActorModel.property(expectation, name, condition) adds a "
                    "property; the single-argument form looks one up by name "
                    f"and requires a string, got {type(expectation).__name__}"
                )
            return Model.property(self, expectation)
        self._properties.append(Property(expectation, name, condition))
        return self

    def with_record_msg_in(self, hook) -> "ActorModel":
        self.record_msg_in = hook
        return self

    def with_record_msg_out(self, hook) -> "ActorModel":
        self.record_msg_out = hook
        return self

    def with_within_boundary(self, hook) -> "ActorModel":
        self._within_boundary = hook
        return self

    # -- command processing (model.rs:188-226) ------------------------------

    def _process_commands(self, id: Id, out: Out, state: ActorModelState) -> None:
        index = int(id)
        for cmd in out.commands:
            if isinstance(cmd, Send):
                env = Envelope(id, cmd.dst, cmd.msg)
                history = self.record_msg_out(self.cfg, state.history, env)
                if history is not None:
                    state.history = history
                state.network.send(env)
            elif isinstance(cmd, SetTimer):
                while len(state.timers_set) <= index:
                    state.timers_set.append(Timers())
                state.timers_set[index].set(cmd.timer)
            elif isinstance(cmd, CancelTimer):
                state.timers_set[index].cancel(cmd.timer)
            elif isinstance(cmd, ChooseRandom):
                if not cmd.choices:
                    state.random_choices[index].remove(cmd.key)
                else:
                    state.random_choices[index].insert(cmd.key, cmd.choices)
            else:
                raise TypeError(f"unknown command: {cmd!r}")

    # -- Model interface (model.rs:228-426) ----------------------------------

    def init_states(self) -> List[ActorModelState]:
        state = ActorModelState(
            actor_states=[],
            network=self.init_network.copy(),
            timers_set=[Timers() for _ in self.actors],
            random_choices=[RandomChoices() for _ in self.actors],
            crashed=[False] * len(self.actors),
            history=self.init_history,
        )
        for index, actor in enumerate(self.actors):
            id = Id(index)
            out = Out()
            actor_state = actor.on_start(id, out)
            state.actor_states.append(actor_state)
            self._process_commands(id, out, state)
        return [state]

    def actions(self, state: ActorModelState, actions: List[Any]) -> None:
        # Head-of-channel-only delivery for Ordered networks (model.rs:269-275)
        # is enforced by Ordered.iter_deliverable itself, which yields exactly
        # one head envelope per (src, dst) flow.
        for env in state.network.iter_deliverable():
            if self.lossy_network:
                actions.append(Drop(env))
            if int(env.dst) < len(self.actors):  # ignored if recipient DNE
                actions.append(Deliver(env.src, env.dst, env.msg))

        for index, timers in enumerate(state.timers_set):
            for timer in timers:
                actions.append(Timeout(Id(index), timer))

        if sum(state.crashed) < self.max_crashes:
            for index, crashed in enumerate(state.crashed):
                if not crashed:
                    actions.append(Crash(Id(index)))

        for index, randoms in enumerate(state.random_choices):
            for key in sorted(randoms.map):
                for choice in randoms.map[key]:
                    actions.append(SelectRandom(Id(index), key, choice))

    def next_state(
        self, last_state: ActorModelState, action: Any
    ) -> Optional[ActorModelState]:
        if isinstance(action, Drop):
            next_state = last_state.clone()
            next_state.network.on_drop(action.envelope)
            return next_state

        if isinstance(action, Deliver):
            index = int(action.dst)
            if index >= len(last_state.actor_states):
                return None  # not all messages can be delivered
            if last_state.crashed[index]:
                return None
            last_actor_state = last_state.actor_states[index]
            out = Out()
            returned = self.actors[index].on_msg(
                action.dst, last_actor_state, action.src, action.msg, out
            )
            if is_no_op(returned, out) and not isinstance(self.init_network, Ordered):
                return None
            env = Envelope(action.src, action.dst, action.msg)
            history = self.record_msg_in(self.cfg, last_state.history, env)
            next_state = last_state.clone()
            next_state.network.on_deliver(env)
            if returned is not None:
                next_state.actor_states[index] = returned
            if history is not None:
                next_state.history = history
            self._process_commands(action.dst, out, next_state)
            return next_state

        if isinstance(action, Timeout):
            index = int(action.id)
            out = Out()
            returned = self.actors[index].on_timeout(
                action.id, last_state.actor_states[index], action.timer, out
            )
            if is_no_op_with_timer(returned, out, action.timer):
                return None
            next_state = last_state.clone()
            next_state.timers_set[index].cancel(action.timer)  # timer consumed
            if returned is not None:
                next_state.actor_states[index] = returned
            self._process_commands(action.id, out, next_state)
            return next_state

        if isinstance(action, Crash):
            index = int(action.id)
            next_state = last_state.clone()
            next_state.timers_set[index].cancel_all()
            next_state.random_choices[index] = RandomChoices()
            next_state.crashed[index] = True
            return next_state

        if isinstance(action, SelectRandom):
            index = int(action.actor)
            out = Out()
            returned = self.actors[index].on_random(
                action.actor, last_state.actor_states[index], action.random, out
            )
            next_state = last_state.clone()
            next_state.random_choices[index].remove(action.key)  # choice consumed
            if returned is not None:
                next_state.actor_states[index] = returned
            self._process_commands(action.actor, out, next_state)
            return next_state

        raise TypeError(f"unknown action: {action!r}")

    def properties(self) -> List[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self._within_boundary(self.cfg, state)

    # -- display (model.rs:428-548) ------------------------------------------

    def format_action(self, action: Any) -> str:
        if isinstance(action, Deliver):
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        if isinstance(action, SelectRandom):
            return f"{action.actor!r} select random {action.random!r}"
        return repr(action)

    def format_step(self, last_state: ActorModelState, action: Any) -> Optional[str]:
        def actor_step(last, returned, out) -> str:
            lines = [f"OUT: {out.commands!r}", ""]
            if returned is not None:
                lines += [f"NEXT_STATE: {returned!r}", "", f"PREV_STATE: {last!r}"]
            else:
                lines.append(f"UNCHANGED: {last!r}")
            return "\n".join(lines) + "\n"

        if isinstance(action, Drop):
            return f"DROP: {action.envelope!r}"
        if isinstance(action, Deliver):
            index = int(action.dst)
            if index >= len(last_state.actor_states):
                return None
            out = Out()
            returned = self.actors[index].on_msg(
                action.dst, last_state.actor_states[index], action.src, action.msg, out
            )
            return actor_step(last_state.actor_states[index], returned, out)
        if isinstance(action, Timeout):
            index = int(action.id)
            if index >= len(last_state.actor_states):
                return None
            out = Out()
            returned = self.actors[index].on_timeout(
                action.id, last_state.actor_states[index], action.timer, out
            )
            return actor_step(last_state.actor_states[index], returned, out)
        if isinstance(action, Crash):
            index = int(action.id)
            if index >= len(last_state.actor_states):
                return None
            return actor_step(last_state.actor_states[index], None, Out())
        if isinstance(action, SelectRandom):
            index = int(action.actor)
            if index >= len(last_state.actor_states):
                return None
            out = Out()
            returned = self.actors[index].on_random(
                action.actor, last_state.actor_states[index], action.random, out
            )
            return actor_step(last_state.actor_states[index], returned, out)
        return None

    def as_svg(self, path) -> Optional[str]:
        """Sequence diagram of a path: lifelines + message/timeout arrows.

        Role parity with model.rs:550-754 (layout is our own).
        """
        letter_px = 10
        actor_names = []
        for i, actor in enumerate(self.actors):
            name = actor.name()
            actor_names.append(f"{name} {i}" if name else str(i))
        n = len(actor_names)
        if n == 0:
            return None
        spacing = max(120, 20 + letter_px * max(len(s) for s in actor_names))
        steps = path.into_actions()
        height = 60 + 40 * (len(steps) + 1)
        width = spacing * n + 40

        def x(actor_index: int) -> int:
            return 20 + spacing * actor_index + spacing // 2

        svg = [
            f'<svg version="1.1" baseProfile="full" width="{width}" height="{height}" '
            'xmlns="http://www.w3.org/2000/svg">',
            "<style>"
            "text { font-family: monospace; font-size: 12px; }"
            ".lifeline { stroke: #888; stroke-dasharray: 4; }"
            ".msg { stroke: #111; stroke-width: 1.5; marker-end: url(#arrow); }"
            ".evt { fill: #0366d6; }"
            "</style>",
            '<defs><marker id="arrow" markerWidth="10" markerHeight="10" refX="9" '
            'refY="3" orient="auto"><path d="M0,0 L9,3 L0,6 z" fill="#111"/></marker></defs>',
        ]
        for i, label in enumerate(actor_names):
            svg.append(
                f'<text x="{x(i)}" y="20" text-anchor="middle">{_svg_escape(label)}</text>'
            )
            svg.append(
                f'<line class="lifeline" x1="{x(i)}" y1="30" x2="{x(i)}" y2="{height - 10}"/>'
            )
        y = 60
        for action in steps:
            if isinstance(action, Deliver):
                x1, x2 = x(int(action.src)), x(int(action.dst))
                if x1 == x2:
                    x2 += 10
                svg.append(f'<line class="msg" x1="{x1}" y1="{y}" x2="{x2}" y2="{y}"/>')
                mid = (x1 + x2) // 2
                svg.append(
                    f'<text x="{mid}" y="{y - 5}" text-anchor="middle">'
                    f"{_svg_escape(repr(action.msg))}</text>"
                )
            elif isinstance(action, Timeout):
                cx = x(int(action.id))
                svg.append(f'<circle class="evt" cx="{cx}" cy="{y}" r="5"/>')
                svg.append(
                    f'<text x="{cx + 10}" y="{y + 4}">'
                    f"timeout {_svg_escape(repr(action.timer))}</text>"
                )
            elif isinstance(action, Crash):
                cx = x(int(action.id))
                svg.append(
                    f'<text x="{cx}" y="{y + 4}" text-anchor="middle" fill="#c00">✖ crash</text>'
                )
            elif isinstance(action, Drop):
                env = action.envelope
                cx = x(int(env.src))
                svg.append(
                    f'<text x="{cx + 10}" y="{y + 4}" fill="#c00">'
                    f"drop {_svg_escape(repr(env.msg))}</text>"
                )
            elif isinstance(action, SelectRandom):
                cx = x(int(action.actor))
                svg.append(f'<circle class="evt" cx="{cx}" cy="{y}" r="5"/>')
                svg.append(
                    f'<text x="{cx + 10}" y="{y + 4}">'
                    f"random {_svg_escape(repr(action.random))}</text>"
                )
            y += 40
        svg.append("</svg>")
        return "".join(svg)


def _svg_escape(s: str) -> str:
    return s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
