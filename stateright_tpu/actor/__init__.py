"""The actor layer: event-driven actors compiled into checkable models.

Reference parity: the `stateright::actor` module (src/actor.rs and
src/actor/*). Layout:

  - `base`      — `Actor`, `Out`, commands, `ScriptActor`
  - `ids`       — `Id` (dense index ⇔ socket address), `majority`, `model_peers`
  - `network`   — `Envelope` + three `Network` delivery semantics
  - `timers`    — per-actor named-timer sets
  - `model_state` — `ActorModelState`, `RandomChoices`
  - `model`     — `ActorModel` + its action types
"""

from .base import (
    Actor,
    CancelTimer,
    ChooseRandom,
    Out,
    ScriptActor,
    Send,
    SetTimer,
    is_no_op,
    is_no_op_with_timer,
)
from .ids import Id, addr_from_id, id_from_addr, majority, model_peers
from .model import (
    ActorModel,
    Crash,
    Deliver,
    Drop,
    SelectRandom,
    Timeout,
    model_timeout,
)
from .model_state import ActorModelState, RandomChoices
from .network import Envelope, Network, Ordered, UnorderedDuplicating, UnorderedNonDuplicating
from .timers import Timers

__all__ = [
    "Actor",
    "ActorModel",
    "ActorModelState",
    "CancelTimer",
    "ChooseRandom",
    "Crash",
    "Deliver",
    "Drop",
    "Envelope",
    "Id",
    "Network",
    "Ordered",
    "Out",
    "RandomChoices",
    "ScriptActor",
    "SelectRandom",
    "Send",
    "SetTimer",
    "Timeout",
    "Timers",
    "UnorderedDuplicating",
    "UnorderedNonDuplicating",
    "addr_from_id",
    "id_from_addr",
    "is_no_op",
    "is_no_op_with_timer",
    "majority",
    "model_peers",
    "model_timeout",
]
