"""Register actor kit: a reusable client + history hooks for consistency
checking of register-like systems.

Reference parity: src/actor/register.rs. `RegisterClient` performs
`put_count` Puts (round-robin over the servers) followed by a Get; the
`record_invocations` / `record_returns` hooks bridge the message protocol
into a `ConsistencyTester` carried as the model's history variable.

Unlike the reference, no `RegisterActor::Server` wrapper type is needed:
Python actor lists are heterogeneous, so server actors are added to the
model directly (their state types fingerprint distinctly by construction).
Servers must still be added *before* clients — the client derives server
ids as `(index + k) % server_count` (register.rs:117-119).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..semantics.register import Read as RegisterRead
from ..semantics.register import ReadOk as RegisterReadOk
from ..semantics.register import Write as RegisterWrite
from ..semantics.register import WRITE_OK as REGISTER_WRITE_OK
from .base import Actor, Out
from .ids import Id
from .network import Envelope


# -- the wire protocol (register.rs:17-30) -----------------------------------

@dataclass(frozen=True)
class Internal:
    """A message specific to the register system's internal protocol."""

    msg: Any


@dataclass(frozen=True)
class Put:
    request_id: int
    value: Any


@dataclass(frozen=True)
class Get:
    request_id: int


@dataclass(frozen=True)
class PutOk:
    request_id: int


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: Any


# -- history hooks (register.rs:33-91) ---------------------------------------

def record_invocations(cfg, history, env: Envelope) -> Optional[Any]:
    """Pass to `ActorModel.with_record_msg_out`: Get→Read, Put→Write."""
    if isinstance(env.msg, Get):
        history = history.copy()
        history.on_invoke(env.src, RegisterRead())
        return history
    if isinstance(env.msg, Put):
        history = history.copy()
        history.on_invoke(env.src, RegisterWrite(env.msg.value))
        return history
    return None


def record_returns(cfg, history, env: Envelope) -> Optional[Any]:
    """Pass to `ActorModel.with_record_msg_in`: GetOk→ReadOk, PutOk→WriteOk."""
    if isinstance(env.msg, GetOk):
        history = history.copy()
        history.on_return(env.dst, RegisterReadOk(env.msg.value))
        return history
    if isinstance(env.msg, PutOk):
        history = history.copy()
        history.on_return(env.dst, REGISTER_WRITE_OK)
        return history
    return None


# -- the reusable client (register.rs:93-275) --------------------------------

@dataclass(frozen=True)
class RegisterClientState:
    awaiting: Optional[int]
    op_count: int


class RegisterClient(Actor):
    """Puts `put_count` values round-robin across servers, then Gets.

    Request ids are `(op_count) * index`, values walk 'A'..+client-index for
    the first put and 'Z'..-client-index for subsequent puts, exactly as the
    reference does (register.rs:150-232) so histories stay comparable.
    """

    def __init__(
        self,
        put_count: int,
        server_count: int,
        index: Optional[int] = None,
        server_ids: Optional[list] = None,
    ):
        """In the model, the client's index IS its dense `Id` and server ids
        are `Id(0..server_count)`. A real deployment's ids encode socket
        addresses instead, so `index` (the client's model index) and
        `server_ids` (the servers' deployment ids, model order) override
        the derivations — behavior is unchanged when both are None."""
        self.put_count = put_count
        self.server_count = server_count
        self.index = index
        self.server_ids = list(server_ids) if server_ids is not None else None

    def name(self) -> str:
        return "Client"

    def _index(self, id: Id) -> int:
        return self.index if self.index is not None else int(id)

    def _server(self, k: int) -> Id:
        if self.server_ids is not None:
            return Id(self.server_ids[k % self.server_count])
        return Id(k % self.server_count)

    def on_start(self, id: Id, out: Out) -> RegisterClientState:
        index = self._index(id)
        if self.index is None and index < self.server_count:
            raise ValueError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return RegisterClientState(awaiting=None, op_count=0)
        unique_request_id = index  # next will be 2 * index
        value = chr(ord("A") + index - self.server_count)
        out.send(self._server(index), Put(unique_request_id, value))
        return RegisterClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(
        self, id: Id, state: RegisterClientState, src: Id, msg: Any, out: Out
    ) -> Optional[RegisterClientState]:
        if state.awaiting is None:
            return None
        index = self._index(id)
        if isinstance(msg, PutOk) and msg.request_id == state.awaiting:
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    self._server(index + state.op_count),
                    Put(unique_request_id, value),
                )
            else:
                out.send(
                    self._server(index + state.op_count),
                    Get(unique_request_id),
                )
            return RegisterClientState(
                awaiting=unique_request_id, op_count=state.op_count + 1
            )
        if isinstance(msg, GetOk) and msg.request_id == state.awaiting:
            return RegisterClientState(awaiting=None, op_count=state.op_count + 1)
        return None
