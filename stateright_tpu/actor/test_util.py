"""Actor-test fixtures: the ping_pong system.

Reference parity: src/actor/actor_test_util.rs. Two actors bounce a counter
back and forth; each tracks how many messages it has processed. The model
exercises every ActorModel feature knob: lossy networks, history hooks,
boundaries, and all three property expectations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..core import Expectation
from .base import Actor, Out
from .ids import Id
from .model import ActorModel


@dataclass(frozen=True)
class Ping:
    value: int


@dataclass(frozen=True)
class Pong:
    value: int


class PingPongActor(Actor):
    """State is the count of messages processed (a plain int)."""

    def __init__(self, serve_to: Optional[Id] = None):
        self.serve_to = serve_to

    def on_start(self, id: Id, out: Out) -> int:
        if self.serve_to is not None:
            out.send(self.serve_to, Ping(0))
        return 0

    def on_msg(self, id: Id, state: int, src: Id, msg: Any, out: Out) -> Optional[int]:
        if isinstance(msg, Pong) and state == msg.value:
            out.send(src, Ping(msg.value + 1))
            return state + 1
        if isinstance(msg, Ping) and state == msg.value:
            out.send(src, Pong(msg.value))
            return state + 1
        return None


@dataclass
class PingPongCfg:
    maintains_history: bool = False
    max_nat: int = 1


def ping_pong_model(cfg: PingPongCfg) -> ActorModel:
    """History is the pair (#messages in, #messages out).

    Reference: actor_test_util.rs:60-126.
    """

    def record_msg_in(cfg, history, env):
        if cfg.maintains_history:
            msg_in, msg_out = history
            return (msg_in + 1, msg_out)
        return None

    def record_msg_out(cfg, history, env):
        if cfg.maintains_history:
            msg_in, msg_out = history
            return (msg_in, msg_out + 1)
        return None

    return (
        ActorModel(cfg=cfg, init_history=(0, 0))
        .actor(PingPongActor(serve_to=Id(1)))
        .actor(PingPongActor())
        .with_record_msg_in(record_msg_in)
        .with_record_msg_out(record_msg_out)
        .with_within_boundary(
            lambda cfg, state: all(count <= cfg.max_nat for count in state.actor_states)
        )
        .property(
            Expectation.ALWAYS,
            "delta within 1",
            lambda model, state: max(state.actor_states) - min(state.actor_states) <= 1,
        )
        .property(
            Expectation.SOMETIMES,
            "can reach max",
            lambda model, state: any(
                count == model.cfg.max_nat for count in state.actor_states
            ),
        )
        .property(
            Expectation.EVENTUALLY,
            "must reach max",
            lambda model, state: any(
                count == model.cfg.max_nat for count in state.actor_states
            ),
        )
        .property(
            Expectation.EVENTUALLY,
            "must exceed max",  # falsifiable due to the boundary
            lambda model, state: any(
                count == model.cfg.max_nat + 1 for count in state.actor_states
            ),
        )
        .property(
            Expectation.ALWAYS,
            "#in <= #out",
            lambda model, state: state.history[0] <= state.history[1],
        )
        .property(
            Expectation.EVENTUALLY,
            "#out <= #in + 1",
            lambda model, state: state.history[1] <= state.history[0] + 1,
        )
    )
