"""Ordered reliable link (ORL): middleware adding seq/ack/resend reliability.

Reference parity: src/actor/ordered_reliable_link.rs — a "perfect link" with
per-(src, dst) ordering, based on Cachin/Guerraoui/Rodrigues. Wraps any
actor so that its sends are sequenced, acked, resent on a timer, and
deduplicated on receipt. Assumes actors never restart (the sequencer state
is in-memory only; ordered_reliable_link.rs:9-10).

Deviation from the reference, by design: the reference's `on_timeout` for
user timers drops the wrapped actor's revised state on the floor (an
upstream bug at ordered_reliable_link.rs:177-188 — the `Cow::Owned` branch
is missing); here the revised state is applied.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from .base import Actor, CancelTimer, ChooseRandom, Out, Send, SetTimer, is_no_op
from .ids import Id


@dataclass(frozen=True)
class DeliverMsg:
    """A sequenced payload. Reference: MsgWrapper::Deliver."""

    seq: int
    msg: Any


@dataclass(frozen=True)
class AckMsg:
    """Acknowledges receipt of a sequenced payload. Reference: MsgWrapper::Ack."""

    seq: int


@dataclass(frozen=True)
class NetworkTimer:
    """The resend timer. Reference: TimerWrapper::Network."""


@dataclass(frozen=True)
class UserTimer:
    """A wrapped actor's own timer. Reference: TimerWrapper::User."""

    timer: Any


@dataclass(frozen=True)
class LinkState:
    """ORL bookkeeping around the wrapped actor's state.

    Reference: StateWrapper (ordered_reliable_link.rs:50-60).
    `msgs_pending_ack` maps seq -> (dst, msg); `last_delivered_seqs` maps
    src -> highest seq delivered (for receive-side dedup).
    """

    next_send_seq: int
    msgs_pending_ack: Tuple[Tuple[int, Tuple[Id, Any]], ...]
    last_delivered_seqs: Tuple[Tuple[Id, int], ...]
    wrapped_state: Any

    def pending(self) -> dict:
        return dict(self.msgs_pending_ack)

    def delivered(self) -> dict:
        return dict(self.last_delivered_seqs)


def _freeze(d: dict) -> tuple:
    return tuple(sorted(d.items()))


class OrderedReliableLink(Actor):
    """Wraps `wrapped_actor` with ordering/reliability/dedup logic.

    Reference: ActorWrapper (ordered_reliable_link.rs:28-35).
    """

    def __init__(self, wrapped_actor: Actor, resend_interval: Tuple[float, float] = (1.0, 2.0)):
        self.wrapped_actor = wrapped_actor
        self.resend_interval = resend_interval

    @staticmethod
    def with_default_timeout(wrapped_actor: Actor) -> "OrderedReliableLink":
        return OrderedReliableLink(wrapped_actor)

    def name(self) -> str:
        return self.wrapped_actor.name()

    # -- event handlers ------------------------------------------------------

    def on_start(self, id: Id, out: Out) -> LinkState:
        out.set_timer(NetworkTimer(), self.resend_interval)
        wrapped_out = Out()
        wrapped_state = self.wrapped_actor.on_start(id, wrapped_out)
        state = LinkState(
            next_send_seq=1,
            msgs_pending_ack=(),
            last_delivered_seqs=(),
            wrapped_state=wrapped_state,
        )
        return self._process_output(state, wrapped_out, out)

    def on_msg(self, id: Id, state: LinkState, src: Id, msg: Any, out: Out):
        if isinstance(msg, DeliverMsg):
            # Always ack to stop resends; drop if already delivered.
            out.send(src, AckMsg(msg.seq))
            if msg.seq <= state.delivered().get(src, 0):
                return None

            wrapped_out = Out()
            returned = self.wrapped_actor.on_msg(
                id, state.wrapped_state, src, msg.msg, wrapped_out
            )
            if is_no_op(returned, wrapped_out):
                return None

            delivered = state.delivered()
            delivered[src] = msg.seq
            next_state = LinkState(
                next_send_seq=state.next_send_seq,
                msgs_pending_ack=state.msgs_pending_ack,
                last_delivered_seqs=_freeze(delivered),
                wrapped_state=returned if returned is not None else state.wrapped_state,
            )
            return self._process_output(next_state, wrapped_out, out)

        if isinstance(msg, AckMsg):
            pending = state.pending()
            pending.pop(msg.seq, None)
            # The reference always clones here (ordered_reliable_link.rs:168);
            # a redundant ack dedups against the parent by fingerprint.
            return LinkState(
                next_send_seq=state.next_send_seq,
                msgs_pending_ack=_freeze(pending),
                last_delivered_seqs=state.last_delivered_seqs,
                wrapped_state=state.wrapped_state,
            )

        return None

    def on_timeout(self, id: Id, state: LinkState, timer: Any, out: Out):
        if isinstance(timer, NetworkTimer):
            out.set_timer(NetworkTimer(), self.resend_interval)
            for seq, (dst, msg) in sorted(state.msgs_pending_ack):
                out.send(dst, DeliverMsg(seq, msg))
            return None  # pruned as no-op-with-timer when nothing is pending

        if isinstance(timer, UserTimer):
            wrapped_out = Out()
            returned = self.wrapped_actor.on_timeout(
                id, state.wrapped_state, timer.timer, wrapped_out
            )
            if is_no_op(returned, wrapped_out):
                return None
            next_state = LinkState(
                next_send_seq=state.next_send_seq,
                msgs_pending_ack=state.msgs_pending_ack,
                last_delivered_seqs=state.last_delivered_seqs,
                wrapped_state=returned if returned is not None else state.wrapped_state,
            )
            return self._process_output(next_state, wrapped_out, out)

        return None

    def on_random(self, id: Id, state: LinkState, random: Any, out: Out):
        wrapped_out = Out()
        returned = self.wrapped_actor.on_random(
            id, state.wrapped_state, random, wrapped_out
        )
        if is_no_op(returned, wrapped_out):
            return None
        next_state = LinkState(
            next_send_seq=state.next_send_seq,
            msgs_pending_ack=state.msgs_pending_ack,
            last_delivered_seqs=state.last_delivered_seqs,
            wrapped_state=returned if returned is not None else state.wrapped_state,
        )
        return self._process_output(next_state, wrapped_out, out)

    # -- plumbing (ordered_reliable_link.rs:196-228) -------------------------

    def _process_output(self, state: LinkState, wrapped_out: Out, out: Out) -> LinkState:
        next_seq = state.next_send_seq
        pending = state.pending()
        for cmd in wrapped_out.commands:
            if isinstance(cmd, Send):
                out.send(cmd.dst, DeliverMsg(next_seq, cmd.msg))
                pending[next_seq] = (cmd.dst, cmd.msg)
                next_seq += 1
            elif isinstance(cmd, SetTimer):
                out.set_timer(UserTimer(cmd.timer), cmd.duration)
            elif isinstance(cmd, CancelTimer):
                out.cancel_timer(UserTimer(cmd.timer))
            elif isinstance(cmd, ChooseRandom):
                out.choose_random(cmd.key, cmd.choices)
            else:
                raise TypeError(f"unknown command: {cmd!r}")
        return LinkState(
            next_send_seq=next_seq,
            msgs_pending_ack=_freeze(pending),
            last_delivered_seqs=state.last_delivered_seqs,
            wrapped_state=state.wrapped_state,
        )
