"""In-memory network models: the "communication backend" that gets checked.

Reference parity: `Network`/`Envelope` and the deliverable iterators
(src/actor/network.rs:24-68, 203-316, 350-440). Three delivery semantics:

  - `UnorderedDuplicating`  — messages race and can be redelivered; the set of
    in-flight envelopes only grows (drops remove). Remembers the last
    delivered envelope so that a delivery that does not change actor state
    still produces a distinct fingerprint (network.rs:226-229).
  - `UnorderedNonDuplicating` — a multiset; delivery consumes one copy.
  - `Ordered` — per directed (src, dst) flow FIFO; only the head of each flow
    is deliverable (enforced here *and* in `ActorModel.actions`,
    model.rs:269-275).

Determinism note (a deliberate improvement over the reference): envelope
iteration is sorted by canonical encoding, so action enumeration order — and
therefore visit order and discovery traces — is fully deterministic across
runs and platforms, where the reference relies on fixed-seed HashMap order.

Messages may be any canonically-fingerprintable Python value (ints, strings,
tuples, frozen dataclasses, ...). Network values are cloned before mutation;
a `Network` held in an `ActorModelState` is never mutated in place.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, List, NamedTuple, Optional, Tuple

from ..fingerprint import canonical_bytes
from .ids import Id


class _EnvelopeBase(NamedTuple):
    src: Id
    dst: Id
    msg: Any


class Envelope(_EnvelopeBase):
    """The source, destination, and payload of an in-flight message.

    Reference: network.rs:24-29. `src`/`dst` are coerced to `Id` on
    construction so symmetry rewriting (which remaps `Id`s, never plain
    ints) sees every envelope, regardless of how the user built it.
    """

    __slots__ = ()

    def __new__(cls, src, dst, msg):
        return super().__new__(cls, Id(src), Id(dst), msg)


def _env_sort_key(env: Envelope) -> Tuple[int, int, bytes]:
    return (int(env.src), int(env.dst), canonical_bytes(env.msg))


class Network:
    """Base class for the three delivery semantics. Reference: network.rs:46-68."""

    # -- constructors --------------------------------------------------------

    @staticmethod
    def new_unordered_duplicating(envelopes: Iterable[Envelope] = ()) -> "Network":
        net = UnorderedDuplicating()
        for env in envelopes:
            net.send(env)
        return net

    @staticmethod
    def new_unordered_duplicating_with_last_msg(
        envelopes: Iterable[Envelope], last_msg: Optional[Envelope]
    ) -> "Network":
        net = UnorderedDuplicating()
        for env in envelopes:
            net.send(env)
        net.last_msg = last_msg
        return net

    @staticmethod
    def new_unordered_nonduplicating(envelopes: Iterable[Envelope] = ()) -> "Network":
        net = UnorderedNonDuplicating()
        for env in envelopes:
            net.send(env)
        return net

    @staticmethod
    def new_ordered(envelopes: Iterable[Envelope] = ()) -> "Network":
        net = Ordered()
        for env in envelopes:
            net.send(env)
        return net

    @staticmethod
    def names() -> List[str]:
        """Reference: network.rs:140-151."""
        return ["ordered", "unordered_duplicating", "unordered_nonduplicating"]

    @staticmethod
    def from_name(name: str) -> "Network":
        """Parse a network name from a CLI. Reference: network.rs:318-331."""
        if name == "ordered":
            return Network.new_ordered()
        if name == "unordered_duplicating":
            return Network.new_unordered_duplicating()
        if name == "unordered_nonduplicating":
            return Network.new_unordered_nonduplicating()
        raise ValueError(f"unable to parse network name: {name}")

    # -- value-object interface ---------------------------------------------

    def copy(self) -> "Network":
        raise NotImplementedError

    def send(self, envelope: Envelope) -> None:
        raise NotImplementedError

    def on_deliver(self, envelope: Envelope) -> None:
        raise NotImplementedError

    def on_drop(self, envelope: Envelope) -> None:
        raise NotImplementedError

    def iter_deliverable(self) -> Iterator[Envelope]:
        """Envelopes a `Deliver` action may target, in deterministic order."""
        raise NotImplementedError

    def iter_all(self) -> Iterator[Envelope]:
        """Every in-flight envelope, including multiset/queue duplicates."""
        raise NotImplementedError

    def __len__(self) -> int:
        return sum(1 for _ in self.iter_all())

    def fingerprint_key(self):
        raise NotImplementedError

    def __hash__(self) -> int:
        return hash(canonical_bytes(self.fingerprint_key()))

    def rewrite_with(self, plan) -> "Network":
        raise NotImplementedError


class UnorderedDuplicating(Network):
    """Unordered + redeliverable: a grow-only set of envelopes (drops remove),
    plus the last delivered envelope. Reference: network.rs:52, 226-229.
    """

    __slots__ = ("envelopes", "last_msg", "_sorted")

    def __init__(self):
        self.envelopes: set = set()
        self.last_msg: Optional[Envelope] = None
        self._sorted: Optional[List[Envelope]] = None  # lazy, shared via copy()

    def copy(self) -> "UnorderedDuplicating":
        new = UnorderedDuplicating.__new__(UnorderedDuplicating)
        new.envelopes = set(self.envelopes)
        new.last_msg = self.last_msg
        new._sorted = self._sorted
        return new

    def send(self, envelope: Envelope) -> None:
        if envelope not in self.envelopes:
            self.envelopes.add(envelope)
            self._sorted = None

    def on_deliver(self, envelope: Envelope) -> None:
        # Delivery does not consume: the message may race/redeliver. Remember
        # it so no-op deliveries still perturb the fingerprint.
        self.last_msg = envelope

    def on_drop(self, envelope: Envelope) -> None:
        if envelope in self.envelopes:
            self.envelopes.discard(envelope)
            self._sorted = None

    def iter_deliverable(self) -> Iterator[Envelope]:
        if self._sorted is None:
            self._sorted = sorted(self.envelopes, key=_env_sort_key)
        return iter(self._sorted)

    def iter_all(self) -> Iterator[Envelope]:
        return self.iter_deliverable()

    def fingerprint_key(self):
        return (frozenset(self.envelopes), self.last_msg)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, UnorderedDuplicating)
            and self.envelopes == other.envelopes
            and self.last_msg == other.last_msg
        )

    __hash__ = Network.__hash__

    def __repr__(self) -> str:
        return (
            f"UnorderedDuplicating({sorted(self.envelopes, key=_env_sort_key)!r}, "
            f"last_msg={self.last_msg!r})"
        )

    def rewrite_with(self, plan) -> "UnorderedDuplicating":
        new = UnorderedDuplicating()
        new.envelopes = {plan.rewrite(env) for env in self.envelopes}
        new.last_msg = None if self.last_msg is None else plan.rewrite(self.last_msg)
        return new


class UnorderedNonDuplicating(Network):
    """Unordered, delivered at most once: a multiset. Reference: network.rs:55."""

    __slots__ = ("counts", "_sorted")

    def __init__(self):
        self.counts: dict = {}
        self._sorted: Optional[List[Envelope]] = None  # lazy, shared via copy()

    def copy(self) -> "UnorderedNonDuplicating":
        new = UnorderedNonDuplicating.__new__(UnorderedNonDuplicating)
        new.counts = dict(self.counts)
        new._sorted = self._sorted
        return new

    def send(self, envelope: Envelope) -> None:
        if envelope in self.counts:
            self.counts[envelope] += 1
        else:
            self.counts[envelope] = 1
            self._sorted = None

    def _remove_one(self, envelope: Envelope) -> None:
        count = self.counts.get(envelope)
        if count is None:
            raise KeyError(f"envelope not found: {envelope!r}")
        if count == 1:
            del self.counts[envelope]
            self._sorted = None
        else:
            self.counts[envelope] = count - 1

    def on_deliver(self, envelope: Envelope) -> None:
        self._remove_one(envelope)

    def on_drop(self, envelope: Envelope) -> None:
        self._remove_one(envelope)

    def iter_deliverable(self) -> Iterator[Envelope]:
        if self._sorted is None:
            self._sorted = sorted(self.counts, key=_env_sort_key)
        return iter(self._sorted)

    def iter_all(self) -> Iterator[Envelope]:
        for env in self.iter_deliverable():
            for _ in range(self.counts[env]):
                yield env

    def fingerprint_key(self):
        return dict(self.counts)

    def __eq__(self, other) -> bool:
        return isinstance(other, UnorderedNonDuplicating) and self.counts == other.counts

    __hash__ = Network.__hash__

    def __repr__(self) -> str:
        return f"UnorderedNonDuplicating({self.counts!r})"

    def rewrite_with(self, plan) -> "UnorderedNonDuplicating":
        new = UnorderedNonDuplicating()
        for env, count in self.counts.items():
            new.counts[plan.rewrite(env)] = count
        return new


class Ordered(Network):
    """Per-(src, dst)-flow FIFO ordering; no cross-flow ordering.

    Reference: network.rs:58-68. Only the head of each flow is deliverable.
    Empty flows are removed so that removing a message is the exact inverse
    of adding it (canonical form; network.rs:243-247).
    """

    __slots__ = ("flows",)

    def __init__(self):
        self.flows: dict = {}  # (src, dst) -> list of msgs, oldest first

    def copy(self) -> "Ordered":
        new = Ordered.__new__(Ordered)
        new.flows = {flow: list(msgs) for flow, msgs in self.flows.items()}
        return new

    def send(self, envelope: Envelope) -> None:
        self.flows.setdefault((envelope.src, envelope.dst), []).append(envelope.msg)

    def _remove_first(self, envelope: Envelope) -> None:
        flow = (envelope.src, envelope.dst)
        msgs = self.flows.get(flow)
        if msgs is None:
            raise KeyError(f"flow not found: {flow!r}")
        msgs.remove(envelope.msg)  # first occurrence
        if not msgs:
            del self.flows[flow]

    def on_deliver(self, envelope: Envelope) -> None:
        self._remove_first(envelope)

    def on_drop(self, envelope: Envelope) -> None:
        self._remove_first(envelope)

    def iter_deliverable(self) -> Iterator[Envelope]:
        for (src, dst) in sorted(self.flows):
            yield Envelope(src, dst, self.flows[(src, dst)][0])

    def iter_all(self) -> Iterator[Envelope]:
        for (src, dst) in sorted(self.flows):
            for msg in self.flows[(src, dst)]:
                yield Envelope(src, dst, msg)

    def fingerprint_key(self):
        return {flow: tuple(msgs) for flow, msgs in self.flows.items()}

    def __eq__(self, other) -> bool:
        return isinstance(other, Ordered) and self.flows == other.flows

    __hash__ = Network.__hash__

    def __repr__(self) -> str:
        return f"Ordered({self.flows!r})"

    def rewrite_with(self, plan) -> "Ordered":
        new = Ordered()
        for (src, dst), msgs in self.flows.items():
            new.flows[(plan.rewrite(src), plan.rewrite(dst))] = [
                plan.rewrite(m) for m in msgs
            ]
        return new
