"""ActorModelState: a snapshot in time of the entire actor system.

Reference: src/actor/model_state.rs. Holds per-actor states (structurally
shared across system states — the Python analogue of the reference's
`Arc<State>` COW discipline), the network, pending timers, pending random
choices, crash flags, and the auxiliary history.

Hash/equality parity (model_state.rs:121-182): `crashed` and
`random_choices` are **excluded** from both the fingerprint and equality —
two states differing only in crash flags or pending random choices collapse
into one visited-set entry, exactly as in the reference.

The symmetry `representative()` sorts actor states into a canonical order
and rewrites every embedded `Id` accordingly (model_state.rs:163-182).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..fingerprint import canonical_bytes, fingerprint
from ..symmetry import RewritePlan
from .ids import Id
from .network import Network
from .timers import Timers


class RandomChoices:
    """Pending `choose_random` branches for one actor: key -> choice list.

    Reference: model_state.rs:24-62.
    """

    __slots__ = ("map",)

    def __init__(self, map: Optional[Dict[str, Tuple[Any, ...]]] = None):
        self.map: Dict[str, Tuple[Any, ...]] = dict(map) if map else {}

    def copy(self) -> "RandomChoices":
        return RandomChoices(self.map)

    def insert(self, key: str, choices) -> None:
        self.map[key] = tuple(choices)

    def remove(self, key: str) -> None:
        self.map.pop(key, None)

    def __eq__(self, other) -> bool:
        return isinstance(other, RandomChoices) and self.map == other.map

    def __repr__(self) -> str:
        return f"RandomChoices({self.map!r})"

    def fingerprint_key(self):
        return self.map

    def rewrite_with(self, plan) -> "RandomChoices":
        return RandomChoices(
            {k: tuple(plan.rewrite(c) for c in v) for k, v in self.map.items()}
        )


class ActorModelState:
    """System snapshot: actor states + network + timers + randoms + crashes + history."""

    __slots__ = (
        "actor_states",
        "network",
        "timers_set",
        "random_choices",
        "crashed",
        "history",
    )

    def __init__(
        self,
        actor_states: List[Any],
        network: Network,
        timers_set: List[Timers],
        random_choices: List[RandomChoices],
        crashed: List[bool],
        history: Any,
    ):
        self.actor_states = list(actor_states)
        self.network = network
        self.timers_set = list(timers_set)
        self.random_choices = list(random_choices)
        self.crashed = list(crashed)
        self.history = history

    def clone(self) -> "ActorModelState":
        """A next-state scratch copy: containers are copied, the per-actor
        states themselves are shared (the `Arc<State>` analogue)."""
        return ActorModelState(
            actor_states=list(self.actor_states),
            network=self.network.copy(),
            timers_set=[t.copy() for t in self.timers_set],
            random_choices=[r.copy() for r in self.random_choices],
            crashed=list(self.crashed),
            history=self.history,
        )

    # -- identity (crashed + random_choices excluded; model_state.rs:121-162) --

    def fingerprint_key(self):
        return (
            tuple(self.actor_states),
            self.history,
            tuple(self.timers_set),
            self.network,
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ActorModelState)
            and self.actor_states == other.actor_states
            and self.history == other.history
            and self.timers_set == other.timers_set
            and self.network == other.network
        )

    def __hash__(self) -> int:
        return fingerprint(self)

    def __repr__(self) -> str:
        return (
            f"ActorModelState(actor_states={self.actor_states!r}, "
            f"history={self.history!r}, timers_set={self.timers_set!r}, "
            f"network={self.network!r}, crashed={self.crashed!r})"
        )

    # -- symmetry (model_state.rs:163-182) -----------------------------------

    def representative(self) -> "ActorModelState":
        plan = RewritePlan.from_values_to_sort(
            Id, [canonical_bytes(s) for s in self.actor_states]
        )
        return ActorModelState(
            actor_states=plan.reindex(self.actor_states),
            network=self.network.rewrite_with(plan),
            timers_set=plan.reindex(self.timers_set),
            random_choices=plan.reindex(self.random_choices),
            crashed=plan.reindex(self.crashed),
            history=plan.rewrite(self.history),
        )
