"""Write-once-register actor kit: like the register kit plus `PutFail`.

Reference parity: src/actor/write_once_register.rs. The message protocol
adds `PutFail` (a rejected write), `record_returns` maps it to
`WriteFail`, and the client treats it like `PutOk` for sequencing purposes
(write_once_register.rs:247-266). Message `rewrite_with` hooks keep the
protocol symmetric under id permutation (write_once_register.rs:300-332) —
request ids and values pass through, only embedded internal messages are
rewritten.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..semantics.write_once_register import Read as WORead
from ..semantics.write_once_register import ReadOk as WOReadOk
from ..semantics.write_once_register import Write as WOWrite
from ..semantics.write_once_register import WRITE_FAIL as WO_WRITE_FAIL
from ..semantics.write_once_register import WRITE_OK as WO_WRITE_OK
from .base import Actor, Out
from .ids import Id
from .network import Envelope


# -- the wire protocol (write_once_register.rs:16-31) ------------------------

@dataclass(frozen=True)
class Internal:
    msg: Any

    def rewrite_with(self, plan):
        return Internal(plan.rewrite(self.msg))


@dataclass(frozen=True)
class Put:
    request_id: int
    value: Any

    def rewrite_with(self, plan):
        return self  # request ids and values carry no actor ids


@dataclass(frozen=True)
class Get:
    request_id: int

    def rewrite_with(self, plan):
        return self


@dataclass(frozen=True)
class PutOk:
    request_id: int

    def rewrite_with(self, plan):
        return self


@dataclass(frozen=True)
class PutFail:
    request_id: int

    def rewrite_with(self, plan):
        return self


@dataclass(frozen=True)
class GetOk:
    request_id: int
    value: Any

    def rewrite_with(self, plan):
        return self


# -- history hooks (write_once_register.rs:34-97) ----------------------------

def record_invocations(cfg, history, env: Envelope) -> Optional[Any]:
    """Pass to `ActorModel.with_record_msg_out`: Get→Read, Put→Write."""
    if isinstance(env.msg, Get):
        history = history.copy()
        history.on_invoke(env.src, WORead())
        return history
    if isinstance(env.msg, Put):
        history = history.copy()
        history.on_invoke(env.src, WOWrite(env.msg.value))
        return history
    return None


def record_returns(cfg, history, env: Envelope) -> Optional[Any]:
    """Pass to `ActorModel.with_record_msg_in`: GetOk→ReadOk, PutOk→WriteOk,
    PutFail→WriteFail."""
    if isinstance(env.msg, GetOk):
        history = history.copy()
        history.on_return(env.dst, WOReadOk(env.msg.value))
        return history
    if isinstance(env.msg, PutOk):
        history = history.copy()
        history.on_return(env.dst, WO_WRITE_OK)
        return history
    if isinstance(env.msg, PutFail):
        history = history.copy()
        history.on_return(env.dst, WO_WRITE_FAIL)
        return history
    return None


# -- the reusable client (write_once_register.rs:100-298) --------------------

@dataclass(frozen=True)
class WORegisterClientState:
    awaiting: Optional[int]
    op_count: int

    def rewrite_with(self, plan):
        return self


class WORegisterClient(Actor):
    """Puts `put_count` values round-robin across servers, then Gets.

    `PutFail` advances the sequence just like `PutOk`
    (write_once_register.rs:247-266).
    """

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, out: Out) -> WORegisterClientState:
        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "WORegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return WORegisterClientState(awaiting=None, op_count=0)
        unique_request_id = index  # next will be 2 * index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(unique_request_id, value))
        return WORegisterClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(
        self, id: Id, state: WORegisterClientState, src: Id, msg: Any, out: Out
    ) -> Optional[WORegisterClientState]:
        if state.awaiting is None:
            return None
        index = int(id)
        if (
            isinstance(msg, (PutOk, PutFail))
            and msg.request_id == state.awaiting
        ):
            unique_request_id = (state.op_count + 1) * index
            if state.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + state.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                out.send(
                    Id((index + state.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            return WORegisterClientState(
                awaiting=unique_request_id, op_count=state.op_count + 1
            )
        if isinstance(msg, GetOk) and msg.request_id == state.awaiting:
            return WORegisterClientState(awaiting=None, op_count=state.op_count + 1)
        return None


# -- a bundled demo system (speclint dogfood / examples) ----------------------


class FirstWriteWinsServer(Actor):
    """Accepts only the first write; later writes of other values fail
    (the minimal server honoring write-once semantics)."""

    def on_start(self, id: Id, out: Out) -> None:
        return None

    def on_msg(self, id: Id, state: Any, src: Id, msg: Any, out: Out):
        if isinstance(msg, Put):
            if state is None or state == msg.value:
                out.send(src, PutOk(msg.request_id))
                return msg.value
            out.send(src, PutFail(msg.request_id))
            return None
        if isinstance(msg, Get):
            out.send(src, GetOk(msg.request_id, state))
            return None
        return None


def wo_register_model(client_count: int = 2):
    """One first-write-wins server + `client_count` clients, checked for
    linearizability against `WORegister` via the kit's history hooks.
    The `write-once-register` shorthand in the speclint CLI."""
    from .. import Expectation
    from ..semantics import LinearizabilityTester
    from ..semantics.write_once_register import WORegister
    from .model import ActorModel
    from .network import Network

    return (
        ActorModel(init_history=LinearizabilityTester(WORegister()))
        .actor(FirstWriteWinsServer())
        .add_actors(
            WORegisterClient(put_count=1, server_count=1)
            for _ in range(client_count)
        )
        .with_init_network(Network.new_unordered_nonduplicating())
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, state: state.history.serialized_history()
            is not None,
        )
        .property(
            Expectation.SOMETIMES,
            "a write fails",
            lambda model, state: any(
                isinstance(env.msg, PutFail)
                for env in state.network.iter_deliverable()
            ),
        )
        .with_record_msg_in(record_returns)
        .with_record_msg_out(record_invocations)
    )
