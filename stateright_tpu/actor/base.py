"""The Actor abstraction: event-driven state machines that emit commands.

Reference parity: the `Actor` trait and `Command`/`Out` types
(src/actor.rs:158-389). An actor initializes state in `on_start`, then
reacts to events — `on_msg`, `on_timeout`, `on_random` — by returning a
revised state and recording commands on the `Out` buffer.

Python adaptation of the reference's copy-on-write (`Cow<State>`) protocol:
event handlers receive the current state (treat it as immutable) and return
either a **new state value** (the `Cow::Owned` case) or **None** meaning
"state unchanged" (the `Cow::Borrowed` case). Returning None with an empty
`Out` is a no-op, which the model checker prunes (actor.rs:269-274).

The reference's `Choice<A, B>` machinery for heterogeneous actor systems
(actor.rs:391-548) is unnecessary here: Python lists hold actors of
different classes natively, and distinct state dataclass types fingerprint
distinctly by construction. Just mix actor instances in `ActorModel.actors`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, List, Optional, Tuple

from .ids import Id


# ---------------------------------------------------------------------------
# Commands (actor.rs:160-166)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Send:
    """Send `msg` to `dst`."""

    dst: Id
    msg: Any


@dataclass(frozen=True)
class SetTimer:
    """Set/reset a named timer. The duration range is only meaningful to the
    real-network runtime; the checker abstracts it away (model.rs:73-78)."""

    timer: Any
    duration: Tuple[float, float] = (0.0, 0.0)


@dataclass(frozen=True)
class CancelTimer:
    timer: Any


@dataclass(frozen=True)
class ChooseRandom:
    """Record a nondeterministic choice: a branch per element of `choices`.
    An empty `choices` removes any pending choice under `key`."""

    key: str
    choices: Tuple[Any, ...]


class Out:
    """Buffer of commands recorded by an actor during one event.

    Reference: `Out` (actor.rs:174-258).
    """

    __slots__ = ("commands",)

    def __init__(self):
        self.commands: List[Any] = []

    def send(self, recipient: Id, msg: Any) -> None:
        self.commands.append(Send(Id(recipient), msg))

    def broadcast(self, recipients: Iterable[Id], msg: Any) -> None:
        for recipient in recipients:
            self.send(recipient, msg)

    def set_timer(self, timer: Any, duration: Tuple[float, float] = (0.0, 0.0)) -> None:
        self.commands.append(SetTimer(timer, duration))

    def cancel_timer(self, timer: Any) -> None:
        self.commands.append(CancelTimer(timer))

    def choose_random(self, key: str, choices: Iterable[Any]) -> None:
        self.commands.append(ChooseRandom(key, tuple(choices)))

    def remove_random(self, key: str) -> None:
        self.commands.append(ChooseRandom(key, ()))

    def append(self, other: "Out") -> None:
        self.commands.extend(other.commands)
        other.commands.clear()

    def __iter__(self):
        return iter(self.commands)

    def __len__(self) -> int:
        return len(self.commands)

    def __repr__(self) -> str:
        return f"Out({self.commands!r})"


def is_no_op(returned_state: Optional[Any], out: Out) -> bool:
    """True when the handler neither revised state nor emitted commands.

    Reference: actor.rs:269-274 (Cow::Borrowed + empty out).
    """
    return returned_state is None and not out.commands


def is_no_op_with_timer(returned_state: Optional[Any], out: Out, timer: Any) -> bool:
    """True when the handler only re-set the very timer that fired.

    Reference: actor.rs:276-287.
    """
    if returned_state is not None or len(out.commands) != 1:
        return False
    cmd = out.commands[0]
    return isinstance(cmd, SetTimer) and cmd.timer == timer


# ---------------------------------------------------------------------------
# The Actor interface (actor.rs:293-389)
# ---------------------------------------------------------------------------

class Actor:
    """An event-driven state machine.

    Handlers return the revised state, or None for "unchanged". States must
    be treated as immutable values (frozen dataclasses, tuples, ints, ...):
    never mutate the `state` argument in place.
    """

    def on_start(self, id: Id, out: Out) -> Any:
        """Return the initial state, optionally emitting commands."""
        raise NotImplementedError

    def on_msg(self, id: Id, state: Any, src: Id, msg: Any, out: Out) -> Optional[Any]:
        """React to a delivered message. None means state unchanged."""
        return None

    def on_timeout(self, id: Id, state: Any, timer: Any, out: Out) -> Optional[Any]:
        """React to a fired timer. None means state unchanged."""
        return None

    def on_random(self, id: Id, state: Any, random: Any, out: Out) -> Optional[Any]:
        """React to a resolved random choice. None means state unchanged."""
        return None

    def name(self) -> str:
        return ""


class ScriptActor(Actor):
    """Sends a fixed message sequence, one message per delivery received.

    The Python port of the reference's `Vec<(Id, Msg)>` actor impl
    (actor.rs:565-602); useful for modeling external test inputs.
    State is the index of the next script entry.
    """

    def __init__(self, script: List[Tuple[Id, Any]]):
        self.script = list(script)

    def on_start(self, id: Id, out: Out) -> int:
        if self.script:
            dst, msg = self.script[0]
            out.send(dst, msg)
            return 1
        return 0

    def on_msg(self, id: Id, state: int, src: Id, msg: Any, out: Out) -> Optional[int]:
        if state < len(self.script):
            dst, next_msg = self.script[state]
            out.send(dst, next_msg)
            return state + 1
        return None
