"""Real-network execution: run the *same actor code* over UDP sockets.

Reference parity: src/actor/spawn.rs — the framework's dual-execution
property: model-check an actor system, then deploy it unchanged. Each actor
runs an event loop bound to the UDP socket its `Id` encodes
(Id ⇔ SocketAddrV4 bijection, ids.py): receive → deserialize → `on_msg`;
timer/random interrupts are implemented by bounding the socket read timeout
with the earliest pending deadline (spawn.rs:92-142). Serialization is
pluggable; `json_serializer`/`json_deserializer` handle dataclass-based
message types out of the box.

Two engines run this event loop:

  - the portable Python threading engine (this module), and
  - the native C++ event-loop core (`stateright_tpu/native/core.cpp`,
    compiled to `_core.so` by `python -m stateright_tpu.native.build` and
    auto-built on first use when a C++ compiler is available) that owns the
    sockets, deadline map, and poll loop, calling back into Python only for
    the protocol logic — the analogue of the reference keeping its runtime
    in compiled code (spawn.rs:64-154).

`engine="auto"` (default) prefers the native core and falls back to Python
threads; `"native"` / `"python"` force one.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import logging
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

from .base import Actor, CancelTimer, ChooseRandom, Out, Send, SetTimer
from .ids import Id, addr_from_id

_PRACTICALLY_NEVER = float("inf")
_RECV_BUF = 65_535  # matches the reference's receive buffer (spawn.rs:82)


# ---------------------------------------------------------------------------
# JSON serde for dataclass message protocols.
# ---------------------------------------------------------------------------

def json_serializer(msg: Any) -> bytes:
    """Encode a message as JSON: dataclasses become ["TypeName", field...]."""
    return json.dumps(_to_jsonable(msg)).encode("utf-8")


def _to_jsonable(value: Any):
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return [type(value).__name__] + [
            _to_jsonable(getattr(value, f.name))
            for f in dataclasses.fields(value)
        ]
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return value


def make_json_deserializer(*message_types) -> Callable[[bytes], Any]:
    """A deserializer recognizing ["TypeName", field...] for the given types."""
    by_name = {t.__name__: t for t in message_types}

    def deserialize(data: bytes) -> Any:
        decoded = json.loads(data.decode("utf-8"))
        return _from_jsonable(decoded, by_name)

    return deserialize


def _from_jsonable(value, by_name):
    if isinstance(value, list) and value and isinstance(value[0], str) and value[0] in by_name:
        cls = by_name[value[0]]
        fields = [_from_jsonable(v, by_name) for v in value[1:]]
        return cls(*fields)
    if isinstance(value, list):
        # TUPLES, not lists: dataclass fields like paxos ballots are
        # (round, id) tuples that handlers compare (`msg.ballot >
        # state.ballot`); a JSON round-trip to list would make those
        # comparisons raise inside the actor loop (messages silently
        # dropped). JSON has no list/tuple distinction, so tuple is the
        # faithful decoding for message payloads.
        return tuple(_from_jsonable(v, by_name) for v in value)
    return value


def json_deserializer(data: bytes) -> Any:
    """Plain-JSON deserializer (no dataclass reconstruction)."""
    return json.loads(data.decode("utf-8"))


# ---------------------------------------------------------------------------
# The event loop (one per actor).
# ---------------------------------------------------------------------------

class _ActorLoop:
    def __init__(
        self,
        id: Id,
        actor: Actor,
        serialize,
        deserialize,
        stop: threading.Event,
        index: int = 0,
        recorder=None,
        injector=None,
        netobs=None,
    ):
        self.id = Id(id)
        self.actor = actor
        self.serialize = serialize
        self.deserialize = deserialize
        self.stop = stop
        self.index = index
        self.recorder = recorder  # conformance.TraceRecorder or None
        self.injector = injector  # conformance.FaultInjector or None
        self.netobs = netobs  # obs.netobs.NetObs or None
        # interrupt key -> absolute deadline; keys are ("t", timer) / ("r", random)
        self.next_interrupts: Dict[Any, float] = {}
        self.state: Any = None
        ip, port = addr_from_id(self.id)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))

    def _raw_send(self, payload: bytes, addr) -> None:
        try:
            self.sock.sendto(payload, addr)
            if self.netobs is not None:
                self.netobs.transmit()
        except OSError as e:
            log.warning(
                "actor %s: sendto %s failed: %s", self.id, addr, e
            )  # fire-and-forget (spawn.rs:188-196)

    def _on_command(self, cmd) -> None:
        import random as _random

        now = time.monotonic()
        if isinstance(cmd, Send):
            if self.netobs is not None:
                self.netobs.command(self.index, "send")
            try:
                payload = self.serialize(cmd.msg)
            except Exception as e:
                # Dropped like the reference, but logged (spawn.rs:178-186
                # logs these events); silent drops make network debugging
                # miserable.
                log.warning(
                    "actor %s: failed to serialize %r to %s: %s",
                    self.id, cmd.msg, cmd.dst, e,
                )
                return
            addr = addr_from_id(cmd.dst)
            if self.injector is not None:
                self.injector.transmit(
                    int(self.id),
                    int(cmd.dst),
                    payload,
                    lambda data, _addr=addr: self._raw_send(data, _addr),
                    recorder=self.recorder,
                    actor_index=self.index,
                )
            else:
                self._raw_send(payload, addr)
        elif isinstance(cmd, SetTimer):
            if self.netobs is not None:
                self.netobs.command(self.index, "timer_set")
            lo, hi = cmd.duration
            duration = _random.uniform(lo, hi) if lo < hi else lo
            self.next_interrupts[("t", cmd.timer)] = now + duration
        elif isinstance(cmd, CancelTimer):
            key = ("t", cmd.timer)
            if key in self.next_interrupts:
                self.next_interrupts[key] = _PRACTICALLY_NEVER
        elif isinstance(cmd, ChooseRandom):
            if not cmd.choices:
                return
            # The runtime resolves the nondeterminism the checker explored:
            # pick one choice at a random future instant (spawn.rs:216-231).
            chosen = _random.choice(list(cmd.choices))
            self.next_interrupts[("r", chosen)] = now + _random.uniform(0.0, 10.0)

    def _dispatch(self, out: Out) -> None:
        for cmd in out.commands:
            self._on_command(cmd)

    def _record(self, kind: str, out: Out, duration=None, **fields) -> None:
        # Recording precedes _dispatch so command events hit the trace
        # before the wire: an actor's `send` line is causally ordered
        # before the receiver's `deliver` line.
        if self.netobs is not None:
            self.netobs.handler(self.index, kind, duration)
        if self.recorder is not None:
            self.recorder.record_handler(
                self.index, kind, self.state, out, duration=duration, **fields
            )

    def run(self) -> None:
        out = Out()
        t0 = time.monotonic()
        self.state = self.actor.on_start(self.id, out)
        self._record("init", out, duration=time.monotonic() - t0)
        self._dispatch(out)

        while not self.stop.is_set():
            out = Out()
            if self.next_interrupts:
                min_key = min(self.next_interrupts, key=self.next_interrupts.get)
                min_deadline = self.next_interrupts[min_key]
            else:
                min_key, min_deadline = None, _PRACTICALLY_NEVER
            max_wait = min_deadline - time.monotonic()

            if max_wait > 0:
                self.sock.settimeout(min(max_wait, 0.25))  # 0.25s stop poll
                try:
                    data, src_addr = self.sock.recvfrom(_RECV_BUF)
                except socket.timeout:
                    continue
                except OSError:
                    continue
                try:
                    msg = self.deserialize(data)
                except Exception:
                    continue  # unparseable: ignore (spawn.rs:123-127)
                src = Id.from_addr(*src_addr)
                t0 = time.monotonic()
                returned = self.actor.on_msg(self.id, self.state, src, msg, out)
                dur = time.monotonic() - t0
                event = ("deliver", {"src": int(src), "msg": msg})
            else:
                del self.next_interrupts[min_key]  # interrupt consumed
                kind, payload = min_key
                t0 = time.monotonic()
                if kind == "t":
                    returned = self.actor.on_timeout(self.id, self.state, payload, out)
                    event = ("timeout", {"timer": payload})
                else:
                    returned = self.actor.on_random(self.id, self.state, payload, out)
                    event = ("random", {"value": payload})
                dur = time.monotonic() - t0

            if returned is not None:
                self.state = returned
            self._record(event[0], out, duration=dur, **event[1])
            self._dispatch(out)

        self.sock.close()


def spawn(
    serialize: Callable[[Any], bytes],
    deserialize: Callable[[bytes], Any],
    actors: List[Tuple[Any, Actor]],
    background: bool = False,
    engine: str = "auto",
    record=None,
    faults=None,
    netobs=None,
) -> "SpawnHandle":
    """Run each actor on its own thread with a UDP socket.

    Reference: `spawn()` (spawn.rs:64-154). `actors` pairs ids (or
    (ip, port) tuples) with actor instances. Blocks forever unless
    `background=True`, in which case a `SpawnHandle` controls shutdown —
    a capability the reference lacks, added for testability.

    `engine="native"` requires the C++ runtime extension; `"auto"` uses it
    when available, falling back to Python threads.

    `record` (a path or `conformance.TraceRecorder`) captures every
    handler execution and command as a JSONL TraceEvent stream checkable
    via `conformance.check_trace`; `faults` (a `conformance.FaultPlan`,
    ``"SEED[,drop[,dup[,delay[,reorder]]]]"`` spec string, or
    `FaultInjector`) fuzzes outgoing datagrams with a seeded
    deterministic schedule. Both work identically on either engine.

    `netobs` turns on live deployment metrics (`obs.netobs.NetObs`):
    ``True``/a `NetObs` enables them, ``False`` disables, and ``None``
    (the default) enables them whenever the run is recorded or faulted.
    Read the registry via ``handle.telemetry()``.
    """
    recorder = injector = None
    if record is not None or faults is not None:
        # Imported lazily: conformance imports this module's serde helpers.
        from ..conformance import as_injector, as_recorder

        recorder = as_recorder(record)
        injector = as_injector(faults)
    from ..obs.netobs import as_netobs

    nob = as_netobs(netobs, default=recorder is not None or injector is not None)
    if nob is not None:
        if recorder is not None and recorder.netobs is None:
            recorder.netobs = nob
        if injector is not None and injector.netobs is None:
            injector.netobs = nob

    resolved: List[Tuple[Id, Actor]] = []
    for id_or_addr, actor in actors:
        if isinstance(id_or_addr, tuple):
            resolved.append((Id.from_addr(*id_or_addr), actor))
        else:
            resolved.append((Id(id_or_addr), actor))

    if engine in ("auto", "native"):
        native = _native_runtime()
        if native is not None:
            return native.spawn(
                serialize,
                deserialize,
                resolved,
                background,
                recorder=recorder,
                injector=injector,
                netobs=nob,
            )
        if engine == "native":
            raise RuntimeError(
                "native spawn engine requested but the C++ runtime extension "
                "is not built (run: python -m stateright_tpu.native.build)"
            )

    if recorder is not None:
        recorder.attach(
            resolved, engine="python",
            plan=injector.plan if injector is not None else None,
        )
    if nob is not None:
        nob.attach(resolved, "python")
    stop = threading.Event()
    loops = [
        _ActorLoop(
            id, actor, serialize, deserialize, stop,
            index=i, recorder=recorder, injector=injector, netobs=nob,
        )
        for i, (id, actor) in enumerate(resolved)
    ]
    threads = [
        threading.Thread(target=loop.run, name=f"actor-{int(loop.id)}", daemon=True)
        for loop in loops
    ]
    for t in threads:
        t.start()
    handle = SpawnHandle(
        stop, threads, loops, recorder=recorder, injector=injector, netobs=nob
    )
    if not background:
        try:
            while any(t.is_alive() for t in threads):
                time.sleep(0.5)
        except KeyboardInterrupt:
            handle.shutdown()
    return handle


def _native_runtime():
    try:
        module = importlib.import_module("stateright_tpu.native.runtime")
    except Exception:
        return None
    return module if getattr(module, "is_available", lambda: False)() else None


class SpawnHandle:
    """Controls a running actor deployment (background mode)."""

    def __init__(
        self, stop: threading.Event, threads, loops,
        recorder=None, injector=None, netobs=None,
    ):
        self._stop = stop
        self._threads = threads
        self._loops = loops
        self._recorder = recorder
        self._injector = injector
        self.netobs = netobs

    def telemetry(self):
        """Snapshot of the deployment's live metrics ({} when netobs is off)."""
        return self.netobs.snapshot() if self.netobs is not None else {}

    def state(self, id) -> Any:
        """Peek at an actor's current state (for tests/debugging)."""
        for loop in self._loops:
            if loop.id == Id(id):
                return loop.state
        raise KeyError(f"no actor with id {id!r}")

    def shutdown(self, timeout: float = 2.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        # Injector first: it may still flush held datagrams whose deliveries
        # can no longer be recorded, but the trace file must be sealed last.
        if self._injector is not None:
            self._injector.close()
        if self._recorder is not None:
            self._recorder.close()
