"""Per-actor named-timer sets (durations abstracted away for checking).

Reference: `Timers` (src/actor/timers.rs). A timer is any canonically-
fingerprintable tag; the checker explores a `Timeout` action for each set
timer, so only *which* timers are pending matters, never when they fire.
"""

from __future__ import annotations

from typing import Any, Iterator

from ..fingerprint import canonical_bytes


class Timers:
    """The set of timers currently pending for one actor."""

    __slots__ = ("_set",)

    def __init__(self, timers=()):
        self._set = set(timers)

    def copy(self) -> "Timers":
        return Timers(self._set)

    def set(self, timer: Any) -> bool:
        before = len(self._set)
        self._set.add(timer)
        return len(self._set) != before

    def cancel(self, timer: Any) -> bool:
        if timer in self._set:
            self._set.remove(timer)
            return True
        return False

    def cancel_all(self) -> None:
        self._set.clear()

    def __iter__(self) -> Iterator[Any]:
        return iter(sorted(self._set, key=canonical_bytes))

    def __len__(self) -> int:
        return len(self._set)

    def __contains__(self, timer: Any) -> bool:
        return timer in self._set

    def __eq__(self, other) -> bool:
        return isinstance(other, Timers) and self._set == other._set

    def __hash__(self) -> int:
        return hash(canonical_bytes(self.fingerprint_key()))

    def __repr__(self) -> str:
        return f"Timers({sorted(self._set, key=canonical_bytes)!r})"

    def fingerprint_key(self):
        return frozenset(self._set)

    def rewrite_with(self, plan) -> "Timers":
        # Timer tags never contain actor ids (reference: timers.rs:46-53).
        return self.copy()
