"""Actor identity: a dense index for checking, a socket address for spawning.

Reference parity: `Id` (src/actor.rs:109-157) and the Id ⇔ SocketAddrV4
bijection used by the real-network runtime (src/actor/spawn.rs:10-34):
the 64-bit id packs a 32-bit IPv4 address in the upper lanes and a 16-bit
port in the lower, so model ids 0, 1, 2, ... double as 0.0.0.0:{0,1,2}.

`Id` subclasses `int` so it indexes lists directly and fingerprints as a
plain integer, while remaining a distinct type for `RewritePlan` symmetry
rewriting (which must not remap arbitrary ints).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


class Id(int):
    """Uniquely identifies an actor."""

    __slots__ = ()

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    def __str__(self) -> str:
        ip, port = addr_from_id(self)
        return f"{ip}:{port}"

    @staticmethod
    def vec_from(ids: Iterable[int]) -> List["Id"]:
        """Reference: actor.rs:131-145."""
        return [Id(i) for i in ids]

    @staticmethod
    def from_addr(ip: str, port: int) -> "Id":
        return id_from_addr(ip, port)

    @property
    def addr(self) -> Tuple[str, int]:
        return addr_from_id(self)


def id_from_addr(ip: str, port: int) -> Id:
    """Pack an (IPv4, port) socket address into an Id. Reference: spawn.rs:22-34."""
    octets = [int(o) for o in ip.split(".")]
    if len(octets) != 4 or any(not 0 <= o <= 255 for o in octets):
        raise ValueError(f"not an IPv4 address: {ip!r}")
    if not 0 <= port <= 0xFFFF:
        raise ValueError(f"not a port: {port!r}")
    ip_u32 = (octets[0] << 24) | (octets[1] << 16) | (octets[2] << 8) | octets[3]
    return Id((ip_u32 << 16) | port)


def addr_from_id(id: int) -> Tuple[str, int]:
    """Unpack an Id into its (IPv4, port) socket address. Reference: spawn.rs:10-20."""
    ip_u32 = (int(id) >> 16) & 0xFFFFFFFF
    port = int(id) & 0xFFFF
    ip = f"{(ip_u32 >> 24) & 0xFF}.{(ip_u32 >> 16) & 0xFF}.{(ip_u32 >> 8) & 0xFF}.{ip_u32 & 0xFF}"
    return ip, port


def majority(cluster_size: int) -> int:
    """Number of nodes constituting a majority. Reference: actor.rs:604-607."""
    return cluster_size // 2 + 1


def model_peers(self_ix: int, count: int) -> List[Id]:
    """All ids in a `count`-actor cluster except `self_ix`. Reference: model.rs:81-87."""
    return [Id(j) for j in range(count) if j != self_ix]
