"""The actor-system encoding toolkit: reusable lane programs for building
TensorModel twins of ActorModels.

This generalizes what `models/paxos.py` originally hand-rolled (SURVEY.md
§7 step 3's "hard part" — mapping an actor system onto fixed uint32
lanes), so new twins write ONE batched delivery handler and inherit the
rest:

  - `ActorNetModel`: a TensorModel base that owns the network encoding —
    an ascending-sorted bounded multiset of envelope words (zeros-first,
    so equal multisets have equal lanes and the stream fingerprint is
    order-insensitive by construction), with the whole step evaluated at
    [K*B] width: ONE delivery-handler instance and ONE removal + M
    sorted-insert network update instead of K unrolled copies (the XLA
    program stays O(K); the unrolled form was round 3's scale blocker).
  - envelope packing helpers (`env_word`, `env_fields`): the shared
    typ(4b) | src(4b) | dst(4b) | payload(20b) word layout.
  - `register_client_deliver`: the reference's reusable `RegisterClient`
    (actor/register.rs:93-275) as a lane program — put_count=1 protocol
    phases, read values, and the per-peer completed-op counters that
    carry the linearizability tester's real-time edges as state.
  - `register_linearizable_lanes`: the closed-form register
    linearizability verdict (write-precedence digraph acyclicity) shared
    by every register-family twin; oracle-validated against the
    backtracking `LinearizabilityTester` in
    tests/test_paxos_linearizable.py.

Everything here is pure elementwise array code valid under both numpy and
jax.numpy — the host engines run the same programs row-at-a-time as the
correctness oracle for the device engine.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .tensor import TensorModel, TensorProperty

_PAY_BITS = 20
PAY_MASK = (1 << _PAY_BITS) - 1


def env_word(xp, typ, src, dst, pay):
    """Envelope word: typ(4b)<<28 | src(4b)<<24 | dst(4b)<<20 | payload.

    4-bit actor ids address up to 16 actors; message types are 1-based so
    an envelope word is never zero (zero = empty network slot).
    """
    u = xp.uint32
    return (u(typ) << u(28)) | (src << u(24)) | (dst << u(20)) | pay


def env_fields(xp, env):
    """(typ, src, dst, pay) field views of an envelope word array."""
    u = xp.uint32
    return (
        env >> u(28),
        (env >> u(24)) & u(15),
        (env >> u(20)) & u(15),
        env & u(PAY_MASK),
    )


# Ordered-network rank field: per-flow FIFO position, stored in the top
# nibble of the payload area (typ|src|dst|rank(4)|pay(16)). Handlers see
# rank-stripped envelopes and emit rank-less sends; the ordered network
# update assigns and maintains ranks. 4 bits suffice: a flow can hold at
# most K <= 16 messages.
RANK_SHIFT = 16
RANK_FIELD = 0xF << RANK_SHIFT
ORDERED_PAY_MASK = (1 << RANK_SHIFT) - 1


def _flow_id(xp, env):
    """(src, dst) flow key of an envelope word (bits 20-27)."""
    return (env >> xp.uint32(20)) & xp.uint32(0xFF)


def net_step_ordered(xp, net, slot_id, sends):
    """One batched ORDERED network update over the [K*B] delivery batch.

    The reference's Ordered semantics (src/actor/network.rs:62-68: per
    directed (src, dst) flow FIFO; only heads deliverable, enforced at
    model.rs:269-275) encoded on the same sorted K-slot ring: every
    envelope carries its per-flow rank in the word (see RANK_SHIFT), so
    per-flow SEQUENCES — not just multisets — determine state identity,
    and "deliverable" is the elementwise test rank == 0.

    Steps, all elementwise: remove the delivered slot (callers only
    deliver rank-0 envelopes); decrement the rank of every other envelope
    in the delivered flow; restore sortedness (the decrements can reorder
    words) with an odd-even transposition pass; then insert each send
    with rank = its flow's current depth.
    """
    u = xp.uint32
    K = len(net)
    env_all = xp.concatenate(net)
    delivered_occ = env_all != u(0)
    dflow = _flow_id(xp, env_all)
    bignet = [xp.concatenate([net[m]] * K) for m in range(K)]
    # Remove the delivered slot: entries below it shift up one.
    cur = [
        xp.where(
            slot_id >= u(m),
            bignet[m - 1] if m > 0 else u(0) * env_all,
            bignet[m],
        )
        for m in range(K)
    ]
    # Decrement ranks within the delivered flow.
    cur = [
        xp.where(
            delivered_occ & (c != u(0)) & (_flow_id(xp, c) == dflow),
            c - u(1 << RANK_SHIFT),
            c,
        )
        for c in cur
    ]
    # Odd-even transposition restores ascending order (zeros first: 0 is
    # the minimum word). K passes guarantee a full sort.
    for p in range(K):
        start = p & 1
        for m in range(start, K - 1, 2):
            lo = xp.minimum(cur[m], cur[m + 1])
            hi = xp.maximum(cur[m], cur[m + 1])
            cur[m] = lo
            cur[m + 1] = hi
    # Insert sends at their flow tails (rank = current flow depth).
    for v in sends:
        # Handlers must emit rank-less envelopes (payloads limited to the
        # ORDERED_PAY_MASK 16 bits); mask the rank nibble regardless, so a
        # handler payload that strays into bits 16-19 cannot pre-load a
        # bogus rank and corrupt per-flow FIFO ordering when the real rank
        # is OR'd in below.
        v = v & ~u(RANK_FIELD)
        has = v != u(0)
        vflow = _flow_id(xp, v)
        depth = u(0) * v
        for m in range(K):
            depth = depth + (
                (cur[m] != u(0)) & (_flow_id(xp, cur[m]) == vflow)
            ).astype(xp.uint32)
        vr = v | (depth << u(RANK_SHIFT))
        rank = u(0) * v
        for m in range(1, K):
            rank = rank + (cur[m] < vr).astype(xp.uint32)
        nxt = []
        for m in range(K):
            shifted = cur[m + 1] if m + 1 < K else vr
            placed = xp.where(
                u(m) < rank,
                shifted,
                xp.where(u(m) == rank, vr, cur[m]),
            )
            nxt.append(xp.where(has, placed, cur[m]))
        cur = nxt
    return cur


def net_step(xp, net, slot_id, sends):
    """One batched network update over the [K*B] delivery batch.

    `net` is the K per-slot lane list (each [B]); `slot_id[j]` names the
    slot the j-th batch segment delivers; `sends` are up-to-M envelope
    word arrays at [K*B] width (0 = no send). Returns the K updated net
    lanes at [K*B] width: the delivered slot removed from the ascending
    zeros-first ring, then each send inserted in sorted position. All
    elementwise — insertion ranks are lane-wise popcounts, not
    reductions.
    """
    u = xp.uint32
    K = len(net)
    env_all = xp.concatenate(net)
    bignet = [xp.concatenate([net[m]] * K) for m in range(K)]
    # Remove the delivered slot: entries below it shift up one.
    cur = [
        xp.where(
            slot_id >= u(m),
            bignet[m - 1] if m > 0 else u(0) * env_all,
            bignet[m],
        )
        for m in range(K)
    ]
    for v in sends:
        has = v != u(0)
        rank = u(0) * v
        for m in range(1, K):
            rank = rank + (cur[m] < v).astype(xp.uint32)
        nxt = []
        for m in range(K):
            shifted = cur[m + 1] if m + 1 < K else v
            placed = xp.where(
                u(m) < rank,
                shifted,
                xp.where(u(m) == rank, v, cur[m]),
            )
            nxt.append(xp.where(has, placed, cur[m]))
        cur = nxt
    return cur


class ActorNetModel(TensorModel):
    """TensorModel base for actor systems over the bounded multiset network.

    State layout: `n_actor_lanes` actor lanes followed by `K` network
    lanes (ascending-sorted envelope words, zeros first). Subclasses
    define:

      - `n_actor_lanes`, `K` (net capacity = max simultaneously in-flight
        messages; derive it from the protocol and validate against the
        actor-model goldens), and optionally `max_sends` (<= 4),
      - `deliver(xp, actor_lanes, env) -> (new_actor_lanes, sends,
        changed)`: the batched delivery handler — `env` may be zero
        (empty slot; the result is masked out), `sends` is a list of
        up-to-max_sends envelope word arrays (0 = no send),
      - `init_states_array()` (use `pack_init_row` for the common
        single-init case).

    `step_lanes` then evaluates every Deliver action as one [K*B]-wide
    handler + network update. A successor is valid iff its slot held a
    message AND the delivery changed something (actor state or a send) —
    the reference ActorModel's no-op delivery pruning (model.rs parity
    via `examples/paxos.py`).
    """

    max_sends = 3
    # Ordered mode (reference Network::Ordered, network.rs:62-68): per-flow
    # FIFO with head-only delivery. Envelope words carry a per-flow rank
    # nibble (see net_step_ordered); handlers still see rank-less words
    # and payloads are limited to 16 bits instead of 20.
    ordered = False

    @property
    def state_width(self) -> int:  # type: ignore[override]
        return self.n_actor_lanes + self.K

    @property
    def max_actions(self) -> int:  # type: ignore[override]
        return self.K

    # -- subclass interface --------------------------------------------------

    n_actor_lanes: int
    K: int

    def deliver(self, xp, actor_lanes, env):
        raise NotImplementedError

    # -- shared machinery ----------------------------------------------------

    def pack_init_row(self, actor_values, envelopes) -> np.ndarray:
        """One init row from per-actor lane ints + initial envelope words.

        In ordered mode, envelope list order is send order: each envelope
        gets its per-flow FIFO rank before the canonical sort.
        """
        row = np.zeros(self.state_width, dtype=np.uint32)
        row[: len(actor_values)] = actor_values
        if self.ordered:
            depth: dict = {}
            ranked = []
            for env in envelopes:
                flow = (env >> 20) & 0xFF
                r = depth.get(flow, 0)
                depth[flow] = r + 1
                ranked.append(env | (r << RANK_SHIFT))
            envelopes = ranked
        env_sorted = sorted(envelopes)
        base = self.n_actor_lanes + self.K - len(env_sorted)
        for k, env in enumerate(env_sorted):
            row[base + k] = env
        return row[None, :]

    def net_lanes(self, lanes):
        return list(lanes[self.n_actor_lanes : self.n_actor_lanes + self.K])

    def net_scan(self, xp, lanes, fn):
        """OR of `fn(env)` over every (possibly empty) net slot."""
        acc = lanes[0] != lanes[0]
        for m in range(self.K):
            acc = acc | fn(lanes[self.n_actor_lanes + m])
        return acc

    def net_capacity_property(self):
        """An always-property guarding the in-flight bound K.

        The sorted ring keeps zeros (empty slots) first, so slot 0 being
        nonzero means all K slots are occupied — one more send would
        silently drop the smallest envelope. Size K with at least ONE slot
        of slack above the protocol's derived in-flight bound: a strict
        request-response protocol legitimately SITS at its bound (e.g. the
        single-copy register holds exactly c messages from the initial
        state on), and a slack-free ring would trip this guard on every
        reachable state. K bounds are derived from the
        protocol and validated against actor-model goldens; this property
        turns a bound violation into a LOUD counterexample instead of a
        silent state-space corruption, which is what makes empirically
        tightened bounds (state width and step arithmetic scale with K and
        K^2) safe to use. Include it in `tensor_properties()`.

        Detection-lag caveat (for protocols with max_sends > 1, e.g.
        paxos): one slot of slack guarantees drop-BEFORE-detection cannot
        happen only for single-send transitions. A delivery from a passing
        state at occupancy K-1 that inserts multiple sends drops the
        smallest envelope in the same transition that first trips this
        guard, so the flagged counterexample state may already have lost
        one envelope. The VERDICT is still sound (the violation is
        detected loudly and the run never continues past it); only the
        flagged state's network contents may be one drop stale. Sizing K
        with max_sends slots of slack removes the lag at the cost of a
        wider state."""
        NB = self.n_actor_lanes

        def within_capacity(xp, lanes):
            return lanes[NB] == xp.uint32(0)

        return TensorProperty.always("network within capacity", within_capacity)

    def step_lanes(self, xp, lanes):
        u = xp.uint32
        K = self.K
        NA = self.n_actor_lanes
        net = self.net_lanes(lanes)
        B = lanes[0].shape[0]

        env_all = xp.concatenate(net)
        if self.ordered:
            # Handlers see rank-stripped envelopes; only flow heads
            # (rank 0) are deliverable (model.rs:269-275).
            deliverable = (env_all != u(0)) & (
                (env_all & u(RANK_FIELD)) == u(0)
            )
            env_h = env_all & ~u(RANK_FIELD)
        else:
            env_h = env_all
        big = [xp.concatenate([lanes[t]] * K) for t in range(NA)]
        new_actor, sends, changed = self.deliver(xp, big, env_h)
        assert len(sends) <= self.max_sends

        slot_id = xp.concatenate(
            [xp.full(B, k, dtype=xp.uint32) for k in range(K)]
        )
        if self.ordered:
            cur = net_step_ordered(xp, net, slot_id, sends)
            # No-op deliveries are NOT pruned on the ordered network — the
            # delivery itself mutates the flow (model.rs:345-347).
            mask_all = deliverable
        else:
            cur = net_step(xp, net, slot_id, sends)
            sent_any = env_all != env_all  # all-false, varying
            for v in sends:
                sent_any = sent_any | (v != u(0))
            mask_all = (env_all != u(0)) & (changed | sent_any)

        succs = []
        masks = []
        for k in range(K):
            seg = slice(k * B, (k + 1) * B)
            new_lanes = list(lanes)
            for t in range(NA):
                new_lanes[t] = new_actor[t][seg]
            for m in range(K):
                new_lanes[NA + m] = cur[m][seg]
            succs.append(tuple(new_lanes))
            masks.append(mask_all[seg])
        return succs, masks

    def format_action(self, k: int) -> str:
        return f"Deliver[net slot {k}]"


# -- the register-client tester as lanes -------------------------------------
#
# Client lane packing (identical across register-family twins, so the
# linearizability program below is shared):
#   bits 0-1   phase: 0 = write in flight, 1 = read in flight, 2 = done
#   bits 2-5   read value: 0 = n/a, 1 = None, 2+k = writer k's value
#   bits 6+2p  peer p's phase snapshotted when this client's read was
#              invoked (the tester's real-time edges,
#              linearizability.rs:55-66) — skipping p == self.


def register_client_deliver(
    xp, client_lanes, i, cond_putok, cond_getok, getok_val, get_env
):
    """The put_count=1 RegisterClient's delivery handler for client i.

    `cond_putok`/`cond_getok`: this delivery completes the client's
    write/read; `getok_val`: the 4-bit read value payload; `get_env`: the
    Get envelope to send when the write completes (the read is invoked in
    the same atomic step, register.rs:131-146). Returns (new client lane,
    send word, changed).
    """
    u = xp.uint32
    c = len(client_lanes)
    cl = client_lanes[i]
    phase = cl & u(3)

    b_pok = cond_putok & (phase == u(0))
    ncl = (cl & ~u(3)) | u(1)
    for p in range(c):
        if p == i:
            continue
        peer_phase = client_lanes[p] & u(3)
        ncl = (ncl & ~(u(3) << u(6 + 2 * p))) | (peer_phase << u(6 + 2 * p))

    b_gok = cond_getok & (phase == u(1))
    gok_cl = (cl & ~u(0x3F)) | u(2) | ((getok_val & u(15)) << u(2))

    out = cl
    out = xp.where(b_pok, ncl, out)
    out = xp.where(b_gok, gok_cl, out)
    send = xp.where(b_pok, get_env, u(0) * cl)
    return out, send, b_pok | b_gok


def register_linearizable_lanes(xp, client_lanes):
    """Batched register-linearizability verdict from client lanes.

    For the register workload (every client invokes its unique-valued
    write at time zero and reads only after its own write completes) a
    linearization exists iff an ordering σ of the c writes satisfies, for
    every COMPLETED read_j returning value k_j:

      - gap placement: read_j sits immediately after write_{k_j} in σ,
      - its own write precedes it:                     j   <σ k_j,
      - every write completed before read_j invoked:   i   <σ k_j,
      - every read completed before read_j invoked:    k_i <σ k_j.

    All constraints are binary precedences over c nodes, so existence is
    ACYCLICITY of the induced digraph — adjacency bitmask rows plus a
    log-depth transitive closure, pure elementwise. A completed read
    returning None fails directly (its own write precedes it). Oracle-
    validated against the backtracking tester in
    tests/test_paxos_linearizable.py.
    """
    u = xp.uint32
    c = len(client_lanes)
    cl = client_lanes
    phase = [cl[i] & u(3) for i in range(c)]
    val = [(cl[i] >> u(2)) & u(15) for i in range(c)]
    done = [phase[i] == u(2) for i in range(c)]
    kk = [(val[i] - u(2)) & u(15) for i in range(c)]

    false_ = cl[0] != cl[0]
    none_read = false_
    zero = u(0) * cl[0]
    adj = [zero for _ in range(c)]  # bit t of adj[r]: edge r -> t

    def set_edge(row_static, tgt, cond):
        e = xp.where(cond & (tgt != u(row_static)), u(1) << tgt, zero)
        adj[row_static] = adj[row_static] | e

    for j in range(c):
        rj = done[j]
        none_read = none_read | (rj & (val[j] == u(1)))
        set_edge(j, kk[j], rj)  # own write precedes own read
        for i in range(c):
            if i == j:
                continue
            cij = (cl[j] >> u(6 + 2 * i)) & u(3)
            set_edge(i, kk[j], rj & (cij >= u(1)))
            rr = rj & (cij == u(2))
            for r in range(c):
                set_edge(r, kk[j], rr & (kk[i] == u(r)))

    rounds = max(1, (c - 1).bit_length())
    for _ in range(rounds):
        nxt = list(adj)
        for i in range(c):
            acc = nxt[i]
            for k in range(c):
                acc = acc | xp.where(
                    ((adj[i] >> u(k)) & u(1)) == u(1), adj[k], zero
                )
            nxt[i] = acc
        adj = nxt

    cyclic = false_
    for i in range(c):
        cyclic = cyclic | (((adj[i] >> u(i)) & u(1)) == u(1))
    return ~(cyclic | none_read)


def register_family_properties(model, getok_type: int = 4, val_shift: int = 4):
    """The standard register-twin property list: the shared linearizable
    lane program (always), a value-chosen scan over GetOk envelopes
    (sometimes), and the network capacity guard. `val_shift` is the bit
    offset of the 4-bit tester value code inside the GetOk payload
    (1 = None, 2+k = writer k's value)."""

    def value_chosen(xp, lanes):
        u = xp.uint32

        def is_value_getok(env):
            return (
                ((env >> u(28)) == u(getok_type))
                & (((env >> u(val_shift)) & u(15)) != u(1))
                & (env != u(0))
            )

        return model.net_scan(xp, lanes, is_value_getok)

    return [
        TensorProperty.always("linearizable", model.linearizable_lanes),
        TensorProperty.sometimes("value chosen", value_chosen),
        model.net_capacity_property(),
    ]


def decode_net(row, n_actor_base: int, K: int, type_names) -> List[str]:
    """Human-readable network view (Explorer / error messages)."""
    out = []
    for m in range(K):
        env = int(row[n_actor_base + m])
        if env:
            out.append(
                f"{type_names[env >> 28]}({(env >> 24) & 15}->"
                f"{(env >> 20) & 15}, pay={env & 0xFFFFF:#x})"
            )
    return out


def decode_register_clients(row, n_actor_base: int, c: int) -> List[dict]:
    """Human-readable client tester view (Explorer / error messages)."""
    out = []
    for i in range(c):
        cl = int(row[n_actor_base + i])
        out.append(
            {
                "phase": cl & 3,
                "read_value": (cl >> 2) & 15,
            }
        )
    return out
