"""REST surface of the run service, on the Explorer's HTTP stack.

Routes (JSON in/out unless noted):

  ``POST /submit``                admit a check: ``{"spec": "2pc:3",
                                  "tenant": "...", "priority": 0,
                                  "engine": "auto|multiplex|tpu_bfs|bfs",
                                  "target_max_depth": N}`` ->
                                  202 ``{"job_id", "status"}``; 400
                                  malformed, 413 predicted memory
                                  footprint exceeds the device budget
                                  (predicted/available bytes in the
                                  body), 422 speclint STRxxx
                                  diagnostics, 429 quota/rate limit
  ``GET /jobs``                   all job views (``?tenant=`` filters)
  ``GET /jobs/{id}``              one job's status view
  ``GET /jobs/{id}/result``       the finished job's results (404
                                  unknown, 409 while queued/running)
  ``GET /jobs/{id}/trace``        the job's span ledger (obs/spans.py):
                                  every recorded span sharing the job's
                                  trace_id — admission, queue waits,
                                  executions (with engine phases),
                                  backoff windows, restart recoveries,
                                  the result write and the root span
  ``GET /events``                 Server-Sent Events stream: ``span``
                                  events as spans complete + periodic
                                  ``metrics`` delta events; bounded via
                                  ``?limit=N`` / ``?duration=SECS`` /
                                  ``?replay=N`` (see explorer/server.py)
  ``POST /jobs/{id}/cancel``      cancel a queued job (409 otherwise)
  ``POST /jobs/{id}/retry``       admin re-enqueue of a failed or
                                  cancelled job (409 otherwise; resets
                                  its attempt budget)
  ``POST /scheduler/pause``       freeze the scheduler (deterministic
  ``POST /scheduler/resume``      batching for tests/CI)
  ``GET /stats``                  queue/cache/quota summary plus the
                                  durability sections: retry policy,
                                  circuit-breaker states, journal and
                                  result-store footprints
  ``GET /metrics``                service telemetry snapshot (JSON)
  ``GET /metrics.prom``           Prometheus text exposition with the
                                  per-tenant request series labeled
  ``GET /healthz``                liveness
"""

from __future__ import annotations

import threading
from http.server import ThreadingHTTPServer
from typing import Optional

from ..explorer.server import JsonRequestHandler
from ..obs.log import get_logger
from ..obs.metrics import (
    MEMORY_SERIES_LABELS,
    SHARD_SERIES_LABELS,
    render_prometheus,
)
from .service import RunService

__all__ = ["ServeServer", "serve"]

_log = get_logger("serve.http")


class ServeServer:
    """A running run-service HTTP frontend; `serve()` constructs it."""

    def __init__(self, service: RunService, address: str = "127.0.0.1:3001"):
        self.service = service
        host, _, port = address.replace(
            "localhost", "127.0.0.1"
        ).partition(":")
        self.address = (host or "127.0.0.1", int(port or 3001))

        svc = service

        class Handler(JsonRequestHandler):
            def do_GET(self):
                path, _, query = self.path.partition("?")
                parts = [p for p in path.split("/") if p]
                if path == "/healthz":
                    self._send_json({"ok": True})
                elif path == "/stats":
                    self._send_json(svc.stats())
                elif path == "/metrics.prom" or (
                    path == "/metrics" and "format=prometheus" in query
                ):
                    body = render_prometheus(
                        svc.telemetry(),
                        labels={
                            "serve_tenant_requests": "tenant",
                            **SHARD_SERIES_LABELS,
                            **MEMORY_SERIES_LABELS,
                        },
                    )
                    self._send(
                        200,
                        body.encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/metrics":
                    self._send_json(svc.telemetry())
                elif path == "/events":
                    self._serve_sse(svc.spans, query, telemetry=svc.telemetry)
                elif path == "/jobs":
                    tenant = None
                    for part in query.split("&"):
                        if part.startswith("tenant="):
                            tenant = part[len("tenant="):]
                    self._send_json({"jobs": svc.jobs(tenant)})
                elif len(parts) == 2 and parts[0] == "jobs":
                    job = svc.job(parts[1])
                    if job is None:
                        self._send_json({"error": f"no job {parts[1]!r}"}, 404)
                    else:
                        self._send_json(job.view())
                elif (
                    len(parts) == 3
                    and parts[0] == "jobs"
                    and parts[2] == "trace"
                ):
                    job = svc.job(parts[1])
                    if job is None:
                        self._send_json({"error": f"no job {parts[1]!r}"}, 404)
                    else:
                        self._send_json(
                            {
                                "job_id": job.id,
                                "trace_id": job.trace_id,
                                "spans": svc.trace(job.trace_id),
                            }
                        )
                elif (
                    len(parts) == 3
                    and parts[0] == "jobs"
                    and parts[2] == "result"
                ):
                    job = svc.job(parts[1])
                    if job is None:
                        self._send_json({"error": f"no job {parts[1]!r}"}, 404)
                    elif job.status in ("queued", "running"):
                        self._send_json(
                            {"error": f"job {parts[1]} is {job.status}"},
                            409,
                        )
                    elif job.result is None:
                        self._send_json(
                            {"error": job.error or f"job {parts[1]} "
                             f"finished {job.status} without results"},
                            409,
                        )
                    else:
                        self._send_json(
                            {"job": job.view(), "result": job.result}
                        )
                else:
                    self._send_json({"error": "not found"}, 404)

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                parts = [p for p in path.split("/") if p]
                if path == "/submit":
                    payload = self._read_json()
                    if payload is None:
                        return
                    code, body = svc.submit(payload)
                    self._send_json(body, code)
                elif (
                    len(parts) == 3
                    and parts[0] == "jobs"
                    and parts[2] == "cancel"
                ):
                    code, body = svc.cancel(parts[1])
                    self._send_json(body, code)
                elif (
                    len(parts) == 3
                    and parts[0] == "jobs"
                    and parts[2] == "retry"
                ):
                    code, body = svc.retry_job(parts[1])
                    self._send_json(body, code)
                elif path == "/scheduler/pause":
                    svc.pause()
                    self._send_json({"paused": True})
                elif path == "/scheduler/resume":
                    svc.resume()
                    self._send_json({"paused": False})
                else:
                    self._send_json({"error": "not found"}, 404)

        self.httpd = ThreadingHTTPServer(self.address, Handler)

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}/"

    def serve_forever(self):
        _log.info("run service ready", url=self.url)
        try:
            self.httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def serve_in_background(self) -> "ServeServer":
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self.service.shutdown()


def serve(
    address: str = "127.0.0.1:3001",
    service: Optional[RunService] = None,
    block: bool = True,
    **service_options,
) -> ServeServer:
    """Start the run service (``python -m stateright_tpu.serve`` / the
    examples CLI ``serve`` subcommand). ``block=False`` runs on daemon
    threads and returns the handle (port 0 binds an ephemeral port —
    the tests' and CI smoke's path)."""
    server = ServeServer(
        service or RunService(**service_options), address
    )
    if block:
        server.serve_forever()
    else:
        server.serve_in_background()
    return server
