"""Checking-as-a-service: the multi-tenant run server (ROADMAP item 3).

`RunService` (service.py) is the queue/scheduler/quota core over the
engine layer's build/run split (engines/compiled.py) and the vmapped
lane-multiplexing engine (engines/multiplex.py); `ServeServer` (http.py)
is its REST frontend. ``python -m stateright_tpu.serve`` starts one.
"""

from .http import ServeServer, serve
from .service import Job, RunService

__all__ = ["Job", "RunService", "ServeServer", "serve"]
