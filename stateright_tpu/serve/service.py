"""The run service: queue, scheduler, quotas, and the executable cache.

`RunService` is the engine-facing half of checking-as-a-service (the
HTTP surface is serve/http.py). A submission names a bundled model spec
(analysis/__main__.py's registry — ``"2pc:3"``, ``"increment:2"``, or a
``pkg.module:Factory:ARGS`` path) and rides this pipeline:

  admission   speclint gates every submission (`CheckerBuilder.lint`):
              error-severity STRxxx findings reject with 422 BEFORE
              anything compiles — a broken spec must not spend device
              time. Reports are cached per model signature.
  quotas      per-tenant active-job caps and a rolling per-minute
              submission rate limit reject with 429.
  queue       a priority heap drained by worker threads; queued jobs
              are cancellable; `pause()`/`resume()` freeze the
              scheduler (tests and the CI smoke use this to force
              deterministic batching).
  execution   tensor models default to the multiplexed lane engine
              (engines/multiplex.py): a worker popping a lane-eligible
              job gathers every same-signature queued job into ONE
              fused vmapped batch — thousands of small checks share
              one compiled executable. Solo device runs and host-model
              runs (``engine="tpu_bfs"`` / ``"bfs"``) are served too.
              All device paths go through the `ExecutableCache`
              (engines/compiled.py), so a same-shape resubmission
              reuses the warm executable outright.
  results     state counts, per-property discovery paths with
              `Path.explain` forensics, telemetry, and coverage.
  durability  with `journal_path=`, every lifecycle transition is
              write-ahead journalled (serve/durability.py) so a
              restarted service re-enqueues queued jobs, retries jobs
              that were mid-flight, and keeps serving finished results
              (persisted per-job under `results_dir=`, TTL-expired).
              Transient failures (table/probe exhaustion, OOM, worker
              crashes) retry with bounded exponential backoff —
              multiplex-lane capacity failures escalate to the solo
              engine — behind a per-signature circuit breaker; dead
              worker threads are detected and replaced.

Every stage exports `serve_*` metrics (obs/metrics.py catalog) with
per-tenant request counts as a labeled Prometheus series.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..engines.compiled import ExecutableCache, model_signature
from ..obs import memory as obs_memory
from ..obs.log import get_logger
from ..obs.metrics import MetricsRegistry
from ..obs.spans import (
    SpanRecorder,
    attach_phase_spans,
    new_span_id,
    new_trace_id,
)
from ..tensor import TensorModel, TensorModelAdapter
from .durability import (
    CircuitBreaker,
    JobJournal,
    ResultStore,
    RetryPolicy,
    classify_failure,
    is_oom,
)

__all__ = ["Job", "RunService"]

_log = get_logger("serve.service")

_RATE_WINDOW_SECS = 60.0


class Job:
    """One submitted check, from admission through results.

    Every job IS one trace in the run ledger (obs/spans.py): `trace_id`
    names it end-to-end and `root_span_id` is the pre-assigned id of the
    root "job" span (sealed at finish), so admission/queue/execute child
    spans parent to it while the job is still in flight. Both ride
    `journal_fields()` into the write-ahead journal, which is what makes
    a crash→restart replay CONTINUE the same trace instead of opening a
    new one."""

    __slots__ = (
        "id", "tenant", "spec", "engine", "priority", "status",
        "submitted_at", "started_at", "finished_at", "error", "result",
        "signature", "model", "options", "attempts",
        "trace_id", "root_span_id", "enqueued_at", "backoff_since",
        "memory_at_failure",
    )

    def __init__(self, tenant: str, spec: str, engine: str, priority: int,
                 model: Any, signature: Optional[str],
                 options: Dict[str, Any]):
        self.id = uuid.uuid4().hex[:12]
        self.tenant = tenant
        self.spec = spec
        self.engine = engine
        self.priority = priority
        self.status = "queued"
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.error: Optional[str] = None
        self.result: Optional[Dict[str, Any]] = None
        self.signature = signature
        self.model = model
        self.options = options
        self.attempts = 0
        self.trace_id = new_trace_id()
        self.root_span_id = new_span_id()
        # When the job last entered the queue (reset per requeue) — the
        # start of the current queue_wait span.
        self.enqueued_at = self.submitted_at
        # When the job entered its current backoff window, if any.
        self.backoff_since: Optional[float] = None
        # OOM post-mortem: device residency at the failure (the engine's
        # memory-ledger snapshot, or the planner's prediction when the
        # engine died before reporting one).
        self.memory_at_failure: Optional[Dict[str, Any]] = None

    def journal_fields(self) -> Dict[str, Any]:
        """The job's identity as the write-ahead journal records it —
        everything needed to reconstruct it after a restart (the model
        object itself re-resolves from `spec`)."""
        return {
            "id": self.id,
            "tenant": self.tenant,
            "spec": self.spec,
            "engine": self.engine,
            "priority": self.priority,
            "options": self.options,
            "submitted_at": self.submitted_at,
            "trace_id": self.trace_id,
            "root_span_id": self.root_span_id,
        }

    @classmethod
    def restore(cls, fields: Dict[str, Any], model: Any,
                signature: Optional[str]) -> "Job":
        job = cls(
            str(fields.get("tenant") or "default"), fields["spec"],
            fields.get("engine") or "auto", int(fields.get("priority", 0)),
            model, signature, dict(fields.get("options") or {}),
        )
        job.id = fields["id"]
        job.submitted_at = fields.get("submitted_at", job.submitted_at)
        # Pre-PR-12 journals have no trace ids; the fresh ones from the
        # constructor keep those jobs traceable from the restart on.
        job.trace_id = fields.get("trace_id") or job.trace_id
        job.root_span_id = fields.get("root_span_id") or job.root_span_id
        return job

    def view(self) -> Dict[str, Any]:
        out = {
            "job_id": self.id,
            "tenant": self.tenant,
            "spec": self.spec,
            "engine": self.engine,
            "priority": self.priority,
            "status": self.status,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "attempts": self.attempts,
            "trace_id": self.trace_id,
        }
        if self.error is not None:
            out["error"] = self.error
        if self.memory_at_failure is not None:
            out["memory_at_failure"] = self.memory_at_failure
        return out


def _resolve_spec(spec: str):
    """analysis/__main__.py's model registry, with its CLI-style
    SystemExit turned into a service-style ValueError."""
    from ..analysis.__main__ import resolve_model

    try:
        return resolve_model(spec)
    except SystemExit:
        raise ValueError(f"unknown model spec {spec!r}")
    except Exception as e:  # bad ARGS, import errors in dotted paths
        raise ValueError(f"unable to construct model from {spec!r}: {e}")


class RunService:
    """Multi-tenant run queue + scheduler + executable cache."""

    def __init__(
        self,
        *,
        workers: int = 2,
        lanes: int = 32,
        lane_chunk: int = 256,
        lane_queue_capacity: int = 1 << 13,
        lane_table_capacity: int = 1 << 16,
        solo_chunk: int = 4096,
        solo_queue_capacity: int = 1 << 17,
        solo_table_capacity: int = 1 << 19,
        exec_cache_capacity: int = 8,
        quota_max_active: int = 256,
        quota_per_minute: int = 600,
        lint_samples: int = 64,
        journal_path: Optional[str] = None,
        results_dir: Optional[str] = None,
        result_ttl: float = 7 * 24 * 3600.0,
        retry: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        guard_interval: float = 0.5,
        device_memory_bytes: Optional[int] = None,
    ):
        self.lanes = lanes
        # Device budget for the memory admission gate (413) and lane
        # right-sizing; auto-detected when not given, and both features
        # simply disable when no limit is known (CPU test runs).
        self.device_memory_bytes = (
            device_memory_bytes
            if device_memory_bytes is not None
            else obs_memory.device_memory_bytes()
        )
        self.lane_options = {
            "lanes": lanes,
            "chunk": lane_chunk,
            "queue_capacity": lane_queue_capacity,
            "table_capacity": lane_table_capacity,
        }
        self.solo_options = {
            "chunk_size": solo_chunk,
            "queue_capacity": solo_queue_capacity,
            "table_capacity": solo_table_capacity,
        }
        self.quota_max_active = quota_max_active
        self.quota_per_minute = quota_per_minute
        self.lint_samples = lint_samples

        self.metrics = MetricsRegistry()
        # The run ledger: every job's spans land here; GET /events streams
        # completions live and /jobs/{id}/trace serves whole waterfalls.
        self.spans = SpanRecorder(metrics=self.metrics)
        self.cache = ExecutableCache(capacity=exec_cache_capacity)
        self._cv = threading.Condition()
        self._heap: List[Tuple[int, int, Job]] = []
        self._seq = itertools.count()
        self._jobs: Dict[str, Job] = {}
        self._tenant_submits: Dict[str, deque] = {}
        self._lint_cache: Dict[str, Any] = {}
        self._paused = False
        self._stop = False

        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self._journal = (
            JobJournal(journal_path, metrics=self.metrics)
            if journal_path else None
        )
        self._results = (
            ResultStore(results_dir, ttl=result_ttl, metrics=self.metrics)
            if results_dir else None
        )
        self._timers: set = set()
        self._guard_interval = guard_interval

        # Replay the write-ahead journal BEFORE any worker can pop: a
        # restarted service re-enqueues queued jobs, retries jobs that
        # were mid-flight at the kill, and serves persisted results.
        self._workers = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(max(1, workers))
        ]
        if self._journal is not None:
            self._replay_journal()
        for t in self._workers:
            t.start()
        self._guard = threading.Thread(target=self._guard_loop, daemon=True)
        self._guard.start()

    # -- scheduler control ---------------------------------------------------

    def pause(self) -> None:
        """Freeze the scheduler: submissions queue but nothing executes.
        Deterministic-batching hook for tests and the CI smoke."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            timers = list(self._timers)
        for timer in timers:
            timer.cancel()
        for t in self._workers:
            t.join(timeout=5)
        if self._journal is not None:
            self._journal.close()

    # -- durability ----------------------------------------------------------

    def _replay_journal(self) -> None:
        """Fold the journal back into the job table: queued jobs
        re-enqueue, interrupted (running-at-kill) jobs re-enqueue as a
        retry, done jobs reload their persisted result, terminal jobs
        keep their status. Runs before the workers start."""
        folded = JobJournal.replay(self._journal.path)
        for jid, entry in folded.items():
            fields = entry["job"]
            status = entry["status"]
            model = None
            signature = None
            resolve_error: Optional[str] = None
            if status in ("queued", "running"):
                try:
                    model = _resolve_spec(fields["spec"])
                except ValueError as e:
                    resolve_error = f"unresolvable after restart: {e}"
                if model is not None and isinstance(
                    model, (TensorModel, TensorModelAdapter)
                ):
                    signature = model_signature(model)
            job = Job.restore(fields, model, signature)
            job.attempts = entry["attempts"]
            job.memory_at_failure = entry.get("memory")
            self._jobs[job.id] = job
            self.metrics.inc("journal_replayed_jobs")
            if status == "done":
                job.status = "done"
                job.finished_at = job.submitted_at
                if self._results is not None:
                    job.result = self._results.get(job.id)
                self.metrics.inc("journal_recovered_done")
            elif status == "failed":
                job.status = "failed"
                job.error = entry.get("error")
                job.finished_at = job.submitted_at
            elif status == "cancelled":
                job.status = "cancelled"
                job.finished_at = job.submitted_at
            elif resolve_error is not None:
                job.status = "failed"
                job.error = resolve_error
                job.finished_at = time.time()
            else:
                job.status = "queued"
                job.enqueued_at = time.time()
                heapq.heappush(
                    self._heap, (-job.priority, next(self._seq), job)
                )
                self.metrics.inc(
                    "journal_recovered_running" if status == "running"
                    else "journal_recovered_queued"
                )
                # The recovery joins the job's ORIGINAL trace (the ids
                # rode the journal): one continuous waterfall across the
                # crash, with the restart visible as its own span.
                self.spans.record(
                    "restart_recovery",
                    start=job.enqueued_at,
                    end=job.enqueued_at,
                    trace_id=job.trace_id,
                    parent_id=job.root_span_id,
                    attributes={
                        "job_id": job.id,
                        "was": status,
                        "attempt": job.attempts,
                    },
                )
        self._update_gauges_locked()
        if self._jobs:
            _log.info(
                "journal replay recovered jobs",
                replayed=len(self._jobs),
                requeued=self.metrics.get("journal_recovered_queued")
                + self.metrics.get("journal_recovered_running"),
                done=self.metrics.get("journal_recovered_done"),
            )
        self._journal.compact(self._folded_state())

    def _folded_state(self) -> Dict[str, Dict[str, Any]]:
        return {
            j.id: {
                "job": j.journal_fields(),
                "status": j.status,
                "attempts": j.attempts,
                "error": j.error,
            }
            for j in self._jobs.values()
        }

    def _guard_loop(self) -> None:
        """Worker self-healing + periodic result GC. A worker thread
        that dies OUTSIDE its per-batch try (a crash in the pop path, an
        interpreter-level error) would otherwise silently shrink the
        pool until the queue stalls; the guard detects and replaces it."""
        last_gc = time.monotonic()
        while True:
            time.sleep(self._guard_interval)
            with self._cv:
                if self._stop:
                    return
                for i, t in enumerate(self._workers):
                    if not t.is_alive():
                        self.metrics.inc("serve_worker_crashes")
                        nt = threading.Thread(
                            target=self._worker, daemon=True
                        )
                        self._workers[i] = nt
                        nt.start()
            if (
                self._results is not None
                and time.monotonic() - last_gc >= 60.0
            ):
                last_gc = time.monotonic()
                self.gc_results()

    def gc_results(self) -> List[str]:
        """Expire persisted results past their TTL, drop the matching
        in-memory done jobs, and compact the journal to the survivors."""
        if self._results is None:
            return []
        expired = self._results.gc()
        with self._cv:
            for jid in expired:
                job = self._jobs.get(jid)
                if job is not None and job.status == "done":
                    del self._jobs[jid]
            folded = self._folded_state()
        if expired and self._journal is not None:
            self._journal.compact(folded)
        return expired

    # -- admission -----------------------------------------------------------

    def submit(self, payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """Admit one submission. Returns ``(http_status, body)``:
        202 queued, 400 malformed, 413 predicted footprint exceeds
        device memory, 422 speclint rejection, 429 quota."""
        admit_t0 = time.time()
        self.metrics.inc("serve_requests")
        spec = payload.get("spec") or payload.get("model")
        tenant = str(payload.get("tenant") or "default")
        self.metrics.inc_labeled("serve_tenant_requests", tenant)
        if not isinstance(spec, str) or not spec:
            return 400, {"error": "submission needs a 'spec' model string"}
        try:
            priority = int(payload.get("priority", 0))
        except (TypeError, ValueError):
            return 400, {"error": "'priority' must be an integer"}

        try:
            model = _resolve_spec(spec)
        except ValueError as e:
            return 400, {"error": str(e)}
        tensorish = isinstance(model, (TensorModel, TensorModelAdapter))
        engine = str(payload.get("engine") or "auto")
        if engine == "auto":
            engine = "multiplex" if tensorish else "bfs"
        if engine not in ("multiplex", "tpu_bfs", "bfs"):
            return 400, {"error": f"unknown engine {engine!r}"}
        if engine in ("multiplex", "tpu_bfs") and not tensorish:
            return 400, {
                "error": f"engine {engine!r} requires a tensor model; "
                "use engine='bfs' for host models"
            }
        signature = model_signature(model) if tensorish else None

        code, body = self._check_quota(tenant)
        if code is not None:
            return code, body

        # Speclint admission gate: reject broken specs BEFORE any compile.
        # For tensor models this includes the STR6xx program family — a
        # job whose COMPILED program is broken (hot-loop host callbacks,
        # over-budget op growth, dropped donation) is refused before the
        # ExecutableCache ever warms it.
        report = self._lint(spec, signature, model)
        if not report.ok:
            self.metrics.inc("serve_rejected_lint")
            if any(d.code.startswith("STR6") for d in report.errors):
                self.metrics.inc("serve_rejected_proglint")
            return 422, {
                "error": "speclint rejected the model "
                f"({sum(report.counts_by_code().values())} findings)",
                "diagnostics": report.to_dict(),
            }

        # Memory admission gate: the capacity planner (obs/memory.plan)
        # predicts the device footprint at THIS service's engine geometry
        # before anything compiles; a submission that cannot fit is a 413
        # with the arithmetic in the body, not a mid-run OOM.
        if tensorish and self.device_memory_bytes is not None:
            predicted = self._predicted_bytes(model, engine)
            if predicted is not None and predicted > self.device_memory_bytes:
                self.metrics.inc("serve_rejected_memory")
                body: Dict[str, Any] = {
                    "error": (
                        f"predicted {engine} footprint {predicted} bytes "
                        f"exceeds available device memory "
                        f"{self.device_memory_bytes} bytes"
                    ),
                    "predicted_bytes": int(predicted),
                    "available_bytes": int(self.device_memory_bytes),
                    "engine": engine,
                }
                alt = obs_memory.recommend_engine(
                    model, self.device_memory_bytes
                )
                if alt is not None:
                    body["recommended_engine"] = alt
                return 413, body

        options: Dict[str, Any] = {}
        if payload.get("target_max_depth") is not None:
            try:
                options["target_max_depth"] = int(payload["target_max_depth"])
            except (TypeError, ValueError):
                return 400, {"error": "'target_max_depth' must be an integer"}

        job = Job(tenant, spec, engine, priority, model, signature, options)
        # The trace opens: lint + quota + resolution was the admission
        # leg, and the root "job" span starts where the request arrived.
        job.submitted_at = admit_t0
        job.enqueued_at = time.time()
        self.spans.record(
            "admission",
            start=admit_t0,
            end=job.enqueued_at,
            trace_id=job.trace_id,
            parent_id=job.root_span_id,
            attributes={"job_id": job.id, "spec": spec, "tenant": tenant},
        )
        with self._cv:
            self._jobs[job.id] = job
            heapq.heappush(self._heap, (-priority, next(self._seq), job))
            self._note_submit(tenant)
            self._update_gauges_locked()
            if self._journal is not None:
                # Write-ahead: the submit record is durable before the
                # 202 is acknowledged (and before any worker can log a
                # start for it — appends order under this lock).
                self._journal.submit(job.journal_fields())
            self._cv.notify()
        return 202, {
            "job_id": job.id, "status": "queued", "trace_id": job.trace_id,
        }

    def _check_quota(self, tenant: str):
        with self._cv:
            active = sum(
                1
                for j in self._jobs.values()
                if j.tenant == tenant and j.status in ("queued", "running")
            )
            if active >= self.quota_max_active:
                self.metrics.inc("serve_rejected_quota")
                return 429, {
                    "error": f"tenant {tenant!r} has {active} active jobs "
                    f"(quota {self.quota_max_active})"
                }
            window = self._tenant_submits.get(tenant)
            if window is not None:
                now = time.monotonic()
                while window and now - window[0] > _RATE_WINDOW_SECS:
                    window.popleft()
                if len(window) >= self.quota_per_minute:
                    self.metrics.inc("serve_rejected_quota")
                    return 429, {
                        "error": f"tenant {tenant!r} exceeded "
                        f"{self.quota_per_minute} submissions/minute"
                    }
        return None, None

    def _note_submit(self, tenant: str) -> None:
        self._tenant_submits.setdefault(tenant, deque()).append(
            time.monotonic()
        )

    def _predicted_bytes(self, model, engine: str) -> Optional[int]:
        """Planner prediction for ONE job of this model at the service's
        configured geometry; None when the engine has no device footprint
        (host bfs) or the model refuses to plan."""
        try:
            if engine == "multiplex":
                p = obs_memory.plan(
                    model, engine="multiplex", lanes=1,
                    chunk=self.lane_options["chunk"],
                    queue_capacity=self.lane_options["queue_capacity"],
                    table_capacity=self.lane_options["table_capacity"],
                )
            elif engine == "tpu_bfs":
                p = obs_memory.plan(
                    model, engine="tpu_bfs",
                    chunk=self.solo_options["chunk_size"],
                    queue_capacity=self.solo_options["queue_capacity"],
                    table_capacity=self.solo_options["table_capacity"],
                )
            else:
                return None
            return int(p["total_bytes"])
        except Exception:
            return None  # planning is advisory; never block on its bugs

    def _lane_budget(self, model) -> int:
        """How many multiplex lanes of this model the device budget fits
        (obs/memory.max_lanes_for_budget); the configured lane count when
        no limit is known."""
        if self.device_memory_bytes is None or model is None:
            return self.lanes
        try:
            n = obs_memory.max_lanes_for_budget(
                model, self.device_memory_bytes,
                lanes=self.lanes,
                chunk=self.lane_options["chunk"],
                queue_capacity=self.lane_options["queue_capacity"],
                table_capacity=self.lane_options["table_capacity"],
            )
        except Exception:
            return self.lanes
        if n < self.lanes:
            self.metrics.inc("serve_lanes_rightsized")
        self.metrics.set_gauge("serve_lane_budget", n)
        return n

    def _lint(self, spec: str, signature: Optional[str], model: Any):
        key = signature or f"spec:{spec}"
        report = self._lint_cache.get(key)
        if report is None:
            builder = model.checker()
            report = builder.lint(samples=self.lint_samples)
            self._lint_cache[key] = report
        return report

    # -- job queries ---------------------------------------------------------

    def job(self, job_id: str) -> Optional[Job]:
        with self._cv:
            return self._jobs.get(job_id)

    def jobs(self, tenant: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._cv:
            return [
                j.view()
                for j in self._jobs.values()
                if tenant is None or j.tenant == tenant
            ]

    def cancel(self, job_id: str) -> Tuple[int, Dict[str, Any]]:
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None:
                return 404, {"error": f"no job {job_id!r}"}
            if job.status != "queued":
                return 409, {
                    "error": f"job {job_id} is {job.status}; only queued "
                    "jobs cancel"
                }
            job.status = "cancelled"
            job.finished_at = time.time()
            self.metrics.inc("serve_cancelled")
            self._update_gauges_locked()
            if self._journal is not None:
                self._journal.cancel(job.id)
        # A cancel seals the trace: the root span closes as cancelled.
        self.spans.record(
            "job",
            start=job.submitted_at,
            end=job.finished_at,
            trace_id=job.trace_id,
            span_id=job.root_span_id,
            status="cancelled",
            attributes={
                "job_id": job.id, "spec": job.spec, "tenant": job.tenant,
                "final_status": "cancelled",
            },
        )
        return 200, job.view()

    def retry_job(self, job_id: str) -> Tuple[int, Dict[str, Any]]:
        """Admin re-enqueue of a failed or cancelled job (HTTP
        ``POST /jobs/{id}/retry``). Resets the attempt budget; a job
        restored from the journal re-resolves its model first."""
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None:
                return 404, {"error": f"no job {job_id!r}"}
            if job.status not in ("failed", "cancelled"):
                return 409, {
                    "error": f"job {job_id} is {job.status}; only "
                    "failed/cancelled jobs retry"
                }
            if job.model is None:
                try:
                    job.model = _resolve_spec(job.spec)
                except ValueError as e:
                    return 400, {"error": str(e)}
                if isinstance(job.model, (TensorModel, TensorModelAdapter)):
                    job.signature = model_signature(job.model)
            job.status = "queued"
            job.error = None
            job.finished_at = None
            job.attempts = 0
            self.metrics.inc("serve_admin_retries")
            heapq.heappush(self._heap, (-job.priority, next(self._seq), job))
            self._update_gauges_locked()
            if self._journal is not None:
                self._journal.retry(job.id)
            self._cv.notify()
        return 200, job.view()

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            by_status: Dict[str, int] = {}
            for j in self._jobs.values():
                by_status[j.status] = by_status.get(j.status, 0) + 1
            out = {
                "jobs": by_status,
                "queue_depth": sum(
                    1 for j in self._jobs.values() if j.status == "queued"
                ),
                "paused": self._paused,
                "cache": self.cache.stats(),
                "quota": {
                    "max_active": self.quota_max_active,
                    "per_minute": self.quota_per_minute,
                },
                "retry": self.retry.view(),
                "breaker": self.breaker.snapshot(),
                "latency": self._latency_stats(),
            }
            if self._journal is not None:
                out["journal"] = self._journal.stats()
            if self._results is not None:
                out["results"] = self._results.stats()
            return out

    def _latency_stats(self) -> Dict[str, Any]:
        """p50/p95/p99 seconds for the two service-level distributions
        (the full cumulative histograms ride `telemetry()`)."""
        out: Dict[str, Any] = {}
        for key, name in (
            ("submit_to_result", "submit_to_result_secs"),
            ("queue_wait", "queue_wait_secs"),
        ):
            h = self.metrics.histogram(name)
            out[key] = {
                "count": h.count,
                "p50": round(h.quantile(0.50), 6),
                "p95": round(h.quantile(0.95), 6),
                "p99": round(h.quantile(0.99), 6),
            }
        return out

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """One trace's completed spans in waterfall order (obs/spans.py)."""
        return self.spans.trace(trace_id)

    def telemetry(self) -> Dict[str, Any]:
        snap = self.metrics.snapshot()
        snap["engine"] = "RunService"
        for name, value in self.cache.stats().items():
            snap[f"serve_exec_cache_{name}"] = value
        return snap

    # -- scheduler -----------------------------------------------------------

    def _update_gauges_locked(self) -> None:
        queued = sum(1 for j in self._jobs.values() if j.status == "queued")
        running = sum(1 for j in self._jobs.values() if j.status == "running")
        self.metrics.set_gauge("serve_queue_depth", queued)
        self.metrics.set_gauge("serve_active_jobs", running)

    def _pop_batch(self) -> Optional[List[Job]]:
        """Pop the top job; a multiplex job also gathers EVERY queued
        same-signature multiplex job (any tenant, any priority) into its
        batch — that sharing is the point of the lane engine. Caller
        holds the lock."""
        job: Optional[Job] = None
        while self._heap:
            _, _, candidate = heapq.heappop(self._heap)
            if candidate.status == "queued":  # skip cancelled entries
                job = candidate
                break
        if job is None:
            return None
        batch = [job]
        if job.engine == "multiplex":
            # Footprint-based right-sizing: gather no more same-signature
            # lanes than the device budget fits (obs/memory) — the rest
            # stay queued for the next batch instead of overcommitting.
            budget = self._lane_budget(job.model)
            keep = []
            for entry in self._heap:
                mate = entry[2]
                if (
                    len(batch) < budget
                    and mate.status == "queued"
                    and mate.engine == "multiplex"
                    and mate.signature == job.signature
                ):
                    batch.append(mate)
                else:
                    keep.append(entry)
            if len(batch) > 1:
                heapq.heapify(keep)
                self._heap = keep
        now = time.time()
        for j in batch:
            j.status = "running"
            j.started_at = now
            j.attempts += 1
            wait = max(0.0, now - j.enqueued_at)
            self.metrics.observe("queue_wait_secs", wait)
            self.spans.record(
                "queue_wait",
                start=j.enqueued_at,
                end=now,
                trace_id=j.trace_id,
                parent_id=j.root_span_id,
                attributes={"job_id": j.id, "attempt": j.attempts},
            )
            if self._journal is not None:
                self._journal.start(j.id, j.attempts)
        self._update_gauges_locked()
        return batch

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                    self._paused or not self._heap
                ):
                    self._cv.wait()
                if self._stop:
                    return
                batch = self._pop_batch()
            if not batch:
                continue
            key = batch[0].signature or batch[0].spec
            if not self.breaker.allow(key):
                # Fast-fail while the breaker is open: repeated failures
                # for this signature must not keep burning device time.
                self.metrics.inc("serve_breaker_fastfail", len(batch))
                now = time.time()
                for j in batch:
                    self.spans.record(
                        "breaker_fastfail",
                        start=now,
                        end=now,
                        trace_id=j.trace_id,
                        parent_id=j.root_span_id,
                        status="error",
                        attributes={"job_id": j.id, "signature": key},
                    )
                self._finish(
                    batch,
                    error=f"circuit breaker open for {key!r} after repeated "
                    "failures; retry after the cooldown",
                )
                continue
            exec_t0 = time.time()
            try:
                if batch[0].engine == "multiplex":
                    self._run_multiplex_batch(batch)
                else:
                    self._run_solo(batch[0])
            except Exception as e:
                # The failed attempt is still a span in each job's trace
                # (success spans are recorded by the run paths, which
                # know the cache outcome and engine phase timings).
                now = time.time()
                msg = f"{type(e).__name__}: {e}"
                for j in batch:
                    self.spans.record(
                        "execute",
                        start=exec_t0,
                        end=now,
                        trace_id=j.trace_id,
                        parent_id=j.root_span_id,
                        status="error",
                        attributes={
                            "job_id": j.id, "engine": j.engine,
                            "attempt": j.attempts, "error": msg,
                        },
                    )
                self.breaker.record_failure(key)
                self._handle_failure(batch, e)
            else:
                self.breaker.record_success(key)

    def _handle_failure(self, jobs: List[Job], exc: Exception) -> None:
        """Transient failures retry with deterministic backoff (a
        multiplex capacity failure escalates to the solo engine, which
        sizes its tables dynamically); everything else — and any job out
        of attempts — fails for real."""
        msg = f"{type(exc).__name__}: {exc}"
        transient, escalate = classify_failure(msg)
        if is_oom(msg):
            # OOM post-mortem: engines that died before reporting a
            # ledger snapshot (e.g. a multiplex compile-time OOM) still
            # get the planner's predicted residency recorded.
            for j in jobs:
                if j.memory_at_failure is None:
                    predicted = self._predicted_bytes(j.model, j.engine)
                    if predicted is not None:
                        j.memory_at_failure = {
                            "source": "plan",
                            "engine": j.engine,
                            "total_bytes": predicted,
                        }
        retriable = [
            j for j in jobs
            if transient and j.attempts < self.retry.max_attempts
        ]
        exhausted = [j for j in jobs if j not in retriable]
        if exhausted:
            if transient:
                self.metrics.inc("retry_exhausted", len(exhausted))
            self._finish(exhausted, error=msg)
        for j in retriable:
            if escalate and j.engine == "multiplex":
                j.engine = "tpu_bfs"
                self.metrics.inc("retry_escalated_solo")
                _log.info(
                    "escalating multiplex lane to solo engine",
                    job_id=j.id, trace_id=j.trace_id, attempt=j.attempts,
                )
            delay = self.retry.delay(j.attempts, key=j.id)
            self.metrics.inc("retry_scheduled")
            with self._cv:
                # Queued-but-not-in-heap while backing off: invisible to
                # the scheduler, still cancellable; the timer re-enqueues.
                j.status = "queued"
                j.error = msg  # last error, visible while waiting
                j.backoff_since = time.time()
                self._update_gauges_locked()
                timer = threading.Timer(delay, self._requeue, args=(j,))
                timer.daemon = True
                self._timers.add(timer)
            timer.start()

    def _requeue(self, job: Job) -> None:
        with self._cv:
            self._timers = {t for t in self._timers if t.is_alive()}
            if self._stop or job.status != "queued":
                return  # cancelled (or service stopping) while backing off
            job.error = None
            job.memory_at_failure = None  # fresh attempt, fresh post-mortem
            now = time.time()
            if job.backoff_since is not None:
                # The wait itself is part of the job's story: a span in
                # the ORIGINAL trace, carrying the engine it retries on
                # (so an escalation reads right off the waterfall).
                self.spans.record(
                    "backoff_wait",
                    start=job.backoff_since,
                    end=now,
                    trace_id=job.trace_id,
                    parent_id=job.root_span_id,
                    attributes={
                        "job_id": job.id,
                        "attempt": job.attempts,
                        "next_engine": job.engine,
                    },
                )
                job.backoff_since = None
            job.enqueued_at = now  # fresh queue_wait leg
            heapq.heappush(self._heap, (-job.priority, next(self._seq), job))
            self._update_gauges_locked()
            if self._journal is not None:
                self._journal.retry(job.id)
            self._cv.notify()

    def _finish(self, jobs: List[Job], error: Optional[str] = None) -> None:
        status = "failed" if error is not None else "done"
        # Durability and the trace's closing spans land BEFORE the
        # in-memory status flip: the result payload is on disk before the
        # journal's terminal record (replay never claims "done" without a
        # readable result), and a client that observes a terminal status
        # is guaranteed the job's complete ledger — the root "job" span
        # included.
        for j in jobs:
            j.error = error if error is not None else j.error
            write_t0 = time.time()
            if (
                error is None
                and self._results is not None
                and j.result is not None
            ):
                self._results.put(j.id, j.result)
            if self._journal is not None:
                self._journal.result(
                    j.id, status, error=j.error,
                    memory=(
                        j.memory_at_failure if error is not None else None
                    ),
                )
            done_at = time.time()
            if self._results is not None or self._journal is not None:
                self.spans.record(
                    "result_write",
                    start=write_t0,
                    end=done_at,
                    trace_id=j.trace_id,
                    parent_id=j.root_span_id,
                    attributes={"job_id": j.id, "status": status},
                )
            # The trace closes: the root "job" span (its pre-assigned id
            # is what every child above parented to) plus the job's
            # submit→result latency sample.
            self.metrics.observe(
                "submit_to_result_secs", max(0.0, done_at - j.submitted_at)
            )
            self.spans.record(
                "job",
                start=j.submitted_at,
                end=done_at,
                trace_id=j.trace_id,
                span_id=j.root_span_id,
                status="ok" if error is None else "error",
                attributes={
                    "job_id": j.id,
                    "spec": j.spec,
                    "tenant": j.tenant,
                    "engine": j.engine,
                    "attempts": j.attempts,
                    "final_status": status,
                    **({"error": error} if error else {}),
                },
            )
            if error is not None:
                _log.warning(
                    "job failed",
                    job_id=j.id, trace_id=j.trace_id, spec=j.spec,
                    attempts=j.attempts, error=error,
                )
        now = time.time()
        with self._cv:
            for j in jobs:
                j.finished_at = now
                j.status = status
                self.metrics.inc(
                    "serve_failed" if error is not None else "serve_completed"
                )
            self._update_gauges_locked()
            self._cv.notify_all()

    # -- execution -----------------------------------------------------------

    def _cache_get(self, model, engine: str, options: Dict[str, Any]):
        compiled, hit = self.cache.get(model, engine, **options)
        self.metrics.inc(
            "serve_exec_cache_hits" if hit else "serve_exec_cache_misses"
        )
        return compiled, hit

    def _record_execute(self, job: Job, start: float, checker,
                        cache_hit: bool, span_id: Optional[str] = None,
                        attach_phases: bool = True, **extra: Any) -> None:
        """One "execute" span per attempt, with the engine's phase
        timers attached as children — how device time shows up in the
        job waterfall without the engines knowing about serve. Solo runs
        pass `span_id` (pre-assigned, handed to the engine as its span
        parent) and `attach_phases=False`: the engine itself recorded
        its run/era/phase spans under it."""
        end = time.time()
        span = self.spans.record(
            "execute",
            start=start,
            end=end,
            trace_id=job.trace_id,
            span_id=span_id,
            parent_id=job.root_span_id,
            attributes={
                "job_id": job.id,
                "engine": job.engine,
                "attempt": job.attempts,
                "cache": "hit" if cache_hit else "miss",
                **extra,
            },
        )
        if attach_phases:
            phase_ms = (checker.telemetry() or {}).get("phase_ms") or {}
            attach_phase_spans(
                self.spans, phase_ms,
                trace_id=job.trace_id, parent_id=span["span_id"], end=end,
                attributes={"job_id": job.id},
            )

    def _run_multiplex_batch(self, jobs: List[Job]) -> None:
        from ..engines.multiplex import run_multiplexed

        exec_t0 = time.time()
        compiled, hit = self._cache_get(
            jobs[0].model, "multiplex", self.lane_options
        )
        builders = []
        for j in jobs:
            b = compiled.builder().multiplex_lane(True)
            if j.options.get("target_max_depth"):
                b.target_max_depth(j.options["target_max_depth"])
            builders.append(b)
        checkers = run_multiplexed(builders, **self.lane_options)
        for j, checker in zip(jobs, checkers):
            j.result = self._result_payload(j, checker)
            self.metrics.inc("serve_multiplexed_jobs")
            self._record_execute(
                j, exec_t0, checker, hit, lanes=len(jobs),
            )
        self.metrics.inc(
            "serve_batches",
            (len(jobs) + self.lanes - 1) // self.lanes,
        )
        self._finish(jobs)

    def _run_solo(self, job: Job) -> None:
        exec_t0 = time.time()
        # Pre-assigned execute-span id: the engine parents its own
        # run/era/phase spans to it while executing; the span itself is
        # sealed after the join.
        exec_span_id = new_span_id()
        if job.engine == "tpu_bfs":
            compiled, hit = self._cache_get(
                job.model, "tpu_bfs", self.solo_options
            )
            builder = compiled.builder()
            if job.options.get("target_max_depth"):
                builder.target_max_depth(job.options["target_max_depth"])
            builder.spans(
                self.spans, trace_id=job.trace_id, parent_id=exec_span_id
            )
            checker = compiled.spawn(builder)
            try:
                checker.join()
            except Exception as e:
                # An OOM death still has a live memory ledger on the
                # engine: snapshot it onto the job before the failure
                # path journals it.
                self._note_memory_at_failure(job, checker, e)
                raise
        else:  # host bfs
            hit = False
            builder = job.model.checker()
            if job.options.get("target_max_depth"):
                builder.target_max_depth(job.options["target_max_depth"])
            builder.spans(
                self.spans, trace_id=job.trace_id, parent_id=exec_span_id
            )
            checker = builder.spawn_bfs().join()
        job.result = self._result_payload(job, checker)
        self._record_execute(
            job, exec_t0, checker, hit,
            span_id=exec_span_id, attach_phases=False,
        )
        self._finish([job])

    def _note_memory_at_failure(self, job: Job, checker, exc) -> None:
        """Capture the engine's memory-ledger snapshot onto an
        OOM-failed job so `GET /jobs/{id}` shows post-mortem residency."""
        if not is_oom(f"{type(exc).__name__}: {exc}"):
            return
        try:
            snap = (checker.telemetry() or {}).get("memory")
        except Exception:
            snap = None
        if snap:
            job.memory_at_failure = {"source": "ledger", **snap}

    def _result_payload(self, job: Job, checker) -> Dict[str, Any]:
        model = checker.model()
        expectations = {p.name: p.expectation.value for p in model.properties()}
        discoveries = {}
        for name, path in checker.discoveries().items():
            entry: Dict[str, Any] = {
                "expectation": expectations.get(name),
                "depth": len(path),
                "encoded": path.encode(model),
            }
            try:
                entry["explain"] = path.explain(model)
            except Exception as e:  # forensics are best-effort
                entry["explain_error"] = f"{type(e).__name__}: {e}"
            discoveries[name] = entry
        payload = {
            "engine": job.engine,
            "state_count": checker.state_count(),
            "unique_state_count": checker.unique_state_count(),
            "max_depth": checker.max_depth(),
            "discoveries": discoveries,
            "telemetry": checker.telemetry(),
            "coverage": checker.coverage(),
        }
        try:
            space = checker.space_profile()
        except Exception:  # the profile is observability, never job-fatal
            space = None
        if space:
            payload["space"] = space
        return payload
