"""``python -m stateright_tpu.serve [HOST:PORT]`` — start the run server.

Scheduler knobs ride flags; everything else is serve/README.md.
"""

from __future__ import annotations

import argparse

from .http import ServeServer
from .service import RunService


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m stateright_tpu.serve",
        description="multi-tenant model-checking run server",
    )
    parser.add_argument(
        "address", nargs="?", default="127.0.0.1:3001",
        help="bind address (default 127.0.0.1:3001; port 0 = ephemeral)",
    )
    parser.add_argument(
        "--workers", type=int, default=2, help="scheduler worker threads"
    )
    parser.add_argument(
        "--lanes", type=int, default=32,
        help="multiplexed lane count per fused batch",
    )
    parser.add_argument(
        "--max-active", type=int, default=256,
        help="per-tenant active-job quota",
    )
    parser.add_argument(
        "--per-minute", type=int, default=600,
        help="per-tenant submissions-per-minute rate limit",
    )
    parser.add_argument(
        "--log-level",
        default=None,
        choices=["debug", "info", "warning", "error", "off"],
        help="structured-log threshold (default: $STATERIGHT_LOG or warning)",
    )
    args = parser.parse_args(argv)
    if args.log_level:
        from ..obs.log import configure

        configure(level=args.log_level)
    server = ServeServer(
        RunService(
            workers=args.workers,
            lanes=args.lanes,
            quota_max_active=args.max_active,
            quota_per_minute=args.per_minute,
        ),
        args.address,
    )
    print(f"Run service ready. {server.url}")
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
