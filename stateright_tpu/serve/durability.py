"""Serve-layer durability: job journal, result store, retry, breaker.

Four self-contained pieces that `RunService` (serve/service.py) composes
so a restarted service loses nothing and a transient failure never
becomes a client-visible error:

  JobJournal     a JSONL write-ahead log of job lifecycle records
                 (submit / start / result / cancel / retry). Every
                 append is flushed + fsynced before the state change it
                 describes is acknowledged; `replay()` folds the log
                 back into per-job states on restart, and `compact()`
                 atomically rewrites it to one folded record per live
                 job so the log stays bounded.
  ResultStore    finished result payloads as one JSON file per job
                 (atomic tmp+replace writes), so a restarted service
                 serves completed results without re-running anything;
                 `gc()` expires files past a TTL.
  RetryPolicy    exponential backoff with DETERMINISTIC jitter: the
                 delay for (attempt, key) is a pure function of the
                 policy seed, so tests and incident forensics can
                 reproduce exact schedules. `classify_failure` decides
                 which errors are transient (resource exhaustion,
                 worker crashes, interrupts) and which must escalate a
                 lane job to a solo engine with real capacity.
  CircuitBreaker classic closed -> open -> half-open per key (model
                 signature): after `threshold` consecutive failures the
                 key fast-fails for `cooldown` seconds, then ONE trial
                 is admitted; success closes, failure re-opens. The
                 clock is injectable for deterministic tests.

Journal record shapes (one JSON object per line)::

  {"rec": "submit", "t": ..., "job": {"id", "tenant", "spec", "engine",
                                      "priority", "options"}}
  {"rec": "start",  "t": ..., "job_id": ..., "attempt": N}
  {"rec": "result", "t": ..., "job_id": ..., "status": "done"|"failed",
                    "error": ...?, "memory": ...?}
  {"rec": "cancel", "t": ..., "job_id": ...}
  {"rec": "retry",  "t": ..., "job_id": ...}

A truncated final line (kill mid-append) is ignored; every complete
prefix of the log folds to a consistent state.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..obs.log import get_logger

_log = get_logger("serve.durability")

__all__ = [
    "CircuitBreaker",
    "JobJournal",
    "ResultStore",
    "RetryPolicy",
    "classify_failure",
    "is_oom",
]


# ---------------------------------------------------------------------------
# Failure classification
# ---------------------------------------------------------------------------

# Substrings marking a failure as TRANSIENT: worth retrying, because the
# retry runs under different conditions (bigger tables after escalation, a
# fresh worker, freed device memory) rather than deterministically
# re-failing. Speclint rejections, bad specs, and model bugs are NOT here.
_TRANSIENT_MARKERS = (
    "probe budget",          # visited-table exhaustion (engines raise this)
    "lane budget",           # lane outgrew its fixed shape
    "did not complete within the lane",
    "table_capacity",        # capacity guidance in engine errors
    "queue_capacity",
    "resource_exhausted",    # XLA OOM spelling
    "out of memory",
    "worker crashed",
    "interrupted",
)

# Substrings that additionally mean "this shape is too small, run solo":
# retrying the same multiplex lane would hit the identical wall, but the
# solo engine sizes tables dynamically (growth + spill) and succeeds.
_ESCALATE_MARKERS = (
    "lane budget",
    "did not complete within the lane",
    "probe budget",
    "run it solo",
)


def classify_failure(error: str) -> Tuple[bool, bool]:
    """``(transient, escalate_solo)`` for an error string."""
    low = error.lower()
    transient = any(m in low for m in _TRANSIENT_MARKERS)
    escalate = transient and any(m in low for m in _ESCALATE_MARKERS)
    return transient, escalate


# Substrings that specifically mean the device ran out of memory (as
# opposed to the other transient markers). An OOM failure carries a
# post-mortem residency snapshot — the memory ledger at death, or the
# planner's prediction when the engine died before reporting — into the
# journal so `GET /jobs/{id}` can answer "what was resident when it died".
_OOM_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "out-of-memory",
)


def is_oom(error: str) -> bool:
    """Did this failure die on device memory?"""
    low = error.lower()
    return any(m in low for m in _OOM_MARKERS)


# ---------------------------------------------------------------------------
# Retry policy
# ---------------------------------------------------------------------------


class RetryPolicy:
    """Bounded exponential backoff with deterministic, per-key jitter."""

    def __init__(self, *, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 5.0, jitter: float = 0.5, seed: int = 0):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if base_delay <= 0 or max_delay < base_delay:
            raise ValueError("need 0 < base_delay <= max_delay")
        if not 0 <= jitter <= 1:
            raise ValueError("jitter is a fraction in [0, 1]")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self.seed = seed

    def delay(self, attempt: int, key: str = "") -> float:
        """Backoff before retry number `attempt` (1-based: the delay
        after the first failure is ``delay(1)``). Deterministic: the
        jitter fraction is a hash of (seed, key, attempt), so the same
        job always gets the same schedule."""
        if attempt < 1:
            raise ValueError("attempt is 1-based")
        base = min(self.max_delay, self.base_delay * (2 ** (attempt - 1)))
        digest = hashlib.sha256(
            f"{self.seed}:{key}:{attempt}".encode()
        ).digest()
        frac = int.from_bytes(digest[:8], "big") / float(1 << 64)
        return base * (1.0 + self.jitter * frac)

    def view(self) -> Dict[str, Any]:
        return {
            "max_attempts": self.max_attempts,
            "base_delay": self.base_delay,
            "max_delay": self.max_delay,
            "jitter": self.jitter,
        }


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Per-key closed/open/half-open breaker with an injectable clock."""

    def __init__(self, *, threshold: int = 5, cooldown: float = 30.0,
                 clock=time.monotonic):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = threshold
        self.cooldown = cooldown
        self._clock = clock
        self._lock = threading.Lock()
        # key -> {"state", "failures", "opened_at", "trial"}
        self._keys: Dict[str, Dict[str, Any]] = {}

    def _entry(self, key: str) -> Dict[str, Any]:
        return self._keys.setdefault(
            key, {"state": "closed", "failures": 0, "opened_at": 0.0,
                  "trial": False}
        )

    def allow(self, key: str) -> bool:
        """May a request for `key` proceed right now? An open key admits
        exactly ONE trial request once the cooldown elapses (half-open);
        further requests fast-fail until that trial reports back."""
        with self._lock:
            e = self._entry(key)
            if e["state"] == "closed":
                return True
            if e["state"] == "open":
                if self._clock() - e["opened_at"] < self.cooldown:
                    return False
                e["state"] = "half-open"
                e["trial"] = True
                return True
            # half-open: only the single in-flight trial is admitted.
            if e["trial"]:
                return False
            e["trial"] = True
            return True

    def record_success(self, key: str) -> None:
        with self._lock:
            e = self._entry(key)
            e.update(state="closed", failures=0, trial=False)

    def record_failure(self, key: str) -> None:
        with self._lock:
            e = self._entry(key)
            e["failures"] += 1
            e["trial"] = False
            if e["state"] == "half-open" or e["failures"] >= self.threshold:
                e["state"] = "open"
                e["opened_at"] = self._clock()

    def state(self, key: str) -> str:
        with self._lock:
            return self._keys.get(key, {"state": "closed"})["state"]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "threshold": self.threshold,
                "cooldown": self.cooldown,
                "open_keys": sorted(
                    k for k, e in self._keys.items() if e["state"] != "closed"
                ),
                "states": {k: e["state"] for k, e in self._keys.items()},
            }


# ---------------------------------------------------------------------------
# Write-ahead job journal
# ---------------------------------------------------------------------------


class JobJournal:
    """Append-only JSONL WAL for job lifecycle; fsync on every append."""

    def __init__(self, path: str, metrics=None):
        self.path = path
        self._metrics = metrics
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    # -- appends -------------------------------------------------------------

    def _append(self, rec: Dict[str, Any]) -> None:
        rec = dict(rec)
        rec["t"] = time.time()
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        if self._metrics is not None:
            self._metrics.inc("journal_records")
            self._metrics.inc("journal_bytes", len(line))

    def submit(self, job_fields: Dict[str, Any]) -> None:
        self._append({"rec": "submit", "job": job_fields})

    def start(self, job_id: str, attempt: int) -> None:
        self._append({"rec": "start", "job_id": job_id, "attempt": attempt})

    def result(self, job_id: str, status: str,
               error: Optional[str] = None,
               memory: Optional[Dict[str, Any]] = None) -> None:
        rec: Dict[str, Any] = {
            "rec": "result", "job_id": job_id, "status": status,
        }
        if error is not None:
            rec["error"] = error
        if memory is not None:
            # OOM post-mortem: the residency snapshot rides the terminal
            # record so replay restores it alongside the error.
            rec["memory"] = memory
        self._append(rec)

    def cancel(self, job_id: str) -> None:
        self._append({"rec": "cancel", "job_id": job_id})

    def retry(self, job_id: str) -> None:
        self._append({"rec": "retry", "job_id": job_id})

    # -- replay / compaction -------------------------------------------------

    @staticmethod
    def replay(path: str) -> Dict[str, Dict[str, Any]]:
        """Fold the log into ``{job_id: {"job", "status", "attempts",
        "error"}}`` in submission order. Tolerates a truncated final
        line (kill mid-append) and records for unknown ids (compacted
        prefix lost); every complete prefix folds consistently."""
        folded: Dict[str, Dict[str, Any]] = {}
        if not os.path.exists(path):
            return folded
        torn = 0
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn += 1  # torn tail from a kill mid-append
                    continue
                kind = rec.get("rec")
                if kind == "submit":
                    job = rec.get("job") or {}
                    jid = job.get("id")
                    if jid:
                        folded[jid] = {
                            "job": job, "status": "queued",
                            "attempts": 0, "error": None,
                        }
                    continue
                entry = folded.get(rec.get("job_id"))
                if entry is None:
                    continue
                if kind == "start":
                    entry["status"] = "running"
                    entry["attempts"] = int(
                        rec.get("attempt", entry["attempts"] + 1)
                    )
                elif kind == "result":
                    entry["status"] = rec.get("status", "done")
                    entry["error"] = rec.get("error")
                    entry["memory"] = rec.get("memory")
                elif kind == "cancel":
                    entry["status"] = "cancelled"
                elif kind == "retry":
                    entry["status"] = "queued"
                    entry["error"] = None
        if torn:
            _log.warning(
                "journal replay skipped unparsable lines",
                path=path, skipped=torn,
            )
        return folded

    def compact(self, folded: Dict[str, Dict[str, Any]]) -> None:
        """Atomically rewrite the log as one folded snapshot: a submit
        record per job plus its terminal/attempt records. Bounds the log
        after replay and after result GC drops old jobs."""
        tmp = self.path + ".tmp"
        with self._lock:
            with open(tmp, "w", encoding="utf-8") as out:
                for jid, entry in folded.items():
                    now = time.time()
                    out.write(json.dumps(
                        {"rec": "submit", "t": now, "job": entry["job"]},
                        separators=(",", ":"),
                    ) + "\n")
                    status = entry["status"]
                    if entry["attempts"]:
                        out.write(json.dumps(
                            {"rec": "start", "t": now, "job_id": jid,
                             "attempt": entry["attempts"]},
                            separators=(",", ":"),
                        ) + "\n")
                    if status in ("done", "failed"):
                        rec = {"rec": "result", "t": now, "job_id": jid,
                               "status": status}
                        if entry.get("error"):
                            rec["error"] = entry["error"]
                        if entry.get("memory"):
                            rec["memory"] = entry["memory"]
                        out.write(json.dumps(rec, separators=(",", ":")) + "\n")
                    elif status == "cancelled":
                        out.write(json.dumps(
                            {"rec": "cancel", "t": now, "job_id": jid},
                            separators=(",", ":"),
                        ) + "\n")
                    elif status == "queued" and entry["attempts"]:
                        out.write(json.dumps(
                            {"rec": "retry", "t": now, "job_id": jid},
                            separators=(",", ":"),
                        ) + "\n")
                out.flush()
                os.fsync(out.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
        _log.info("journal compacted", path=self.path, jobs=len(folded))
        if self._metrics is not None:
            self._metrics.inc("journal_compactions")

    def stats(self) -> Dict[str, Any]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        return {"path": self.path, "bytes": size}

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Result store
# ---------------------------------------------------------------------------


class ResultStore:
    """Finished result payloads on disk, one JSON per job, TTL-expired."""

    def __init__(self, root: str, *, ttl: float = 7 * 24 * 3600.0,
                 clock=time.time, metrics=None):
        if ttl <= 0:
            raise ValueError("result ttl must be positive (seconds)")
        self.root = root
        self.ttl = ttl
        self._clock = clock
        self._metrics = metrics
        os.makedirs(root, exist_ok=True)

    def _path(self, job_id: str) -> str:
        return os.path.join(self.root, f"{job_id}.json")

    def put(self, job_id: str, payload: Dict[str, Any]) -> None:
        doc = {"saved_at": self._clock(), "result": payload}
        path = self._path(job_id)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        if self._metrics is not None:
            self._metrics.inc("serve_results_persisted")

    def get(self, job_id: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(job_id), encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        if self._clock() - doc.get("saved_at", 0) > self.ttl:
            return None
        return doc.get("result")

    def gc(self) -> List[str]:
        """Delete expired results; returns the expired job ids (the
        caller prunes its in-memory jobs + journal to match)."""
        expired: List[str] = []
        now = self._clock()
        for name in os.listdir(self.root):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path, encoding="utf-8") as fh:
                    saved_at = json.load(fh).get("saved_at", 0)
            except (OSError, ValueError):
                saved_at = 0  # unreadable -> treat as ancient
            if now - saved_at > self.ttl:
                try:
                    os.remove(path)
                except OSError:
                    continue
                expired.append(name[: -len(".json")])
        if expired:
            _log.info(
                "result store expired results",
                root=self.root, expired=len(expired),
            )
            if self._metrics is not None:
                self._metrics.inc("serve_results_gc", len(expired))
        return expired

    def stats(self) -> Dict[str, Any]:
        try:
            names = [n for n in os.listdir(self.root) if n.endswith(".json")]
        except OSError:
            names = []
        return {"root": self.root, "results": len(names), "ttl": self.ttl}
