"""Scratch: [cap,2] pair gather/scatter vs 2x flat u32 ops (round 5)."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32
K = 30
CAP = 1 << 22
W = 75776

flat1 = (jnp.arange(CAP, dtype=u) * u(0x9E3779B9))
flat2 = (jnp.arange(CAP, dtype=u) * u(0x85EBCA6B))
pair = jnp.stack([flat1, flat2], axis=1)  # [CAP, 2]
iota = jnp.arange(W, dtype=u)


def mix(x, salt):
    x = (x ^ u(salt)) * u(0x9E3779B9)
    return x ^ (x >> u(16))


def timeit(name, fn):
    f = jax.jit(fn)
    np.asarray(f())
    t0 = time.perf_counter()
    s = np.asarray(f())
    dt = time.perf_counter() - t0
    print(f"{name:46s} {dt/K*1000:8.2f} ms/iter  sum={s}", flush=True)


def f_two_flat():
    def body(i, acc):
        idx = mix(iota + i * u(W), 3) & u(CAP - 1)
        return acc ^ flat1[idx].sum(dtype=u) ^ flat2[idx].sum(dtype=u)
    return lax.fori_loop(u(0), u(K), body, u(0))
timeit("2x flat u32 gather W=75776", f_two_flat)


def f_pair():
    def body(i, acc):
        idx = mix(iota + i * u(W), 3) & u(CAP - 1)
        rows = pair[idx]  # [W, 2]
        return acc ^ rows[:, 0].sum(dtype=u) ^ rows[:, 1].sum(dtype=u)
    return lax.fori_loop(u(0), u(K), body, u(0))
timeit("1x [CAP,2] pair gather W=75776", f_pair)


def f_one_flat():
    def body(i, acc):
        idx = mix(iota + i * u(W), 3) & u(CAP - 1)
        return acc ^ flat1[idx].sum(dtype=u)
    return lax.fori_loop(u(0), u(K), body, u(0))
timeit("1x flat u32 gather W=75776 (floor)", f_one_flat)

# u64 packed gather
jax.config.update("jax_enable_x64", True)
try:
    flat64 = flat1.astype(jnp.uint64) | (flat2.astype(jnp.uint64) << 32)
    def f_u64():
        def body(i, acc):
            idx = mix(iota + i * u(W), 3) & u(CAP - 1)
            g = flat64[idx]
            return acc ^ (g & jnp.uint64(0xFFFFFFFF)).sum(dtype=jnp.uint64).astype(u) ^ (g >> 32).sum(dtype=jnp.uint64).astype(u)
        return lax.fori_loop(u(0), u(K), body, u(0))
    timeit("1x u64 gather W=75776", f_u64)
except Exception as e:
    print("u64 gather failed:", repr(e)[:200])
