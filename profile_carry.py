"""Scratch: does while/fori carry SIZE dominate per-iteration cost? (round 5)"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32
K = 30


def timeit(name, mk_args, fn):
    f = jax.jit(fn, donate_argnums=tuple(range(len(mk_args()))))
    out = f(*mk_args())
    np.asarray(jax.tree.leaves(out)[-1])
    args = mk_args()
    t0 = time.perf_counter()
    out = f(*args)
    s = np.asarray(jax.tree.leaves(out)[-1])
    dt = time.perf_counter() - t0
    print(f"{name:52s} {dt/K*1000:8.2f} ms/iter  (sum={s.ravel()[:1]})", flush=True)


def mk_while(n_lanes, lane_words, touch):
    """while_loop carrying n_lanes x [lane_words] u32; body touches
    element 0 of each lane (touch=True) or nothing."""
    def run(*lanes_and_i):
        lanes = lanes_and_i[:-1]
        def cond(c):
            return c[-1] < u(K)
        def body(c):
            ls, i = c[:-1], c[-1]
            if touch:
                ls = tuple(l.at[0].add(u(1)) for l in ls)
            return ls + (i + u(1),)
        out = lax.while_loop(cond, body, tuple(lanes) + (lanes_and_i[-1],))
        return out
    return run


for n_lanes, words in [(1, 1 << 10), (4, 1 << 22), (7, 1 << 20), (11, 1 << 22)]:
    mb = n_lanes * words * 4 / 1e6
    mk = lambda n_lanes=n_lanes, words=words: tuple(
        np.zeros(words, dtype=np.uint32) for _ in range(n_lanes)
    ) + (np.uint32(0),)
    timeit(f"while {n_lanes}x[{words}] ({mb:.0f}MB) touch0", mk, mk_while(n_lanes, words, True))
    timeit(f"while {n_lanes}x[{words}] ({mb:.0f}MB) notouch", mk, mk_while(n_lanes, words, False))

# same but fori_loop
def mk_fori(touch):
    def run(*lanes):
        def body(i, ls):
            if touch:
                return tuple(l.at[0].add(u(1)) for l in ls)
            return ls
        return lax.fori_loop(0, K, body, tuple(lanes))
    return run

mk11 = lambda: tuple(np.zeros(1 << 22, dtype=np.uint32) for _ in range(11))
timeit("fori 11x[4M] (185MB) touch0", mk11, mk_fori(True))

# engine-like: big carry + a realistic scatter into one lane
def mk_scatter_body(*lanes_and_i):
    iota = jnp.arange(1 << 15, dtype=u)
    def cond(c):
        return c[-1] < u(K)
    def body(c):
        ls, i = c[:-1], c[-1]
        idx = ((iota + i) * u(0x9E3779B9)) & u((1 << 22) - 1)
        l0 = ls[0].at[idx].set(iota, mode="drop")
        return (l0,) + ls[1:] + (i + u(1),)
    return lax.while_loop(cond, body, lanes_and_i)

mk11i = lambda: tuple(np.zeros(1 << 22, dtype=np.uint32) for _ in range(11)) + (np.uint32(0),)
timeit("while 11x[4M] + 32k scatter into lane0", mk11i, mk_scatter_body)
