import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

if __name__ == "__main__":
    tm = TwoPhaseTensor(10)
    opts = dict(
        chunk_size=8192,
        queue_capacity=1 << 21,
        table_capacity=1 << 24,
        sync_steps=128,
    )
    t0 = time.perf_counter()
    c = TensorModelAdapter(tm).checker().symmetry().spawn_tpu_bfs(**opts).join()
    dt = time.perf_counter() - t0
    print(
        f"2pc-10-sym device: secs={dt:.1f} unique={c.unique_state_count()} "
        f"gen={c.state_count()} tel={c.telemetry()}",
        flush=True,
    )
    assert c.discovery("consistent") is None
    assert c.discovery("abort agreement") is not None
    print("verdicts ok", flush=True)
