"""Scratch: carry penalty — device-staged args, donation on/off (round 5)."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

u = jnp.uint32
K = 30
N = 1 << 22  # 16MB per lane


def body_factory(K):
    def run(l0, l1, l2, l3, i0):
        def cond(c):
            return c[-1] < u(K)
        def body(c):
            ls, i = c[:-1], c[-1]
            ls = tuple(l.at[0].add(u(1)) for l in ls)
            return ls + (i + u(1),)
        return lax.while_loop(cond, body, (l0, l1, l2, l3, i0))
    return run


def stage():
    # Device-resident arrays produced BY a jit (so they're ordinary device
    # buffers, like the engine's inter-era table/queue).
    mk = jax.jit(lambda: tuple(jnp.zeros(N, dtype=u) for _ in range(4)))
    out = mk()
    jax.tree.map(lambda x: np.asarray(x[:1]), out)  # settle
    return out


for donate, label in ((True, "donated"), (False, "not-donated")):
    f = jax.jit(body_factory(K), donate_argnums=(0, 1, 2, 3, 4) if donate else ())
    args = stage()
    out = f(*args, u(0))  # compile
    np.asarray(out[-1])
    args = stage()
    i0 = jnp.asarray(np.uint32(0))
    t0 = time.perf_counter()
    out = f(*args, i0)
    s = np.asarray(out[-1])
    dt = time.perf_counter() - t0
    print(f"device args, {label:12s} while 4x[4M] K={K}: total={dt*1000:8.1f} ms ({dt/K*1000:6.2f} ms/iter)", flush=True)

# returning big lanes from an in-jit-created loop: is return free?
def run_injit_ret(i0):
    ls = tuple(jnp.zeros(N, dtype=u) + i0 * u(0) for _ in range(4))
    def cond(c):
        return c[-1] < u(K)
    def body(c):
        ls, i = c[:-1], c[-1]
        ls = tuple(l.at[0].add(u(1)) for l in ls)
        return ls + (i + u(1),)
    return lax.while_loop(cond, body, ls + (i0,))

f = jax.jit(run_injit_ret)
out = f(u(0))
np.asarray(out[-1])
t0 = time.perf_counter()
out = f(jnp.asarray(np.uint32(0)))
s = np.asarray(out[-1])
dt = time.perf_counter() - t0
print(f"in-jit create, RETURN 4x[4M]   K={K}: total={dt*1000:8.1f} ms ({dt/K*1000:6.2f} ms/iter)", flush=True)

# chain: feed returned buffers back in as donated args (era-2 simulation)
f2 = jax.jit(body_factory(K), donate_argnums=(0, 1, 2, 3, 4))
out2 = f2(*out[:4], out[-1])  # compile likely shared... still, run twice
np.asarray(out2[-1])
out = f(jnp.asarray(np.uint32(0)))
np.asarray(out[-1])
t0 = time.perf_counter()
out2 = f2(*out[:4], out[-1])
s = np.asarray(out2[-1])
dt = time.perf_counter() - t0
print(f"returned bufs -> donated era2   K={K}: total={dt*1000:8.1f} ms ({dt/K*1000:6.2f} ms/iter)", flush=True)
