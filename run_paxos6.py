import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models.paxos import PaxosTensorExhaustive

if __name__ == "__main__":
    t0 = time.perf_counter()
    c = (
        TensorModelAdapter(PaxosTensorExhaustive(6))
        .checker()
        .threads(8)
        .timeout(3600)
        .spawn_bfs()
        .join()
    )
    dt = time.perf_counter() - t0
    print(
        f"paxos-6 vbfs: unique={c.unique_state_count()} gen={c.state_count()} "
        f"{dt:.1f}s done_exhaustive={not c._timed_out()}",
        flush=True,
    )
    for name in ("network within capacity", "ballot rounds within range", "linearizable"):
        d = c.discovery(name)
        print(f"  guard {name!r}: {'VIOLATED' if d is not None else 'quiet'}", flush=True)
