"""Scratch: chunk-scaling experiment for the 2pc-7 device run (round 5)."""
import sys
import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 6144
qcap = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 20
tcap = int(sys.argv[3]) if len(sys.argv) > 3 else 1 << 22

tm = TwoPhaseTensor(7)
opts = dict(chunk_size=chunk, queue_capacity=qcap, table_capacity=tcap)
t0 = time.perf_counter()
c = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**opts).join()  # compile
print(f"compile+first run: {time.perf_counter()-t0:.1f}s", flush=True)
for i in range(3):
    t0 = time.perf_counter()
    c = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**opts).join()
    dt = time.perf_counter() - t0
    print(
        f"chunk={chunk} secs={dt:.3f} gen_rate={c.state_count()/dt:,.0f} "
        f"unique={c.unique_state_count()} tel={c.telemetry()}",
        flush=True,
    )
