"""Scratch: per-op honest microbench at 2pc-7 step shapes (round 5).

Times each piece of the BFS era-step body in its own jitted counted loop,
with a checksum carry that data-depends on the op output (block_until_ready
lies on this platform; np.asarray of a dependent scalar is the only honest
sync). Fresh pseudo-random inputs are derived per iteration from the loop
counter so access patterns stay realistic.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

C = int(sys.argv[1]) if len(sys.argv) > 1 else 6144
A = 37
CA = C * A
TCAP = 1 << 22
RCAP = max(64 * A, CA // 8)
RCAP2 = 1 << (CA // 4 - 1).bit_length()  # valid-width probe cap
DEDUP_CAP = 1 << (2 * CA - 1).bit_length()
K = 30
u = jnp.uint32

from stateright_tpu.ops import frontier as fr
from stateright_tpu.ops import visited_set as vs
from stateright_tpu.ops.expand import build_eval_and_expand
from stateright_tpu.models import TwoPhaseTensor

tm = TwoPhaseTensor(7)
props = tm.tensor_properties()
eval_and_expand = build_eval_and_expand(tm, props, C)


def mix(x, salt):
    x = (x ^ u(salt)) * u(0x9E3779B9)
    x = (x ^ (x >> u(16))) * u(0x85EBCA6B)
    return x ^ (x >> u(13))


def timeit(name, fn, *args):
    f = jax.jit(fn)
    out = f(*args)  # compile
    np.asarray(out)
    t0 = time.perf_counter()
    out = f(*args)
    s = np.asarray(out)
    dt = time.perf_counter() - t0
    print(f"{name:34s} {dt/K*1000:8.3f} ms/iter   (total {dt:.3f}s, sum={s})", flush=True)


iota_ca = jnp.arange(CA, dtype=u)
iota_c = jnp.arange(C, dtype=u)

# A ~7%-loaded table like the real run's.
key = jax.random.PRNGKey(0)
nfill = int(0.07 * TCAP)
fill1 = jax.random.randint(key, (nfill,), 1, 1 << 30, dtype=jnp.int32).astype(u)
fill2 = jax.random.randint(jax.random.PRNGKey(1), (nfill,), 1, 1 << 30, dtype=jnp.int32).astype(u)
table0 = vs.empty_table(TCAP)
table0, _, _, _ = vs.insert_jit(table0, fill1, fill2, fill1, fill2, jnp.ones(nfill, bool))
table0 = tuple(np.asarray(t) for t in table0)

# Realistic validity/dup profile: ~20% valid, of which ~2/3 are dups of
# earlier steps (simulated by drawing keys from a small window).
def cand(i, salt):
    h1 = mix(iota_ca + i * u(CA), salt)
    h2 = mix(iota_ca * u(3) + i, salt + 7) | u(1)
    valid = (mix(iota_ca, salt + 13) & u(15)) < u(3)
    return h1, h2, valid


def loop(body):
    def run():
        def step(i, acc):
            return acc ^ body(i)
        return lax.fori_loop(u(0), u(K), step, u(0))
    return run


# 1. candidate generation alone (the shared preamble cost)
timeit("preamble (mix+valid)", loop(lambda i: cand(i, 1)[0].sum(dtype=u)))

# 2. claim_dedup at C*A width
def f_dedup(i):
    h1, h2, valid = cand(i, 2)
    reps = fr.claim_dedup(h1, h2, valid, DEDUP_CAP)
    return reps.sum(dtype=u)
timeit("claim_dedup", loop(f_dedup))

# 3. compact_ids at C*A -> RCAP
def f_compact(i):
    h1, h2, valid = cand(i, 3)
    ids, cv, n = vs._compact_ids(valid, RCAP)
    return ids.sum(dtype=u) + n
timeit("compact_ids(rcap)", loop(f_compact))

# 4. compacted insert (rcap) into the loaded table
def mk_insert(rcap):
    def f_insert(carry_tab):
        def step(i, st):
            tab, acc = st
            h1, h2, valid = cand(i, 4)
            tab, is_new, unres, novf = vs.insert(tab, h1, h2, h1, h2, valid, rcap=rcap)
            return tab, acc ^ is_new.sum(dtype=u) + unres.sum(dtype=u)
        tab, acc = lax.fori_loop(u(0), u(K), step, (carry_tab, u(0)))
        return acc
    return f_insert
timeit(f"insert rcap={RCAP}", mk_insert(RCAP), tuple(jnp.asarray(t) for t in table0))
timeit(f"insert rcap={RCAP2}", mk_insert(RCAP2), tuple(jnp.asarray(t) for t in table0))

# 5. ring gather (7 lanes x C)
QCAP = 1 << 20
ring = tuple(jnp.zeros(QCAP, u) + u(w) for w in range(7))
def f_rgather(i):
    popped, _ = fr.ring_gather(ring, i * u(C) & u(QCAP - 1), C)
    return sum(p.sum(dtype=u) for p in popped)
timeit("ring_gather 7xC", loop(f_rgather))

# 6. ring scatter (7 lanes x CA)
def f_rscatter(carry_ring):
    def step(i, st):
        ring, acc = st
        h1, h2, valid = cand(i, 6)
        cl = tuple(mix(iota_ca, 20 + w) for w in range(7))
        ring = fr.ring_scatter(ring, i * u(977), cl, valid)
        return ring, acc ^ ring[0][0]
    ring2, acc = lax.fori_loop(u(0), u(K), step, (carry_ring, u(0)))
    return acc
timeit("ring_scatter 7xCA", f_rscatter, ring)

# 7. eval_and_expand (real model)
def f_expand(i):
    rows = tuple(mix(iota_c, 30 + s) & u(0x3FFF) for s in range(3))
    ex = eval_and_expand(rows, mix(iota_c, 41), mix(iota_c, 42), iota_c & u(0),
                         iota_c & u(0) + u(1), iota_c < u(C), u(0xFFFFFFFF))
    return ex.h1.sum(dtype=u) + ex.generated
timeit("eval_and_expand", loop(f_expand))
