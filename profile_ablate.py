"""Scratch: in-engine ablation of the 2pc-7 era-step body (round 5).

Monkeypatches pieces of the step out (breaking semantics where needed —
unique counts will be wrong for some configs; only wall time matters) and
times the real engine end-to-end. Each config's loop is cache-keyed by a
fresh model instance so ablations don't reuse stale compiled loops.
"""
import sys
import time

import numpy as np

import stateright_tpu.engines.tpu_bfs as tb
import stateright_tpu.ops.frontier as fr
import stateright_tpu.ops.visited_set as vs
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

MODE = sys.argv[1]

orig_dedup = fr.claim_dedup
orig_insert = vs.insert

if MODE == "full":
    pass
elif MODE == "no_dedup":
    # reps = valid; insert handles in-batch dups (needs wider rcap)
    fr.claim_dedup = lambda h1, h2, valid, cap: valid
    tb._rcap = lambda A, chunk: (chunk * A) // 3
elif MODE == "no_insert":
    # table never probed: is_new = reps & (cheap pseudo-filter keeping ~11%
    # of slots so queue growth roughly matches reality). Run bounded steps.
    def fake_insert(table, h1, h2, p1, p2, active, rcap=None, primary_rounds=2):
        import jax.numpy as jnp
        u = jnp.uint32
        is_new = active & ((h1 & u(7)) == u(0))
        return table, is_new, active & ~active, u(0)
    vs.insert = fake_insert
elif MODE == "no_dedup_no_insert":
    fr.claim_dedup = lambda h1, h2, valid, cap: valid
    def fake_insert(table, h1, h2, p1, p2, active, rcap=None, primary_rounds=2):
        import jax.numpy as jnp
        u = jnp.uint32
        is_new = active & ((h1 & u(7)) == u(0))
        return table, is_new, active & ~active, u(0)
    vs.insert = fake_insert
elif MODE == "insert_no_tail":
    def probe_all_notail(table, claim, h1, h2, p1, p2, stride, idx, done, is_new, rounds):
        table, claim, idx, done, is_new = vs._probe_rounds(
            table, claim, h1, h2, p1, p2, stride, idx, done, is_new, rounds + 4
        )
        return table, claim, done, is_new
    vs._probe_all = probe_all_notail
elif MODE == "no_hash":
    import stateright_tpu.fingerprint as fp_mod
    def cheap_hash(lanes):
        import jax.numpy as jnp
        u = jnp.uint32
        h1 = lanes[0] * u(0x9E3779B9)
        h2 = lanes[0] * u(0x85EBCA6B)
        for l in lanes[1:]:
            h1 = h1 ^ l
            h2 = h2 + l
        return h1 | u(1), h2
    fp_mod.hash_lanes_jnp = cheap_hash
elif MODE == "no_ring_gather":
    # pop reads replaced by a cheap slice at fixed position (breaks BFS
    # order/uniques; timing only)
    def fake_ring_gather(lanes, head, n):
        import jax.numpy as jnp
        idx = jnp.arange(n, dtype=jnp.uint32)
        return tuple(l[idx] for l in lanes), idx
    fr.ring_gather = fake_ring_gather
elif MODE == "no_ring_scatter":
    def fake_ring_scatter(lanes, tail, cand_lanes, valid):
        import jax.numpy as jnp
        n = valid.shape[0]
        return tuple(
            l.at[jnp.uint32(0)].set(c[0]) for l, c in zip(lanes, cand_lanes)
        )
    fr.ring_scatter = fake_ring_scatter
elif MODE in ("fake_expand", "fake_expand_noring"):
    # Entire eval+expand replaced by ~15 BIG ops at C*A width (garbage
    # semantics; bounded by target_state_count). Tests the op-count
    # hypothesis: if the step collapses, the real expand's ~500 small
    # [C] ops are the bottleneck.
    import stateright_tpu.ops.expand as ex_mod

    fr.claim_dedup = lambda h1, h2, valid, cap: valid

    def fake_insert(table, h1, h2, p1, p2, active, rcap=None, primary_rounds=2):
        import jax.numpy as jnp
        u = jnp.uint32
        is_new = active & ((h1 & u(3)) == u(0))
        return table, is_new, active & ~active, u(0)
    vs.insert = fake_insert

    def fake_build(tm, props, chunk):
        import jax.numpy as jnp
        S, A, P = tm.state_width, tm.max_actions, len(props)
        CA = chunk * A

        def f(rows, row_h1, row_h2, ebits, depth, active, depth_limit):
            u = jnp.uint32
            iota = jnp.arange(CA, dtype=u)
            t1 = jnp.tile(row_h1, A)
            k = iota ^ (iota >> u(10)) ^ (iota >> u(5))
            h1 = ((t1 ^ k) * u(0x9E3779B9)) ^ (t1 >> u(13))
            h2 = ((t1 + k) * u(0x85EBCA6B)) | u(1)
            valid = jnp.tile(active, A) & ((h1 & u(3)) < u(3))
            flat = tuple(jnp.tile(rows[s], A) for s in range(S))
            hits = [(row_h1 & u(0)) != u(0) for _ in range(P)]
            return ex_mod.Expanded(
                ebits=ebits,
                flat=flat,
                h1=h1,
                h2=h2,
                parent1=t1,
                parent2=jnp.tile(row_h2, A),
                child_ebits=jnp.tile(ebits, A),
                child_depth=jnp.tile(depth + u(1), A),
                valid=valid,
                generated=valid.sum(dtype=u),
                prop_hits=hits,
            )
        return f
    ex_mod.build_eval_and_expand = fake_build
    tb.build_eval_and_expand = fake_build
    if MODE == "fake_expand_noring":
        def fake_ring_gather(lanes, head, n):
            import jax.numpy as jnp
            idx = jnp.arange(n, dtype=jnp.uint32)
            return tuple(l[idx] for l in lanes), idx
        fr.ring_gather = fake_ring_gather
        orig_scatter = fr.ring_scatter
        def fake_ring_scatter(lanes, tail, cand_lanes, valid):
            import jax.numpy as jnp
            u = jnp.uint32
            n = valid.shape[0]
            cap = lanes[0].shape[0]
            idx = jnp.arange(n, dtype=u) & u(cap - 1)
            return tuple(
                l.at[idx].set(c, mode="drop", unique_indices=True)
                for l, c in zip(lanes, cand_lanes)
            )
        fr.ring_scatter = fake_ring_scatter
else:
    raise SystemExit(f"unknown mode {MODE}")

tm = TwoPhaseTensor(7)
opts = dict(chunk_size=6144, queue_capacity=int(sys.argv[2]) if len(sys.argv)>2 else 1 << 20, table_capacity=int(sys.argv[3]) if len(sys.argv)>3 else 1 << 22)

def run():
    b = TensorModelAdapter(tm).checker()
    if MODE in ("no_insert", "no_dedup_no_insert", "fake_expand", "fake_expand_noring"):
        b = b.target_state_count(2_700_000)
    return b.spawn_tpu_bfs(**opts).join()

t0 = time.perf_counter()
c = run()
print(f"[{MODE}] compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
for _ in range(3):
    t0 = time.perf_counter()
    c = run()
    dt = time.perf_counter() - t0
    tel = c.telemetry()
    print(
        f"[{MODE}] secs={dt:.3f} steps={tel['steps']} ms/step={dt/max(1,tel['steps'])*1000:.1f} "
        f"unique={c.unique_state_count()} gen={c.state_count()}",
        flush=True,
    )
