"""ABD linearizable register: quorum-replicated shared memory.

Implements the algorithm from "Sharing Memory Robustly in Message-Passing
Systems" by Attiya, Bar-Noy, and Dolev: Phase 1 queries a quorum for the
highest (logical-clock, id) sequencer; Phase 2 records the chosen
value/sequencer at a quorum before replying.

Reference parity: examples/linearizable-register.rs. Golden: 544 unique
states with 2 clients and 2 servers on an unordered non-duplicating
network (linearizable-register.rs:287).

Usage::

    python examples/linearizable_register.py check [CLIENT_COUNT] [NETWORK]
    python examples/linearizable_register.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any, FrozenSet, Optional, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from stateright_tpu.actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import Register

Seq = Tuple[int, Id]  # (logical clock, actor id) — globally unique


# -- internal protocol (linearizable-register.rs:28-34) ----------------------

@dataclass(frozen=True)
class Query:
    request_id: int


@dataclass(frozen=True)
class AckQuery:
    request_id: int
    seq: Seq
    value: Any


@dataclass(frozen=True)
class Record:
    request_id: int
    seq: Seq
    value: Any


@dataclass(frozen=True)
class AckRecord:
    request_id: int


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[Any]  # None for reads
    responses: Tuple[Tuple[Id, Tuple[Seq, Any]], ...]


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    # An explicit flag rather than a `read is None` sentinel: an empty
    # register legitimately reads as None, which must still GetOk.
    is_read: bool
    read: Optional[Any]
    acks: FrozenSet[Id]


@dataclass(frozen=True)
class AbdState:
    seq: Seq
    val: Any
    phase: Optional[Any]  # Phase1 | Phase2 | None


class AbdActor(Actor):
    """Reference: AbdActor (linearizable-register.rs:60-210)."""

    def __init__(self, peers):
        self.peers = list(peers)

    def name(self) -> str:
        return "ABD Server"

    def on_start(self, id: Id, out: Out) -> AbdState:
        return AbdState(seq=(0, id), val=None, phase=None)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg: Any, out: Out):
        if isinstance(msg, (Put, Get)) and state.phase is None:
            write = msg.value if isinstance(msg, Put) else None
            out.broadcast(self.peers, Internal(Query(msg.request_id)))
            return replace(
                state,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=write,
                    responses=((id, (state.seq, state.val)),),
                ),
            )

        if isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Query):
                out.send(src, Internal(AckQuery(inner.request_id, state.seq, state.val)))
                return None

            if (
                isinstance(inner, AckQuery)
                and isinstance(state.phase, Phase1)
                and state.phase.request_id == inner.request_id
            ):
                phase = state.phase
                responses = dict(phase.responses)
                responses[src] = (inner.seq, inner.value)
                if len(responses) < majority(len(self.peers) + 1):
                    return replace(
                        state, phase=replace(phase, responses=tuple(sorted(responses.items())))
                    )
                # Quorum reached; move to phase 2. Sequencers are distinct,
                # so max-by-seq is deterministic (linearizable-register.rs:136-140).
                seq, val = max(responses.values(), key=lambda sv: sv[0])
                is_read = phase.write is None
                read = None
                if is_read:
                    read = val
                else:
                    seq = (seq[0] + 1, id)
                    val = phase.write
                out.broadcast(self.peers, Internal(Record(phase.request_id, seq, val)))
                new_seq, new_val = (
                    (seq, val) if seq > state.seq else (state.seq, state.val)
                )  # self-send Record
                return replace(
                    state,
                    seq=new_seq,
                    val=new_val,
                    phase=Phase2(
                        request_id=phase.request_id,
                        requester_id=phase.requester_id,
                        is_read=is_read,
                        read=read,
                        acks=frozenset({id}),  # self-send AckRecord
                    ),
                )

            if isinstance(inner, Record):
                out.send(src, Internal(AckRecord(inner.request_id)))
                if inner.seq > state.seq:
                    return replace(state, seq=inner.seq, val=inner.value)
                return None

            if (
                isinstance(inner, AckRecord)
                and isinstance(state.phase, Phase2)
                and state.phase.request_id == inner.request_id
                and src not in state.phase.acks
            ):
                phase = state.phase
                acks = phase.acks | {src}
                if len(acks) < majority(len(self.peers) + 1):
                    return replace(state, phase=replace(phase, acks=acks))
                if phase.is_read:
                    out.send(phase.requester_id, GetOk(phase.request_id, phase.read))
                else:
                    out.send(phase.requester_id, PutOk(phase.request_id))
                return replace(state, phase=None)

        return None


def abd_model(
    client_count: int, server_count: int = 2, network: Optional[Network] = None
) -> ActorModel:
    """Reference: AbdModelCfg::into_model (linearizable-register.rs:215-255)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model, state) -> bool:
        return any(
            isinstance(env.msg, GetOk) and env.msg.value is not None
            for env in state.network.iter_deliverable()
        )

    return (
        ActorModel(
            cfg=(client_count, server_count),
            init_history=LinearizabilityTester(Register(None)),
        )
        .add_actors(
            AbdActor(model_peers(i, server_count)) for i in range(server_count)
        )
        .add_actors(
            RegisterClient(put_count=1, server_count=server_count)
            for _ in range(client_count)
        )
        .with_init_network(network)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, state: state.history.serialized_history() is not None,
        )
        .property(Expectation.SOMETIMES, "value chosen", value_chosen)
        .with_record_msg_in(record_returns)
        .with_record_msg_out(record_invocations)
    )


ABD_MESSAGE_TYPES = (
    Put, PutOk, Get, GetOk, Internal, Query, AckQuery, Record, AckRecord,
)


def spawn_info(record=None, faults=None, duration=None, engine="auto"):
    """Run a real 2-server ABD cluster over UDP
    (linearizable-register.rs:257-284). `record`/`faults` thread through
    to `spawn` (the CLI's ``--record``/``--faults`` flags); `duration`
    runs in the background for that many seconds instead of blocking."""
    from stateright_tpu.actor import Id
    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )

    port = 3000
    ids = [Id.from_addr("127.0.0.1", port + i) for i in range(2)]
    print("  A set of servers that implement a linearizable register.")
    print("  You can monitor and interact using tcpdump and netcat:")
    print(f"$ nc -u localhost {port}")
    print('["Put", 1, "X"]')
    print('["Get", 2]')
    handle = spawn(
        json_serializer,
        make_json_deserializer(*ABD_MESSAGE_TYPES),
        [
            (ids[i], AbdActor([ids[j] for j in range(2) if j != i]))
            for i in range(2)
        ],
        background=duration is not None,
        engine=engine,
        record=record,
        faults=faults,
    )
    if duration is not None:
        import time

        time.sleep(float(duration))
        handle.shutdown()


def record_abd_demo(
    path: str,
    duration: float = 1.5,
    client_count: int = 1,
    seed: Optional[int] = None,
    engine: str = "auto",
    base_port: int = 46200,
    plan=None,
):
    """End-to-end demo: a 2-server ABD cluster plus register clients on
    loopback UDP, recorded at `path`; a `seed` injects seeded
    drop/duplicate faults — the mix the duplicating model network claims
    to tolerate. Ports ascend with model index (servers first); the
    conformance id mapping relies on that order."""
    import time

    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )
    from stateright_tpu.conformance import FaultPlan

    ids = [
        Id.from_addr("127.0.0.1", base_port + i) for i in range(2 + client_count)
    ]
    server_ids = ids[:2]
    actors = [
        (server_ids[i], AbdActor([server_ids[j] for j in range(2) if j != i]))
        for i in range(2)
    ]
    for k in range(client_count):
        actors.append(
            (
                ids[2 + k],
                RegisterClient(
                    put_count=1, server_count=2,
                    index=2 + k, server_ids=server_ids,
                ),
            )
        )
    if plan is None and seed is not None:
        plan = FaultPlan(seed=seed, drop=0.03, duplicate=0.12)
    handle = spawn(
        json_serializer,
        make_json_deserializer(*ABD_MESSAGE_TYPES),
        actors,
        background=True,
        engine=engine,
        record=path,
        faults=plan,
    )
    time.sleep(duration)
    handle.shutdown()
    return path


def conform_abd_trace(path: str, client_count: Optional[int] = None, metrics=None):
    """Check a recorded ABD trace against `abd_model` (on a duplicating
    network, so injected duplicates are model-explainable) and extract its
    linearizability history. `client_count=None` infers the topology from
    the trace's actor roster. Returns (ConformanceReport, tester)."""
    from stateright_tpu.conformance import (
        check_trace,
        load_trace,
        make_decoder,
        register_history,
    )

    meta, events = load_trace(path)
    if client_count is None:
        roster = meta.get("actors", [])
        servers = sum(1 for a in roster if a.get("actor") == "AbdActor") or 2
        client_count = max(len(roster) - servers, 0)
    model = abd_model(client_count, 2, Network.new_unordered_duplicating())
    report = check_trace(
        model,
        (meta, events),
        decode=make_decoder(*ABD_MESSAGE_TYPES),
        metrics=metrics,
    )
    return report, register_history(events)


def main(argv=None):
    from examples._cli import example_main

    example_main(
        argv,
        name="a linearizable register",
        build_model=lambda client_count, network: abd_model(client_count, 2, network),
        default_client_count=2,
        spawn_info=spawn_info,
        conform_info=lambda path, client_count: conform_abd_trace(
            path, client_count=client_count
        ),
    )


if __name__ == "__main__":
    main()
