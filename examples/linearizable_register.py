"""ABD linearizable register: quorum-replicated shared memory.

Implements the algorithm from "Sharing Memory Robustly in Message-Passing
Systems" by Attiya, Bar-Noy, and Dolev: Phase 1 queries a quorum for the
highest (logical-clock, id) sequencer; Phase 2 records the chosen
value/sequencer at a quorum before replying.

Reference parity: examples/linearizable-register.rs. Golden: 544 unique
states with 2 clients and 2 servers on an unordered non-duplicating
network (linearizable-register.rs:287).

Usage::

    python examples/linearizable_register.py check [CLIENT_COUNT] [NETWORK]
    python examples/linearizable_register.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any, FrozenSet, Optional, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from stateright_tpu.actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import Register

Seq = Tuple[int, Id]  # (logical clock, actor id) — globally unique


# -- internal protocol (linearizable-register.rs:28-34) ----------------------

@dataclass(frozen=True)
class Query:
    request_id: int


@dataclass(frozen=True)
class AckQuery:
    request_id: int
    seq: Seq
    value: Any


@dataclass(frozen=True)
class Record:
    request_id: int
    seq: Seq
    value: Any


@dataclass(frozen=True)
class AckRecord:
    request_id: int


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[Any]  # None for reads
    responses: Tuple[Tuple[Id, Tuple[Seq, Any]], ...]


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    # An explicit flag rather than a `read is None` sentinel: an empty
    # register legitimately reads as None, which must still GetOk.
    is_read: bool
    read: Optional[Any]
    acks: FrozenSet[Id]


@dataclass(frozen=True)
class AbdState:
    seq: Seq
    val: Any
    phase: Optional[Any]  # Phase1 | Phase2 | None


class AbdActor(Actor):
    """Reference: AbdActor (linearizable-register.rs:60-210)."""

    def __init__(self, peers):
        self.peers = list(peers)

    def name(self) -> str:
        return "ABD Server"

    def on_start(self, id: Id, out: Out) -> AbdState:
        return AbdState(seq=(0, id), val=None, phase=None)

    def on_msg(self, id: Id, state: AbdState, src: Id, msg: Any, out: Out):
        if isinstance(msg, (Put, Get)) and state.phase is None:
            write = msg.value if isinstance(msg, Put) else None
            out.broadcast(self.peers, Internal(Query(msg.request_id)))
            return replace(
                state,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=write,
                    responses=((id, (state.seq, state.val)),),
                ),
            )

        if isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Query):
                out.send(src, Internal(AckQuery(inner.request_id, state.seq, state.val)))
                return None

            if (
                isinstance(inner, AckQuery)
                and isinstance(state.phase, Phase1)
                and state.phase.request_id == inner.request_id
            ):
                phase = state.phase
                responses = dict(phase.responses)
                responses[src] = (inner.seq, inner.value)
                if len(responses) < majority(len(self.peers) + 1):
                    return replace(
                        state, phase=replace(phase, responses=tuple(sorted(responses.items())))
                    )
                # Quorum reached; move to phase 2. Sequencers are distinct,
                # so max-by-seq is deterministic (linearizable-register.rs:136-140).
                seq, val = max(responses.values(), key=lambda sv: sv[0])
                is_read = phase.write is None
                read = None
                if is_read:
                    read = val
                else:
                    seq = (seq[0] + 1, id)
                    val = phase.write
                out.broadcast(self.peers, Internal(Record(phase.request_id, seq, val)))
                new_seq, new_val = (
                    (seq, val) if seq > state.seq else (state.seq, state.val)
                )  # self-send Record
                return replace(
                    state,
                    seq=new_seq,
                    val=new_val,
                    phase=Phase2(
                        request_id=phase.request_id,
                        requester_id=phase.requester_id,
                        is_read=is_read,
                        read=read,
                        acks=frozenset({id}),  # self-send AckRecord
                    ),
                )

            if isinstance(inner, Record):
                out.send(src, Internal(AckRecord(inner.request_id)))
                if inner.seq > state.seq:
                    return replace(state, seq=inner.seq, val=inner.value)
                return None

            if (
                isinstance(inner, AckRecord)
                and isinstance(state.phase, Phase2)
                and state.phase.request_id == inner.request_id
                and src not in state.phase.acks
            ):
                phase = state.phase
                acks = phase.acks | {src}
                if len(acks) < majority(len(self.peers) + 1):
                    return replace(state, phase=replace(phase, acks=acks))
                if phase.is_read:
                    out.send(phase.requester_id, GetOk(phase.request_id, phase.read))
                else:
                    out.send(phase.requester_id, PutOk(phase.request_id))
                return replace(state, phase=None)

        return None


def abd_model(
    client_count: int, server_count: int = 2, network: Optional[Network] = None
) -> ActorModel:
    """Reference: AbdModelCfg::into_model (linearizable-register.rs:215-255)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model, state) -> bool:
        return any(
            isinstance(env.msg, GetOk) and env.msg.value is not None
            for env in state.network.iter_deliverable()
        )

    return (
        ActorModel(
            cfg=(client_count, server_count),
            init_history=LinearizabilityTester(Register(None)),
        )
        .add_actors(
            AbdActor(model_peers(i, server_count)) for i in range(server_count)
        )
        .add_actors(
            RegisterClient(put_count=1, server_count=server_count)
            for _ in range(client_count)
        )
        .with_init_network(network)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, state: state.history.serialized_history() is not None,
        )
        .property(Expectation.SOMETIMES, "value chosen", value_chosen)
        .with_record_msg_in(record_returns)
        .with_record_msg_out(record_invocations)
    )


def spawn_info():
    """Run a real 2-server ABD cluster over UDP
    (linearizable-register.rs:257-284)."""
    from stateright_tpu.actor import Id
    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )

    port = 3000
    ids = [Id.from_addr("127.0.0.1", port + i) for i in range(2)]
    print("  A set of servers that implement a linearizable register.")
    print("  You can monitor and interact using tcpdump and netcat:")
    print(f"$ nc -u localhost {port}")
    print('["Put", 1, "X"]')
    print('["Get", 2]')
    spawn(
        json_serializer,
        make_json_deserializer(
            Put, PutOk, Get, GetOk, Internal, Query, AckQuery, Record,
            AckRecord,
        ),
        [
            (ids[i], AbdActor([ids[j] for j in range(2) if j != i]))
            for i in range(2)
        ],
    )


def main(argv=None):
    from examples._cli import example_main

    example_main(
        argv,
        name="a linearizable register",
        build_model=lambda client_count, network: abd_model(client_count, 2, network),
        default_client_count=2,
        spawn_info=spawn_info,
    )


if __name__ == "__main__":
    main()
