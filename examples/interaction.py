"""Modeling external input: a client actor drives a counter service.

Shows how to model user interaction (or any external stimulus) with actors
whose states do not evolve autonomously: timers trigger the client's
increment request and subsequent query, and an `eventually` property checks
the client observes success.

Reference parity: examples/interaction.rs. The reference needs the
`choice!` machinery to mix actor types in one model; Python actor lists are
heterogeneous natively, so `Client` and `Counter` are added directly.

Usage::

    python examples/interaction.py check
    python examples/interaction.py explore [ADDRESS]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation, WriteReporter
from stateright_tpu.actor import Actor, ActorModel, Id, Out, model_timeout


@dataclass(frozen=True)
class IncrementRequest:
    amount: int


@dataclass(frozen=True)
class ReportRequest:
    pass


@dataclass(frozen=True)
class ReplyCount:
    count: int


@dataclass(frozen=True)
class CounterState:
    addr: Id
    counter: int


@dataclass(frozen=True)
class InputState:
    wait_cycles: int  # only for observing system evolution in the explorer
    success: bool


class Counter(Actor):
    """Reference: Counter (interaction.rs:100-133)."""

    def __init__(self, initial_state: CounterState):
        self.initial_state = initial_state

    def name(self) -> str:
        return "Counter"

    def on_start(self, id: Id, out: Out) -> CounterState:
        return self.initial_state

    def on_msg(self, id: Id, state: CounterState, src: Id, msg: Any, out: Out):
        if isinstance(msg, IncrementRequest):
            return replace(state, counter=state.counter + msg.amount)
        if isinstance(msg, ReportRequest):
            out.send(src, ReplyCount(state.counter))
            return None
        return None


class Client(Actor):
    """Reference: Client (interaction.rs:135-203)."""

    def __init__(self, threshold: int, counter_addr: Id):
        self.threshold = threshold
        self.counter_addr = counter_addr

    def name(self) -> str:
        return "Client"

    def on_start(self, id: Id, out: Out) -> InputState:
        out.set_timer("ClientInput", model_timeout())
        return InputState(wait_cycles=0, success=False)

    def on_msg(self, id: Id, state: InputState, src: Id, msg: Any, out: Out):
        if isinstance(msg, ReplyCount) and msg.count >= self.threshold:
            return replace(state, success=True)
        return None

    def on_timeout(self, id: Id, state: InputState, timer: Any, out: Out):
        if timer == "ClientInput":
            # Query only after the increment has been requested.
            out.set_timer("ClientQuery", model_timeout())
            out.send(self.counter_addr, IncrementRequest(3))
            return replace(state, wait_cycles=state.wait_cycles + 1)
        if timer == "ClientQuery":
            out.send(self.counter_addr, ReportRequest())
            return replace(state, wait_cycles=state.wait_cycles + 1)
        return None


def interaction_model() -> ActorModel:
    return (
        ActorModel(init_history=0)
        .actor(Client(threshold=3, counter_addr=Id(1)))
        .actor(Counter(CounterState(addr=Id(1), counter=0)))
        .property(
            Expectation.EVENTUALLY,
            "success",
            lambda model, state: any(
                isinstance(s, InputState) and s.success for s in state.actor_states
            ),
        )
    )


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"
    # target_max_depth bounds the very loosely bounded state space
    # (interaction.rs:43).
    if subcommand == "check":
        checker = (
            interaction_model()
            .checker()
            .target_max_depth(30)
            .spawn_bfs()
            .report(WriteReporter(sys.stdout))
        )
        checker.assert_properties()
    elif subcommand == "explore":
        address = argv[1] if len(argv) > 1 else "localhost:3000"
        interaction_model().checker().target_max_depth(30).serve(address)
    else:
        print("USAGE:")
        print("  python examples/interaction.py check")
        print("  python examples/interaction.py explore [ADDRESS]")


if __name__ == "__main__":
    main()
