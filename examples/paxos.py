"""Single Decree Paxos, model-checked against a linearizability tester.

A cluster of servers that never disagrees on a value: Phase 1 performs
leadership handoff via ballots (`Prepare`/`Prepared`), Phase 2 drives a
proposal to a quorum (`Accept`/`Accepted`/`Decided`). Each client Put starts
a new term.

Reference parity: examples/paxos.rs (actor at paxos.rs:106-254, model at
256-298, CLI at 354-510). Golden: 16,668 unique states with 2 clients and
3 servers on an unordered non-duplicating network (paxos.rs:327).

Usage::

    python examples/paxos.py check [CLIENT_COUNT] [NETWORK]
    python examples/paxos.py check-dfs [CLIENT_COUNT] [NETWORK]
    python examples/paxos.py check-simulation [CLIENT_COUNT] [NETWORK]
    python examples/paxos.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]
    python examples/paxos.py spawn
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any, Dict, FrozenSet, Optional, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from stateright_tpu.actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import Register

Ballot = Tuple[int, Id]  # (round, proposer)
Proposal = Tuple[int, Id, str]  # (request_id, requester, value)


# -- internal protocol messages (paxos.rs:67-89) -----------------------------

@dataclass(frozen=True)
class Prepare:
    ballot: Ballot


@dataclass(frozen=True)
class Prepared:
    ballot: Ballot
    last_accepted: Optional[Tuple[Ballot, Proposal]]


@dataclass(frozen=True)
class Accept:
    ballot: Ballot
    proposal: Proposal


@dataclass(frozen=True)
class Accepted:
    ballot: Ballot


@dataclass(frozen=True)
class Decided:
    ballot: Ballot
    proposal: Proposal


@dataclass(frozen=True)
class PaxosState:
    """Reference: PaxosState (paxos.rs:92-103)."""

    # shared state
    ballot: Ballot
    # leader state
    proposal: Optional[Proposal]
    prepares: Tuple[Tuple[Id, Optional[Tuple[Ballot, Proposal]]], ...]
    accepts: FrozenSet[Id]
    # acceptor state
    accepted: Optional[Tuple[Ballot, Proposal]]
    is_decided: bool


def _accepted_sort_key(entry: Optional[Tuple[Ballot, Proposal]]):
    # None sorts below every accepted proposal (Rust: Option's Ord).
    return (0,) if entry is None else (1, entry)


class PaxosActor(Actor):
    """Reference: PaxosActor (paxos.rs:106-254)."""

    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def name(self) -> str:
        return "Paxos Server"

    def on_start(self, id: Id, out: Out) -> PaxosState:
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(
        self, id: Id, state: PaxosState, src: Id, msg: Any, out: Out
    ) -> Optional[PaxosState]:
        if state.is_decided:
            if isinstance(msg, Get):
                # We can't reply for undecided: a value may have been decided
                # elsewhere with delivery pending (paxos.rs:146-151).
                _ballot, (_req_id, _src, value) = state.accepted
                out.send(src, GetOk(msg.request_id, value))
            return None

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, id)  # simulate Prepare self-send
            out.broadcast(self.peer_ids, Internal(Prepare(ballot)))
            return replace(
                state,
                proposal=(msg.request_id, src, msg.value),
                prepares=((id, state.accepted),),  # simulate Prepared self-send
                accepts=frozenset(),
                ballot=ballot,
            )

        if isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Prepare) and state.ballot < inner.ballot:
                out.send(
                    src, Internal(Prepared(inner.ballot, last_accepted=state.accepted))
                )
                return replace(state, ballot=inner.ballot)

            if isinstance(inner, Prepared) and inner.ballot == state.ballot:
                prepares = dict(state.prepares)
                prepares[src] = inner.last_accepted
                new_state = replace(state, prepares=tuple(sorted(prepares.items())))
                if len(prepares) == majority(len(self.peer_ids) + 1):
                    # Leadership handoff: favor the most recently accepted
                    # proposal from the prepare quorum, else the client's
                    # (paxos.rs:195-216).
                    best = max(prepares.values(), key=_accepted_sort_key)
                    proposal = best[1] if best is not None else state.proposal
                    new_state = replace(
                        new_state,
                        proposal=proposal,
                        accepted=(inner.ballot, proposal),  # Accept self-send
                        accepts=frozenset({id}),  # Accepted self-send
                    )
                    out.broadcast(
                        self.peer_ids, Internal(Accept(inner.ballot, proposal))
                    )
                return new_state

            if isinstance(inner, Accept) and state.ballot <= inner.ballot:
                out.send(src, Internal(Accepted(inner.ballot)))
                return replace(
                    state,
                    ballot=inner.ballot,
                    accepted=(inner.ballot, inner.proposal),
                )

            if isinstance(inner, Accepted) and inner.ballot == state.ballot:
                accepts = state.accepts | {src}
                new_state = replace(state, accepts=accepts)
                if len(accepts) == majority(len(self.peer_ids) + 1):
                    new_state = replace(new_state, is_decided=True)
                    proposal = state.proposal
                    out.broadcast(
                        self.peer_ids, Internal(Decided(inner.ballot, proposal))
                    )
                    request_id, requester_id, _value = proposal
                    out.send(requester_id, PutOk(request_id))
                return new_state

            if isinstance(inner, Decided):
                return replace(
                    state,
                    ballot=inner.ballot,
                    accepted=(inner.ballot, inner.proposal),
                    is_decided=True,
                )

        return None


def paxos_model(
    client_count: int, server_count: int = 3, network: Optional[Network] = None
) -> ActorModel:
    """Reference: PaxosModelCfg::into_model (paxos.rs:256-298)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model, state) -> bool:
        for env in state.network.iter_deliverable():
            if isinstance(env.msg, GetOk) and env.msg.value is not None:
                return True
        return False

    return (
        ActorModel(
            cfg=(client_count, server_count),
            init_history=LinearizabilityTester(Register(None)),
        )
        .add_actors(
            PaxosActor(model_peers(i, server_count)) for i in range(server_count)
        )
        .add_actors(
            RegisterClient(put_count=1, server_count=server_count)
            for _ in range(client_count)
        )
        .with_init_network(network)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, state: state.history.serialized_history() is not None,
        )
        .property(Expectation.SOMETIMES, "value chosen", value_chosen)
        .with_record_msg_in(record_returns)
        .with_record_msg_out(record_invocations)
    )


def spawn_info():
    """Run a real 3-server paxos cluster over UDP (paxos.rs:445-494)."""
    from stateright_tpu.actor import Id
    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )

    port = 3000
    ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
    print("  A set of servers that implement Single Decree Paxos.")
    print("  You can monitor and interact using tcpdump and netcat:")
    print(f"$ nc -u localhost {port}")
    print('["Put", 1, "X"]')
    print('["Get", 2]')
    spawn(
        json_serializer,
        make_json_deserializer(
            Put, PutOk, Get, GetOk, Internal, Prepare, Prepared, Accept,
            Accepted, Decided,
        ),
        [
            (ids[i], PaxosActor([ids[j] for j in range(3) if j != i]))
            for i in range(3)
        ],
    )


def main(argv=None):
    from examples._cli import example_main

    example_main(
        argv,
        name="Single Decree Paxos",
        build_model=lambda client_count, network: paxos_model(
            client_count, 3, network
        ),
        default_client_count=2,
        default_network="unordered_nonduplicating",
        spawn_info=spawn_info,
    )


if __name__ == "__main__":
    main()
