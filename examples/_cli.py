"""Shared CLI plumbing for the example programs.

Role parity with the per-example pico-args CLIs in the reference
(e.g. examples/paxos.rs:354-510): each example exposes `check` /
`check-dfs` / `check-simulation` / `lint` / `explore` / `plan` /
`spawn` subcommands with positional arguments for problem size and
network semantics. `lint` runs the speclint static analysis
(stateright_tpu.analysis) instead of a checking run; its exit status is
nonzero when error-severity diagnostics are found. `plan` predicts a
bundled spec's device footprint (stateright_tpu.obs.memory) without
dispatching anything.
"""

from __future__ import annotations

import inspect
import sys
from typing import Callable, Optional

from stateright_tpu import WriteReporter
from stateright_tpu.actor import Network


def _supported_kwargs(fn: Callable, kwargs: dict) -> dict:
    """Filter kwargs down to those `fn` accepts (older spawn_info hooks
    take no arguments; newer ones take record/faults/duration/engine)."""
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return {}
    if any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values()):
        return kwargs
    return {k: v for k, v in kwargs.items() if k in params}


def _pop_flag(rest: list, flag: str) -> Optional[str]:
    """Remove `--flag VALUE` from an argv slice, returning VALUE (or None)."""
    if flag not in rest:
        return None
    i = rest.index(flag)
    if i + 1 >= len(rest):
        print(f"{flag} requires a value")
        raise SystemExit(1)
    value = rest[i + 1]
    del rest[i : i + 2]
    return value


def print_coverage(checker) -> None:
    """Compact per-action coverage table after a check run (the detailed
    dead-action warning block already rides WriteReporter's summary)."""
    cov = checker.coverage()
    actions = cov.get("actions") or {}
    if not cov.get("enabled") or not actions:
        return
    width = max(len(label) for label in actions)
    print("Action coverage (fire counts):")
    for label, count in actions.items():
        marker = "" if count else "   <- never fired"
        print(f"  {label:<{width}}  {count}{marker}")


def example_main(
    argv,
    name: str,
    build_model: Callable,
    default_client_count: int = 2,
    default_network: str = "unordered_nonduplicating",
    spawn_info: Optional[Callable] = None,
    conform_info: Optional[Callable] = None,
):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"
    rest = argv[1:]

    # Every subcommand honors `--log-level LEVEL` (the structured logger
    # in stateright_tpu/obs/log.py; default $STATERIGHT_LOG or warning).
    log_level = _pop_flag(rest, "--log-level")
    if log_level:
        from stateright_tpu.obs.log import configure

        configure(level=log_level)

    def arg(i, default):
        return rest[i] if len(rest) > i else default

    if subcommand in ("check", "check-bfs", "check-dfs", "check-simulation"):
        client_count = int(arg(0, default_client_count))
        network = Network.from_name(arg(1, default_network))
        print(f"Model checking {name} with {client_count} clients.")
        builder = build_model(client_count, network).checker()
        if subcommand == "check-dfs":
            checker = builder.spawn_dfs()
        elif subcommand == "check-simulation":
            checker = builder.timeout(10.0).spawn_simulation(seed=0)
        else:
            checker = builder.spawn_bfs()
        checker.report(WriteReporter(sys.stdout))
        print_coverage(checker)
    elif subcommand == "lint":
        from stateright_tpu.analysis import analyze

        client_count = int(arg(0, default_client_count))
        network = Network.from_name(arg(1, default_network))
        print(f"Linting {name} with {client_count} clients.")
        report = analyze(build_model(client_count, network))
        print(report.format())
        if not report.ok:
            raise SystemExit(1)
    elif subcommand == "explore":
        trace = _pop_flag(rest, "--trace")
        client_count = int(arg(0, default_client_count))
        address = arg(1, "localhost:3000")
        network = Network.from_name(arg(2, default_network))
        print(
            f"Exploring state space for {name} with {client_count} clients on {address}."
        )
        build_model(client_count, network).checker().serve(
            address, trace=trace
        )
    elif subcommand == "spawn":
        if spawn_info is None:
            print(f"{name} does not support the spawn subcommand.")
            raise SystemExit(1)
        kwargs = {
            "record": _pop_flag(rest, "--record"),
            "faults": _pop_flag(rest, "--faults"),
            "duration": _pop_flag(rest, "--duration"),
            "engine": _pop_flag(rest, "--engine"),
            "base_port": _pop_flag(rest, "--base-port"),
        }
        kwargs = {k: v for k, v in kwargs.items() if v is not None}
        if "duration" in kwargs:
            kwargs["duration"] = float(kwargs["duration"])
        if "base_port" in kwargs:
            kwargs["base_port"] = int(kwargs["base_port"])
        supported = _supported_kwargs(spawn_info, kwargs)
        dropped = sorted(set(kwargs) - set(supported))
        if dropped:
            print(f"{name} spawn ignores flags: {', '.join('--' + f for f in dropped)}")
        spawn_info(**supported)
    elif subcommand == "serve":
        # Start the multi-tenant run server (stateright_tpu.serve): every
        # example exposes the same service; submissions name models by
        # bundled spec ("2pc:3") rather than this example's build_model.
        from stateright_tpu.serve import serve as serve_run_service

        address = arg(0, "localhost:3001")
        print(f"Run service (submit specs like 2pc:3) on {address}.")
        serve_run_service(address)
    elif subcommand == "plan":
        # Capacity planning (stateright_tpu.obs.memory): predict the
        # device footprint of a bundled spec ("2pc:5") at an engine's
        # geometry BEFORE any dispatch. Same registry as `serve`
        # submissions and `python -m stateright_tpu.obs.memory`.
        from stateright_tpu.obs.memory import main as plan_main

        if not rest:
            print(f"Usage: {sys.argv[0]} plan SPEC [--engine E] [--json] ...")
            raise SystemExit(2)
        raise SystemExit(plan_main(rest))
    elif subcommand == "conform":
        if conform_info is None:
            print(f"{name} does not support the conform subcommand.")
            raise SystemExit(1)
        if not rest:
            print(f"Usage: {sys.argv[0]} conform TRACE [CLIENT_COUNT]")
            raise SystemExit(1)
        trace_path = rest[0]
        # None -> the example infers the topology from the trace's roster.
        client_count = int(rest[1]) if len(rest) > 1 else None
        report, tester = conform_info(trace_path, client_count)
        print(report.format(), end="")
        if tester is not None:
            serialized = tester.serialized_history()
            if serialized is None:
                print(f"history: NOT serializable ({len(tester)} ops)")
            else:
                print(f"history: serializable ({len(tester)} ops)")
                for op, ret in serialized:
                    print(f"  {op!r} -> {ret!r}")
        if not report.ok:
            raise SystemExit(1)
    else:
        print(
            f"Usage: {sys.argv[0]} "
            "[check|check-dfs|check-simulation|lint|explore|serve|plan|"
            "spawn|conform]"
        )
        raise SystemExit(1)
