"""Lock-protected-counter CLI (the race in `increment` fixed).

Reference: examples/increment_lock.rs. Both the "fin" and "mutex"
invariants hold.

Usage::

    python examples/increment_lock.py check [THREAD_COUNT]
    python examples/increment_lock.py check-sym [THREAD_COUNT]
    python examples/increment_lock.py check-tpu [THREAD_COUNT]
"""

from __future__ import annotations

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import WriteReporter
from stateright_tpu.models import IncrementLock, IncrementLockTensor


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"
    thread_count = int(argv[1]) if len(argv) > 1 else 3
    print(f"Model checking increment with {thread_count} threads.")
    if subcommand == "check":
        IncrementLock(thread_count).checker().spawn_dfs().report(
            WriteReporter(sys.stdout)
        )
    elif subcommand == "check-sym":
        IncrementLock(thread_count).checker().symmetry().spawn_dfs().report(WriteReporter(sys.stdout))
    elif subcommand == "check-tpu":
        IncrementLockTensor(thread_count).checker().spawn_tpu_bfs().report(
            WriteReporter(sys.stdout)
        )
    else:
        print(
            "USAGE:\n  python examples/increment_lock.py "
            "[check|check-sym|check-tpu] [THREAD_COUNT]"
        )


if __name__ == "__main__":
    main()
