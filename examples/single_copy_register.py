"""A rewritable single-copy register per server — no consensus.

Linearizable with one server; with two or more, a stale read breaks
linearizability and the checker finds the counterexample.

Reference parity: examples/single-copy-register.rs. Goldens: 93 unique
states (2 clients, 1 server, DFS) and 20 states with the linearizability
counterexample (2 clients, 2 servers, BFS).

Usage::

    python examples/single_copy_register.py check [CLIENT_COUNT] [NETWORK]
    python examples/single_copy_register.py explore [CLIENT_COUNT] [ADDRESS] [NETWORK]
    python examples/single_copy_register.py spawn
"""

from __future__ import annotations

import sys
from typing import Any, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.actor.register import (
    Get,
    GetOk,
    Put,
    PutOk,
    RegisterClient,
    record_invocations,
    record_returns,
)
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import Register


class SingleCopyActor(Actor):
    """State is just the stored value. Reference: single-copy-register.rs:18-47."""

    def name(self) -> str:
        return "Server"

    def on_start(self, id: Id, out: Out):
        return None  # empty register

    def on_msg(self, id: Id, state: Any, src: Id, msg: Any, out: Out) -> Optional[Any]:
        if isinstance(msg, Put):
            out.send(src, PutOk(msg.request_id))
            return msg.value
        if isinstance(msg, Get):
            out.send(src, GetOk(msg.request_id, state))
            return None
        return None


def single_copy_model(
    client_count: int, server_count: int = 1, network: Optional[Network] = None
) -> ActorModel:
    if network is None:
        network = Network.new_unordered_nonduplicating()

    def value_chosen(model, state) -> bool:
        return any(
            isinstance(env.msg, GetOk) and env.msg.value is not None
            for env in state.network.iter_deliverable()
        )

    return (
        ActorModel(
            cfg=(client_count, server_count),
            init_history=LinearizabilityTester(Register(None)),
        )
        .add_actors(SingleCopyActor() for _ in range(server_count))
        .add_actors(
            RegisterClient(put_count=1, server_count=server_count)
            for _ in range(client_count)
        )
        .with_init_network(network)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda model, state: state.history.serialized_history() is not None,
        )
        .property(Expectation.SOMETIMES, "value chosen", value_chosen)
        .with_record_msg_in(record_returns)
        .with_record_msg_out(record_invocations)
    )


def spawn_info():
    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )

    port = 3000
    print("  A server that implements a single-copy register.")
    print("  You can monitor and interact using tcpdump and netcat:")
    print(f"$ nc -u localhost {port}")
    print('["Put", 1, "X"]')
    print('["Get", 2]')
    spawn(
        json_serializer,
        make_json_deserializer(Put, Get, PutOk, GetOk),
        [(Id.from_addr("127.0.0.1", port), SingleCopyActor())],
    )


def main(argv=None):
    from examples._cli import example_main

    example_main(
        argv,
        name="a single-copy register",
        build_model=lambda client_count, network: single_copy_model(
            client_count, 1, network
        ),
        default_client_count=2,
        spawn_info=spawn_info,
    )


if __name__ == "__main__":
    main()
