"""Named-timer demo: pingers that fire Even/Odd/NoOp timers.

Reference parity: examples/timers.rs. Each actor sets three named timers on
start; Even/Odd timeouts re-arm themselves and ping even/odd peers; NoOp
only re-arms itself (and is therefore pruned as a no-op by the checker).

Usage::

    python examples/timers.py check [SERVER_COUNT] [NETWORK]
    python examples/timers.py explore [SERVER_COUNT] [ADDRESS] [NETWORK]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    model_peers,
    model_timeout,
)


@dataclass(frozen=True)
class Ping:
    pass


@dataclass(frozen=True)
class Pong:
    pass


@dataclass(frozen=True)
class PingerState:
    sent: int
    received: int


class PingerActor(Actor):
    """Reference: PingerActor (timers.rs:28-98)."""

    TIMERS = ("Even", "Odd", "NoOp")

    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def on_start(self, id: Id, out: Out) -> PingerState:
        for timer in self.TIMERS:
            out.set_timer(timer, model_timeout())
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state: PingerState, src: Id, msg: Any, out: Out):
        if isinstance(msg, Ping):
            out.send(src, Pong())
            return None
        if isinstance(msg, Pong):
            return replace(state, received=state.received + 1)
        return None

    def on_timeout(self, id: Id, state: PingerState, timer: Any, out: Out):
        out.set_timer(timer, model_timeout())
        if timer == "NoOp":
            return None
        parity = 0 if timer == "Even" else 1
        sent = state.sent
        for dst in self.peer_ids:
            if int(dst) % 2 == parity:
                sent += 1
                out.send(dst, Ping())
        return replace(state, sent=sent) if sent != state.sent else None


def timers_model(server_count: int, network: Optional[Network] = None) -> ActorModel:
    if network is None:
        network = Network.new_unordered_duplicating()
    return (
        ActorModel()
        .add_actors(
            PingerActor(model_peers(i, server_count)) for i in range(server_count)
        )
        .with_init_network(network)
        .with_within_boundary(
            lambda cfg, state: all(
                s.sent <= 2 and s.received <= 2 for s in state.actor_states
            )
        )
        .property(Expectation.ALWAYS, "true", lambda m, s: True)
    )


def main(argv=None):
    from examples._cli import example_main

    example_main(
        argv,
        name="timers",
        build_model=lambda count, network: timers_model(count, network),
        default_client_count=2,
        default_network="unordered_duplicating",
    )


if __name__ == "__main__":
    main()
