"""Named-timer demo: pingers that fire Even/Odd/NoOp timers.

Reference parity: examples/timers.rs. Each actor sets three named timers on
start; Even/Odd timeouts re-arm themselves and ping even/odd peers; NoOp
only re-arms itself (and is therefore pruned as a no-op by the checker).

Usage::

    python examples/timers.py check [SERVER_COUNT] [NETWORK]
    python examples/timers.py explore [SERVER_COUNT] [ADDRESS] [NETWORK]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation
from stateright_tpu.actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    model_peers,
    model_timeout,
)


@dataclass(frozen=True)
class Ping:
    pass


@dataclass(frozen=True)
class Pong:
    pass


@dataclass(frozen=True)
class PingerState:
    sent: int
    received: int


class PingerActor(Actor):
    """Reference: PingerActor (timers.rs:28-98)."""

    TIMERS = ("Even", "Odd", "NoOp")

    def __init__(self, peer_ids, timeout_range=None):
        # A real duration range keeps spawned actors from starving the
        # datagram loop with zero-delay model timers.
        self.peer_ids = list(peer_ids)
        self.timeout_range = (
            timeout_range if timeout_range is not None else model_timeout()
        )

    def on_start(self, id: Id, out: Out) -> PingerState:
        for timer in self.TIMERS:
            out.set_timer(timer, self.timeout_range)
        return PingerState(sent=0, received=0)

    def on_msg(self, id: Id, state: PingerState, src: Id, msg: Any, out: Out):
        if isinstance(msg, Ping):
            out.send(src, Pong())
            return None
        if isinstance(msg, Pong):
            return replace(state, received=state.received + 1)
        return None

    def on_timeout(self, id: Id, state: PingerState, timer: Any, out: Out):
        out.set_timer(timer, self.timeout_range)
        if timer == "NoOp":
            return None
        parity = 0 if timer == "Even" else 1
        sent = state.sent
        for dst in self.peer_ids:
            if int(dst) % 2 == parity:
                sent += 1
                out.send(dst, Ping())
        return replace(state, sent=sent) if sent != state.sent else None


def timers_model(server_count: int, network: Optional[Network] = None) -> ActorModel:
    if network is None:
        network = Network.new_unordered_duplicating()
    return (
        ActorModel()
        .add_actors(
            PingerActor(model_peers(i, server_count)) for i in range(server_count)
        )
        .with_init_network(network)
        .with_within_boundary(
            lambda cfg, state: all(
                s.sent <= 2 and s.received <= 2 for s in state.actor_states
            )
        )
        .property(Expectation.ALWAYS, "true", lambda m, s: True)
    )


def record_timers_demo(
    path: str,
    server_count: int = 2,
    duration: float = 0.4,
    engine: str = "auto",
    base_port: int = 46400,
):
    """Run the pingers on loopback UDP, recording a conformance trace.
    `base_port` must be even: the actors pick peers by id parity, so the
    port parity must match the dense model-index parity. No faults here —
    the trace conforms against an Ordered model network, matching the
    per-socket-pair FIFO that loopback UDP actually provides."""
    import time

    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )

    if base_port % 2 != 0:
        raise ValueError(
            f"base_port must be even, got {base_port}: pingers pick peers by "
            "id parity, so each actor's port parity must equal its model-index "
            "parity — an odd base shifts every actor onto the wrong side and "
            "the deployment silently misbehaves"
        )
    ids = [Id.from_addr("127.0.0.1", base_port + i) for i in range(server_count)]
    actors = [
        (
            ids[i],
            PingerActor(
                [ids[j] for j in range(server_count) if j != i],
                timeout_range=(0.02, 0.05),
            ),
        )
        for i in range(server_count)
    ]
    handle = spawn(
        json_serializer,
        make_json_deserializer(Ping, Pong),
        actors,
        background=True,
        engine=engine,
        record=path,
    )
    time.sleep(duration)
    handle.shutdown()
    return path


def conform_timers_trace(path: str, server_count=None, metrics=None):
    """Check a recorded timers trace against `timers_model` on an Ordered
    network (`server_count=None` infers it from the trace's roster).
    Returns (ConformanceReport, None) — no client history here."""
    from stateright_tpu.conformance import check_trace, load_trace, make_decoder

    meta, events = load_trace(path)
    if server_count is None:
        server_count = len(meta.get("actors", [])) or 2
    model = timers_model(server_count, Network.new_ordered())
    report = check_trace(
        model, (meta, events), decode=make_decoder(Ping, Pong), metrics=metrics
    )
    return report, None


def spawn_info(record=None, duration=None, engine="auto", base_port=None):
    """`spawn [--record TRACE] [--duration SECS] [--engine E]
    [--base-port PORT]` (PORT must be even — see `record_timers_demo`)."""
    record_timers_demo(
        record or "/tmp/timers_trace.jsonl",
        duration=duration if duration is not None else 0.4,
        engine=engine,
        **({} if base_port is None else {"base_port": int(base_port)}),
    )
    print(f"Recorded {record or '/tmp/timers_trace.jsonl'}")


def main(argv=None):
    from examples._cli import example_main

    example_main(
        argv,
        name="timers",
        build_model=lambda count, network: timers_model(count, network),
        default_client_count=2,
        default_network="unordered_duplicating",
        spawn_info=spawn_info,
        conform_info=lambda path, count: conform_timers_trace(
            path, server_count=count
        ),
    )


if __name__ == "__main__":
    main()
