"""Two-phase commit CLI. Reference: examples/2pc.rs:231-252.

The model itself lives in `stateright_tpu.models.two_phase_commit` (it
doubles as an engine benchmark). Goldens: 288 states at 3 RMs; 8,832 at
5 RMs; 665 at 5 RMs with symmetry reduction.

Usage::

    python examples/two_phase_commit.py check [RM_COUNT]
    python examples/two_phase_commit.py check-sym [RM_COUNT]
    python examples/two_phase_commit.py check-tpu [RM_COUNT]
    python examples/two_phase_commit.py lint [RM_COUNT]
    python examples/two_phase_commit.py explore [RM_COUNT] [ADDRESS]
"""

from __future__ import annotations


import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import WriteReporter
from stateright_tpu.models import TwoPhaseSys, TwoPhaseTensor


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"

    from examples._cli import _pop_flag, print_coverage

    # Durability flags for check-tpu: --checkpoint writes crash-safe
    # checkpoints (periodically with --checkpoint-every SECONDS, and
    # always at run end / SIGTERM); --resume continues a killed run.
    ckpt = _pop_flag(argv, "--checkpoint")
    ckpt_every = _pop_flag(argv, "--checkpoint-every")
    resume = _pop_flag(argv, "--resume")

    if subcommand == "plan":
        # Capacity planning (stateright_tpu.obs.memory): predict the
        # device footprint of a spec at an engine's geometry BEFORE any
        # dispatch. Defaults to this example's own model.
        from stateright_tpu.obs.memory import main as plan_main

        rest = argv[1:] or ["2pc:3"]
        raise SystemExit(plan_main(rest))

    def arg(i, default):
        return argv[1 + i] if len(argv) > 1 + i else default

    rm_count = int(arg(0, 3))

    if subcommand == "check":
        print(f"Model checking two phase commit with {rm_count} resource managers.")
        checker = TwoPhaseSys(rm_count).checker().spawn_bfs().report(
            WriteReporter(sys.stdout)
        )
        print_coverage(checker)
    elif subcommand == "check-sym":
        print(
            f"Model checking two phase commit with {rm_count} resource managers "
            "using symmetry reduction."
        )
        TwoPhaseSys(rm_count).checker().symmetry().spawn_dfs().report(
            WriteReporter(sys.stdout)
        )
    elif subcommand == "check-tpu":
        print(
            f"Model checking two phase commit with {rm_count} resource managers "
            "on the batched TPU engine."
        )
        kw = {}
        if ckpt is not None:
            kw["checkpoint_path"] = ckpt
        if ckpt_every is not None:
            kw["checkpoint_every"] = float(ckpt_every)
        if resume is not None:
            kw["resume_from"] = resume
        checker = (
            TwoPhaseTensor(rm_count)
            .checker()
            .spawn_tpu_bfs(**kw)
            .report(WriteReporter(sys.stdout))
        )
        print_coverage(checker)
    elif subcommand == "lint":
        from stateright_tpu.analysis import analyze

        print(f"Linting two phase commit with {rm_count} resource managers.")
        ok = True
        for model in (TwoPhaseSys(rm_count), TwoPhaseTensor(rm_count)):
            report = analyze(model)
            print(report.format())
            ok = ok and report.ok
        if not ok:
            raise SystemExit(1)
    elif subcommand == "explore":
        address = arg(1, "localhost:3000")
        print(
            f"Exploring state space for two phase commit with {rm_count} "
            f"resource managers on {address}."
        )
        TwoPhaseSys(rm_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  python examples/two_phase_commit.py check [RM_COUNT]")
        print("  python examples/two_phase_commit.py check-sym [RM_COUNT]")
        print(
            "  python examples/two_phase_commit.py check-tpu [RM_COUNT]"
            " [--checkpoint PATH] [--checkpoint-every SECS] [--resume PATH]"
        )
        print("  python examples/two_phase_commit.py lint [RM_COUNT]")
        print("  python examples/two_phase_commit.py explore [RM_COUNT] [ADDRESS]")
        print(
            "  python examples/two_phase_commit.py plan [SPEC]"
            " [--engine E] [--limit-bytes N] [--json]"
        )


if __name__ == "__main__":
    main()
