"""Unsynchronized-counter CLI (the lost-update race demo) — plus its
message-passing twin, a replicated counter actor system that runs for
real over UDP with trace recording and conformance checking.

Reference: examples/increment.rs. The checker surfaces the race as a "fin"
always-property counterexample; `check-sym` demonstrates symmetry reduction
(13 → 8 unique states at 2 threads).

The actor section (CounterActor/BumpClient) is the conformance smoke
system (scripts/ci.sh): clients bump a session-caching idempotent counter
server with per-client request ids and a retry timer, so the system stays
correct — and its recorded traces stay model-explainable — under injected
drop/duplicate/delay faults.

Usage::

    python examples/increment.py check [THREAD_COUNT]
    python examples/increment.py check-sym [THREAD_COUNT]
    python examples/increment.py check-tpu [THREAD_COUNT]
    python examples/increment.py lint [THREAD_COUNT]
    python examples/increment.py spawn-record [TRACE] [SECONDS] [SEED]
    python examples/increment.py conform TRACE [CLIENT_COUNT]
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass
from typing import Any, Optional, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation, WriteReporter
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out
from stateright_tpu.models import Increment, IncrementTensor
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.spec import SequentialSpec


# ---------------------------------------------------------------------------
# The replicated-counter actor system (the conformance demo).
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Bump:
    """Client -> server: increment, tagged with the client's request id."""

    request_id: int


@dataclass(frozen=True)
class BumpOk:
    """Server -> client: the counter value this bump produced."""

    request_id: int
    value: int


@dataclass(frozen=True)
class CounterState:
    value: int
    # (client id, last request id, value replied) per client, sorted by
    # client — the session cache that makes duplicate Bumps idempotent.
    sessions: Tuple[Tuple[int, int, int], ...]


@dataclass(frozen=True)
class BumpClientState:
    awaiting: Optional[int]
    done: int


class CounterActor(Actor):
    """A single counter server. Duplicated/retransmitted Bumps re-reply the
    cached BumpOk instead of double-counting (exactly-once effect over an
    at-least-once network)."""

    def name(self) -> str:
        return "Counter"

    def on_start(self, id: Id, out: Out) -> CounterState:
        return CounterState(value=0, sessions=())

    def on_msg(self, id: Id, state: CounterState, src: Id, msg: Any, out: Out):
        if not isinstance(msg, Bump):
            return None
        client = int(src)
        cached = {c: (rid, value) for c, rid, value in state.sessions}
        if client in cached:
            rid, value = cached[client]
            if msg.request_id == rid:
                out.send(src, BumpOk(rid, value))  # duplicate: re-reply
                return None
            if msg.request_id < rid:
                return None  # stale retransmit: drop
        new_value = state.value + 1
        cached[client] = (msg.request_id, new_value)
        out.send(src, BumpOk(msg.request_id, new_value))
        return CounterState(
            value=new_value,
            sessions=tuple(sorted((c, r, v) for c, (r, v) in cached.items())),
        )


class BumpClient(Actor):
    """Bumps the counter forever: request ids 1, 2, 3, ... with a retry
    timer re-sending the in-flight Bump (at-least-once delivery).

    `max_ops` bounds the run: after that many completed bumps the client
    goes quiet (no further sends; the retry timer keeps re-arming but has
    nothing to resend), which makes a recorded run's logical event
    sequence finite and — under a duplicate/delay-only plan —
    deterministic across engines (tests/test_netobs.py relies on this)."""

    RETRY = "retry"

    def __init__(
        self,
        server_id,
        retry_range: Optional[Tuple[float, float]] = None,
        max_ops: Optional[int] = None,
    ):
        from stateright_tpu.actor import model_timeout

        self.server_id = Id(server_id)
        self.retry_range = retry_range if retry_range is not None else model_timeout()
        self.max_ops = max_ops

    def name(self) -> str:
        return "BumpClient"

    def on_start(self, id: Id, out: Out) -> BumpClientState:
        out.set_timer(self.RETRY, self.retry_range)
        out.send(self.server_id, Bump(1))
        return BumpClientState(awaiting=1, done=0)

    def on_msg(self, id: Id, state: BumpClientState, src: Id, msg: Any, out: Out):
        if (
            isinstance(msg, BumpOk)
            and state.awaiting is not None
            and msg.request_id == state.awaiting
        ):
            done = state.done + 1
            if self.max_ops is not None and done >= self.max_ops:
                return BumpClientState(awaiting=None, done=done)
            nxt = state.awaiting + 1
            out.send(self.server_id, Bump(nxt))
            return BumpClientState(awaiting=nxt, done=done)
        return None  # stale/duplicate BumpOk

    def on_timeout(self, id: Id, state: BumpClientState, timer: Any, out: Out):
        out.set_timer(self.RETRY, self.retry_range)
        if state.awaiting is not None:
            out.send(self.server_id, Bump(state.awaiting))
        return None


# -- sequential spec + model -------------------------------------------------

@dataclass(frozen=True)
class Inc:
    pass


@dataclass(frozen=True)
class IncOk:
    value: int


class CounterSpec(SequentialSpec):
    """Sequential counter: each Inc returns the post-increment value."""

    def __init__(self, value: int = 0):
        self.value = value

    def copy(self) -> "CounterSpec":
        return CounterSpec(self.value)

    def invoke(self, op):
        assert isinstance(op, Inc), op
        self.value += 1
        return IncOk(self.value)

    def __eq__(self, other):
        return isinstance(other, CounterSpec) and self.value == other.value

    def __hash__(self):
        return hash(("CounterSpec", self.value))

    def __repr__(self):
        return f"CounterSpec({self.value})"


def counter_model(client_count: int, network: Optional[Network] = None) -> ActorModel:
    """Actor 0 is the counter server; actors 1..client_count its clients."""
    if network is None:
        network = Network.new_unordered_duplicating()

    def consistent(model, state) -> bool:
        server = state.actor_states[0]
        # Each client's request ids are 1..rid, each bumping once: the
        # counter must equal the sum of the per-session high-water marks.
        return server.value == sum(rid for _c, rid, _v in server.sessions)

    return (
        ActorModel(cfg=client_count)
        .actor(CounterActor())
        .add_actors(BumpClient(Id(0)) for _ in range(client_count))
        .with_init_network(network)
        .with_within_boundary(
            lambda cfg, state: all(
                s.done <= 2
                for s in state.actor_states
                if isinstance(s, BumpClientState)
            )
        )
        .property(Expectation.ALWAYS, "counter consistent", consistent)
        .property(
            Expectation.SOMETIMES,
            "op completed",
            lambda model, state: any(
                s.done >= 1
                for s in state.actor_states
                if isinstance(s, BumpClientState)
            ),
        )
    )


# -- record -> conform demo path ---------------------------------------------

def counter_history(events, tester=None) -> LinearizabilityTester:
    """Extract the clients' Inc operations from a recorded trace."""
    from stateright_tpu.conformance import extract_history

    if tester is None:
        tester = LinearizabilityTester(CounterSpec(0))

    def invoke_of(actor, msg):
        if isinstance(msg, list) and len(msg) == 2 and msg[0] == "Bump":
            return (msg[1], Inc())
        return None

    def return_of(actor, msg):
        if isinstance(msg, list) and len(msg) == 3 and msg[0] == "BumpOk":
            return (msg[1], IncOk(msg[2]))
        return None

    return extract_history(events, tester, invoke_of, return_of)


def record_counter_demo(
    path: str,
    duration: float = 1.0,
    client_count: int = 2,
    seed: Optional[int] = None,
    engine: str = "auto",
    base_port: int = 46000,
    plan=None,
    max_ops: Optional[int] = None,
    netobs=None,
    retry_range: Optional[Tuple[float, float]] = None,
):
    """Run the counter system on loopback UDP for `duration` seconds,
    recording a conformance trace at `path`; a `seed` injects a default
    drop/duplicate/delay fault mix. Ports ascend with model index (the
    conformance id mapping relies on that order).

    With `max_ops` each client stops after that many completed bumps and
    `duration` becomes a timeout cap: the run ends as soon as every
    client is done. `netobs` is forwarded to `spawn` (live deployment
    metrics); `retry_range` overrides the clients' retry timer."""
    from stateright_tpu.actor.spawn import (
        json_serializer,
        make_json_deserializer,
        spawn,
    )
    from stateright_tpu.conformance import FaultPlan

    if retry_range is None:
        retry_range = (0.05, 0.1)
    ids = [Id.from_addr("127.0.0.1", base_port + i) for i in range(1 + client_count)]
    actors = [(ids[0], CounterActor())]
    for k in range(client_count):
        actors.append(
            (ids[1 + k], BumpClient(ids[0], retry_range=retry_range, max_ops=max_ops))
        )
    if plan is None and seed is not None:
        plan = FaultPlan(
            seed=seed, drop=0.05, duplicate=0.1, delay=0.05,
            delay_range=(0.002, 0.02),
        )
    handle = spawn(
        json_serializer,
        make_json_deserializer(Bump, BumpOk),
        actors,
        background=True,
        engine=engine,
        record=path,
        faults=plan,
        netobs=netobs,
    )
    if max_ops is None:
        time.sleep(duration)
    else:
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            if all(
                getattr(handle.state(id), "done", 0) >= max_ops
                for id in ids[1:]
            ):
                break
            time.sleep(0.01)
        # Let straggler duplicates/delays land so the trace is complete.
        time.sleep(0.15)
    handle.shutdown()
    return path


def conform_counter_trace(
    path: str, client_count: Optional[int] = None, metrics=None
):
    """Check a recorded counter trace against `counter_model` and extract
    its linearizability history. `client_count=None` infers it from the
    trace's actor roster. Returns (ConformanceReport, tester)."""
    from stateright_tpu.conformance import check_trace, load_trace, make_decoder

    meta, events = load_trace(path)
    if client_count is None:
        client_count = max(len(meta.get("actors", [])) - 1, 1)
    model = counter_model(client_count, Network.new_unordered_duplicating())
    report = check_trace(
        model, (meta, events), decode=make_decoder(Bump, BumpOk), metrics=metrics
    )
    return report, counter_history(events)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"
    from examples._cli import _pop_flag

    # Durability flags for check-tpu: --checkpoint writes crash-safe
    # checkpoints (periodically with --checkpoint-every SECONDS, and
    # always at run end / SIGTERM); --resume continues a killed run.
    ckpt = _pop_flag(argv, "--checkpoint")
    ckpt_every = _pop_flag(argv, "--checkpoint-every")
    resume = _pop_flag(argv, "--resume")
    if subcommand == "plan":
        # Capacity planning (stateright_tpu.obs.memory): predict the
        # device footprint of a spec BEFORE any dispatch.
        from stateright_tpu.obs.memory import main as plan_main

        raise SystemExit(plan_main(argv[1:] or ["increment:2"]))

    thread_count = 2
    if subcommand not in ("spawn-record", "conform") and len(argv) > 1:
        thread_count = int(argv[1])
    if subcommand not in ("spawn-record", "conform"):
        print(f"Model checking increment with {thread_count} threads.")
    from examples._cli import print_coverage

    if subcommand == "check":
        checker = Increment(thread_count).checker().spawn_dfs().report(
            WriteReporter(sys.stdout)
        )
        print_coverage(checker)
    elif subcommand == "check-sym":
        Increment(thread_count).checker().symmetry().spawn_dfs().report(
            WriteReporter(sys.stdout)
        )
    elif subcommand == "check-tpu":
        kw = {}
        if ckpt is not None:
            kw["checkpoint_path"] = ckpt
        if ckpt_every is not None:
            kw["checkpoint_every"] = float(ckpt_every)
        if resume is not None:
            kw["resume_from"] = resume
        checker = (
            IncrementTensor(thread_count)
            .checker()
            .spawn_tpu_bfs(**kw)
            .report(WriteReporter(sys.stdout))
        )
        print_coverage(checker)
    elif subcommand == "lint":
        from stateright_tpu.analysis import analyze

        ok = True
        for model in (
            Increment(thread_count),
            IncrementTensor(thread_count),
            counter_model(thread_count),
        ):
            report = analyze(model)
            print(report.format())
            ok = ok and report.ok
        if not ok:
            raise SystemExit(1)
    elif subcommand == "check-actor":
        checker = counter_model(thread_count).checker().spawn_bfs().report(
            WriteReporter(sys.stdout)
        )
        print_coverage(checker)
    elif subcommand == "spawn-record":
        trace = argv[1] if len(argv) > 1 else "/tmp/counter_trace.jsonl"
        duration = float(argv[2]) if len(argv) > 2 else 1.0
        seed = int(argv[3]) if len(argv) > 3 else 7
        print(
            f"Running the counter system on loopback for {duration}s "
            f"(fault seed {seed}); recording {trace}."
        )
        record_counter_demo(trace, duration=duration, seed=seed)
        print(f"Recorded. Now try: python examples/increment.py conform {trace}")
    elif subcommand == "conform":
        if len(argv) < 2:
            print("Usage: python examples/increment.py conform TRACE [CLIENT_COUNT]")
            raise SystemExit(1)
        client_count = int(argv[2]) if len(argv) > 2 else None
        report, tester = conform_counter_trace(argv[1], client_count=client_count)
        print(report.format(), end="")
        serialized = tester.serialized_history()
        verdict = "serializable" if serialized is not None else "NOT serializable"
        print(f"history: {verdict} ({len(tester)} ops)")
        if not report.ok:
            raise SystemExit(1)
    else:
        print("USAGE:")
        print(
            "  python examples/increment.py "
            "[check|check-sym|check-tpu|check-actor|lint] [THREAD_COUNT]"
        )
        print(
            "  python examples/increment.py check-tpu [THREAD_COUNT]"
            " [--checkpoint PATH] [--checkpoint-every SECS] [--resume PATH]"
        )
        print("  python examples/increment.py spawn-record [TRACE] [SECONDS] [SEED]")
        print("  python examples/increment.py conform TRACE [CLIENT_COUNT]")
        print(
            "  python examples/increment.py plan [SPEC]"
            " [--engine E] [--limit-bytes N] [--json]"
        )


if __name__ == "__main__":
    main()
