"""Unsynchronized-counter CLI (the lost-update race demo).

Reference: examples/increment.rs. The checker surfaces the race as a "fin"
always-property counterexample; `check-sym` demonstrates symmetry reduction
(13 → 8 unique states at 2 threads).

Usage::

    python examples/increment.py check [THREAD_COUNT]
    python examples/increment.py check-sym [THREAD_COUNT]
    python examples/increment.py check-tpu [THREAD_COUNT]
    python examples/increment.py lint [THREAD_COUNT]
"""

from __future__ import annotations

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import WriteReporter
from stateright_tpu.models import Increment, IncrementTensor


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"
    thread_count = int(argv[1]) if len(argv) > 1 else 2
    print(f"Model checking increment with {thread_count} threads.")
    from examples._cli import print_coverage

    if subcommand == "check":
        checker = Increment(thread_count).checker().spawn_dfs().report(
            WriteReporter(sys.stdout)
        )
        print_coverage(checker)
    elif subcommand == "check-sym":
        Increment(thread_count).checker().symmetry().spawn_dfs().report(
            WriteReporter(sys.stdout)
        )
    elif subcommand == "check-tpu":
        checker = IncrementTensor(thread_count).checker().spawn_tpu_bfs().report(
            WriteReporter(sys.stdout)
        )
        print_coverage(checker)
    elif subcommand == "lint":
        from stateright_tpu.analysis import analyze

        ok = True
        for model in (Increment(thread_count), IncrementTensor(thread_count)):
            report = analyze(model)
            print(report.format())
            ok = ok and report.ok
        if not ok:
            raise SystemExit(1)
    else:
        print("USAGE:")
        print(
            "  python examples/increment.py "
            "[check|check-sym|check-tpu|lint] [THREAD_COUNT]"
        )


if __name__ == "__main__":
    main()
