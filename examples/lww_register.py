"""Last-write-wins register: a state-based CRDT with modeled clock drift.

Each actor nondeterministically (via `choose_random`) sets a value or
drifts its local clock, broadcasting its register state; receivers merge by
(timestamp, updater_id). The "eventually consistent" property asserts that
whenever the network is empty, all replicas agree — a CRDT-style quiescent
consistency, deliberately expressed as an `always` over quiescent states
rather than an `eventually` (lww-register.rs:163-181).

Reference parity: examples/lww-register.rs.

Usage::

    python examples/lww_register.py check [CLIENT_COUNT] [DEPTH]
    python examples/lww_register.py explore [CLIENT_COUNT] [ADDRESS]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, replace
from typing import Any, Optional

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from stateright_tpu import Expectation, WriteReporter
from stateright_tpu.actor import Actor, ActorModel, Id, Network, Out


@dataclass(frozen=True)
class LwwRegister:
    """Reference: LwwRegister (lww-register.rs:14-34)."""

    value: str
    timestamp: int
    updater_id: int

    @staticmethod
    def merge(a: "LwwRegister", b: "LwwRegister") -> "LwwRegister":
        return a if (a.timestamp, a.updater_id) > (b.timestamp, b.updater_id) else b


@dataclass(frozen=True)
class SetValue:
    value: str


@dataclass(frozen=True)
class SetTime:
    time: int


@dataclass(frozen=True)
class LwwActorState:
    register: Optional[LwwRegister]
    local_clock: int
    maximum_used_clock: int


class LwwActor(Actor):
    """Reference: LwwActor (lww-register.rs:65-146)."""

    VALUES = ("A", "B", "C")

    def __init__(self, peers):
        self.peers = list(peers)

    def name(self) -> str:
        return "LWW"

    def _populate_choices(self, out: Out, time: int) -> None:
        out.choose_random(
            "node_action",
            [SetValue(v) for v in self.VALUES]
            + [SetTime(time + 1), SetTime(max(0, time - 1))],
        )

    def on_start(self, id: Id, out: Out) -> LwwActorState:
        state = LwwActorState(register=None, local_clock=1000, maximum_used_clock=1000)
        self._populate_choices(out, state.local_clock)
        return state

    def on_random(self, id: Id, state: LwwActorState, random: Any, out: Out):
        if isinstance(random, SetValue):
            if state.register is not None:
                # Ensure the clock value is unique per node.
                clock_value = max(state.local_clock, state.maximum_used_clock + 1)
                register = LwwRegister(random.value, clock_value, int(id))
                new_state = replace(
                    state, register=register, maximum_used_clock=clock_value
                )
            else:
                register = LwwRegister(random.value, state.local_clock, int(id))
                new_state = replace(state, register=register)
            out.broadcast(self.peers, register)
            self._populate_choices(out, new_state.local_clock)
            return new_state
        if isinstance(random, SetTime):
            new_state = replace(state, local_clock=random.time)
            self._populate_choices(out, new_state.local_clock)
            return new_state
        return None

    def on_msg(self, id: Id, state: LwwActorState, src: Id, msg: Any, out: Out):
        if state.register is not None:
            return replace(state, register=LwwRegister.merge(state.register, msg))
        return replace(state, register=msg)


def lww_model(actor_count: int) -> ActorModel:
    """Reference: build_checker (lww-register.rs:148-183)."""
    peers = [Id(i) for i in range(actor_count)]

    def eventually_consistent(model, state) -> bool:
        # CRDT eventual consistency: replicas agree whenever no messages are
        # in flight. Transient agreement before quiescence doesn't count.
        if len(state.network) == 0:
            registers = [s.register for s in state.actor_states]
            return all(r == registers[0] for r in registers)
        return True

    model = ActorModel()
    for _ in range(actor_count):
        model.actor(LwwActor(peers))
    return model.with_init_network(
        Network.new_unordered_nonduplicating()
    ).property(Expectation.ALWAYS, "eventually consistent", eventually_consistent)


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    subcommand = argv[0] if argv else "check"

    def arg(i, default):
        return argv[1 + i] if len(argv) > 1 + i else default

    if subcommand == "check":
        actor_count = int(arg(0, 2))
        depth = int(arg(1, 8))
        (
            lww_model(actor_count)
            .checker()
            .target_max_depth(depth)
            .spawn_dfs()
            .join_and_report(WriteReporter(sys.stdout))
        )
    elif subcommand == "explore":
        actor_count = int(arg(0, 2))
        address = arg(1, "localhost:3000")
        print(
            f"Exploring state space for last-writer-wins register with "
            f"{actor_count} clients on {address}."
        )
        lww_model(actor_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  python examples/lww_register.py check [CLIENT_COUNT] [DEPTH]")
        print("  python examples/lww_register.py explore [CLIENT_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
