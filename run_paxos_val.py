import sys
import time

from examples.paxos import paxos_model
from stateright_tpu import TensorModelAdapter
from stateright_tpu.models.paxos import PaxosTensorExhaustive

if __name__ == "__main__":
    which = sys.argv[1]
    if which == "rich2":
        t0 = time.perf_counter()
        c = paxos_model(2).checker().threads(8).spawn_bfs().join()
        print(f"paxos-2 rich pbfs: unique={c.unique_state_count()} {time.perf_counter()-t0:.1f}s", flush=True)
    elif which == "rich4":
        t0 = time.perf_counter()
        c = paxos_model(4).checker().threads(8).timeout(3000).spawn_bfs().join()
        print(f"paxos-4 rich pbfs: unique={c.unique_state_count()} gen={c.state_count()} {time.perf_counter()-t0:.1f}s", flush=True)
    elif which == "vbfs5":
        t0 = time.perf_counter()
        c = (
            TensorModelAdapter(PaxosTensorExhaustive(5))
            .checker()
            .threads(8)
            .timeout(3000)
            .spawn_bfs()
            .join()
        )
        print(f"paxos-5 vbfs: unique={c.unique_state_count()} gen={c.state_count()} {time.perf_counter()-t0:.1f}s", flush=True)
