"""Scratch: bisect the 2pc-10 TPU worker crash trigger (round 5)."""
import sys
import time

from stateright_tpu import TensorModelAdapter
from stateright_tpu.models import TwoPhaseTensor

rm = int(sys.argv[1])
chunk = int(sys.argv[2])
logq = int(sys.argv[3])
logt = int(sys.argv[4])
target = int(sys.argv[5]) if len(sys.argv) > 5 else 2_000_000

tm = TwoPhaseTensor(rm)
opts = dict(chunk_size=chunk, queue_capacity=1 << logq, table_capacity=1 << logt)
t0 = time.perf_counter()
try:
    b = TensorModelAdapter(tm).checker().target_state_count(target)
    c = b.spawn_tpu_bfs(**opts).join()
    print(
        f"OK rm={rm} chunk={chunk} q=2^{logq} t=2^{logt}: "
        f"unique={c.unique_state_count()} gen={c.state_count()} "
        f"{time.perf_counter()-t0:.1f}s",
        flush=True,
    )
except Exception as e:
    print(f"FAIL rm={rm} chunk={chunk} q=2^{logq} t=2^{logt}: {repr(e)[:140]}", flush=True)
    sys.exit(1)
