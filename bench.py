"""Benchmark: batched frontier engine vs the host (Python) reference checker.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: exhaustive check of the two-phase-commit tensor model (the
reference's own benchmark family, bench.sh:27-34 runs `2pc check N`).
The device engine enumerates 2pc-7; the host oracle (the same TensorModel
through the numpy adapter + host BFS, semantics identical to the reference
engine) is timed on 2pc-5 and its states/sec rate is the baseline.
`vs_baseline` is the speedup of the device engine over the host engine in
states/sec.
"""

import json
import sys
import time


def main() -> None:
    import os

    import jax

    # Honor an explicit JAX_PLATFORMS from the caller even when a boot-time
    # sitecustomize pinned a different platform (needed for CPU smoke runs).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_compilation_cache_dir", "/tmp/srtpu_jax_cache")

    from stateright_tpu import TensorModelAdapter
    from stateright_tpu.models import TwoPhaseTensor

    # --- host baseline: 2pc-5 (8,832 states) -----------------------------
    t0 = time.perf_counter()
    host = TensorModelAdapter(TwoPhaseTensor(5)).checker().spawn_bfs().join()
    host_secs = time.perf_counter() - t0
    host_states = host.state_count()
    host_rate = host_states / host_secs

    # --- device engine: 2pc-7 (larger space to amortize dispatch) --------
    tm = TwoPhaseTensor(7)
    engine_opts = dict(
        chunk_size=8192, queue_capacity=1 << 19, table_capacity=1 << 21
    )
    # Warm-up/compile with the SAME TensorModel instance so the cached step
    # function (and XLA executable) is reused by the timed run.
    TensorModelAdapter(tm).checker().target_state_count(1).spawn_tpu_bfs(
        **engine_opts
    ).join()

    t0 = time.perf_counter()
    dev = TensorModelAdapter(tm).checker().spawn_tpu_bfs(**engine_opts).join()
    dev_secs = time.perf_counter() - t0
    dev_states = dev.state_count()
    dev_rate = dev_states / dev_secs

    result = {
        "metric": "2pc-7 exhaustive check, generated states/sec (device engine)",
        "value": round(dev_rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(dev_rate / host_rate, 2),
        "detail": {
            "device_states": dev_states,
            "device_unique": dev.unique_state_count(),
            "device_secs": round(dev_secs, 3),
            "host_states": host_states,
            "host_secs": round(host_secs, 3),
            "host_rate": round(host_rate, 1),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
